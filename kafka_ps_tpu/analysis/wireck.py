"""wireck — wire-schema cross-check (PS204).

Every byte that crosses the wire is written by one ``struct.pack`` /
``pack_into`` / dtype'd array dump and read back by a matching
``unpack`` / ``unpack_from`` / ``np.frombuffer``.  The two sides live
in different files (serde encodes, net/wire decode; agg/relay re-packs
in place) and nothing but convention keeps them agreeing on field
count, byte width and endianness.  This pass extracts both sides from
the wire modules (``runtime/serde.py``, ``runtime/net.py``,
``runtime/wire.py``, ``agg/``) and reports (PS204):

- a pack format no decode side can read: not an exact match, not a
  contiguous slice of a decoder's format, and not decomposable into a
  concatenation of decoder formats (the split-read idiom —
  ``_FRAME`` packs ``<IBq`` whole, the receive buffer reads ``<I``
  then ``<Bq``);
- symmetrically, an unpack format no encoder produces;
- a format string with native endianness (no ``<``/``>``/``=``/``!``
  prefix) — the wire is little-endian by contract, native byte order
  is a portability bug;
- an ``np.frombuffer`` dtype no encoder in the wire group ever
  constructs (decode of bytes nobody writes);
- a serde type-id registry entry (``_TYPE_IDS``) whose name is
  mentioned by only one of ``to_bytes``/``from_bytes`` — a message
  kind that can be encoded but never decoded, or vice versa.

Named ``struct.Struct`` module constants are resolved through
imports (``net._AGG_MEMBER.unpack_from`` in agg/relay.py credits the
unpack side of net.py's constant), so a constant used on both sides
is exact-match covered by construction.  F-string formats
(``f"<q{len(ids)}q"``) normalize their interpolations to a
variable-repeat token that only matches another variable repeat of
the same type code.
"""

from __future__ import annotations

import ast

from .pscheck import Finding
from .program import Program, _dotted

__all__ = ["RULES", "check"]

RULES = {
    "PS204": "wire-schema mismatch: pack/unpack format, frombuffer "
             "dtype, or serde type-id with no agreeing opposite side "
             "(field count / byte width / endianness)",
}

_PACK_ATTRS = frozenset({"pack", "pack_into"})
_UNPACK_ATTRS = frozenset({"unpack", "unpack_from", "iter_unpack"})
_ENDIAN = "<>=!@"
_EXPAND_CAP = 32

_NP_ENCODE_CTORS = frozenset({
    "empty", "zeros", "ones", "asarray", "array", "ascontiguousarray",
    "fromiter", "full",
})

_DTYPE_BASE = {
    "float32": "f4", "float64": "f8", "float16": "f2",
    "int64": "q", "int32": "i4", "int16": "i2", "int8": "i1",
    "uint64": "Q", "uint32": "u4", "uint16": "u2", "uint8": "u1",
}


def _in_group(sf) -> bool:
    from pathlib import Path
    parts = set(Path(sf.path).parts)
    if "agg" in parts:
        return True
    name = Path(sf.path).name
    return (name in ("serde.py", "net.py", "wire.py")
            and "compress" not in parts)


# -- format-string tokenization --------------------------------------------

def _tokenize(fmt: str):
    """'<qI4s' -> ('q','I',('s',4)); returns (endian, tokens) or None."""
    endian = fmt[0] if fmt and fmt[0] in _ENDIAN else None
    body = fmt[1:] if endian else fmt
    toks: list = []
    num = ""
    for ch in body:
        if ch.isdigit():
            num += ch
            continue
        if ch == " ":
            num = ""
            continue
        n = int(num) if num else 1
        num = ""
        if ch in "sx":
            toks.append((ch, n))
        elif n > _EXPAND_CAP:
            toks.append(("*", ch))
        else:
            toks.extend([ch] * n)
    return endian, tuple(toks)


def _tokenize_expr(node):
    """Format expression -> (endian, tokens) for Constant str or
    JoinedStr with {var} repeat counts; None if not statically known."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return _tokenize(node.value)
    if not isinstance(node, ast.JoinedStr):
        return None
    endian = None
    toks: list = []
    pending_var = False
    first = True
    for part in node.values:
        if isinstance(part, ast.Constant):
            text = str(part.value)
            if first and text and text[0] in _ENDIAN:
                endian = text[0]
                text = text[1:]
            if pending_var:
                if not text:
                    return None
                toks.append(("*", text[0]))
                text = text[1:]
                pending_var = False
            got = _tokenize(text)
            if got is None:
                return None
            toks.extend(got[1])
        else:
            if pending_var:
                return None             # {a}{b} — give up
            pending_var = True
        first = False
    if pending_var:
        return None                     # trailing bare interpolation
    return endian, tuple(toks)


def _fmt_str(endian, toks) -> str:
    out = [endian or ""]
    for t in toks:
        if isinstance(t, tuple) and t[0] == "*":
            out.append(f"{{n}}{t[1]}")
        elif isinstance(t, tuple):
            out.append(f"{t[1]}{t[0]}")
        else:
            out.append(t)
    return "".join(out)


def _is_subseq(needle, hay) -> bool:
    n, h = len(needle), len(hay)
    return any(hay[i:i + n] == needle for i in range(h - n + 1))


def _is_concat(target, pieces) -> bool:
    """target decomposable as a concatenation of fmts from `pieces`."""
    ok = {0}
    for i in range(len(target)):
        if i not in ok:
            continue
        for p in pieces:
            if p and target[i:i + len(p)] == p:
                ok.add(i + len(p))
    return len(target) in ok


# -- site collection -------------------------------------------------------

class _Sites:
    def __init__(self):
        self.pack: dict = {}            # tokens -> [(path, line, fmtstr)]
        self.unpack: dict = {}
        self.native: list = []          # (path, line, fmtstr)
        self.dec_dtypes: dict = {}      # base -> [(path, line, label)]
        self.enc_dtypes: set = set()    # bases

    def add_fmt(self, side: str, got, path: str, line: int):
        endian, toks = got
        label = _fmt_str(endian, toks)
        if endian is None or endian == "@":
            self.native.append((path, line, label))
        (self.pack if side == "pack" else self.unpack) \
            .setdefault(toks, []).append((path, line, label))


def _dtype_base(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        s = node.value.lstrip("<>=|")
        return _DTYPE_BASE.get(s, s)
    d = _dotted(node)
    if d.startswith(("np.", "numpy.")):
        return _DTYPE_BASE.get(d.split(".")[-1])
    return None


def _collect(sf, consts, const_uses, sites: _Sites):
    """One walk of a wire-group file: struct format sites, frombuffer
    dtypes, encode-side dtype constructions."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute):
            continue
        recv = _dotted(f.value)
        # struct.pack("<fmt", ...) / struct.unpack_from("<fmt", ...)
        if recv == "struct" and f.attr in (_PACK_ATTRS | _UNPACK_ATTRS):
            if node.args:
                got = _tokenize_expr(node.args[0])
                if got is not None:
                    side = "pack" if f.attr in _PACK_ATTRS else "unpack"
                    sites.add_fmt(side, got, sf.path, node.lineno)
        # NAME.pack(...) / other_mod.NAME.unpack_from(...)
        elif f.attr in (_PACK_ATTRS | _UNPACK_ATTRS):
            key = None
            if isinstance(f.value, ast.Name):
                key = (sf.modname, f.value.id)
            elif (isinstance(f.value, ast.Attribute)
                    and isinstance(f.value.value, ast.Name)):
                local = f.value.value.id
                target = sf.imports.get(local)
                if target:
                    for (mod, cname) in consts:
                        if cname == f.value.attr and (
                                target == mod or target.endswith(mod)
                                or mod.endswith(target)):
                            key = (mod, cname)
                            break
            if key in consts:
                side = "pack" if f.attr in _PACK_ATTRS else "unpack"
                sites.add_fmt(side, consts[key], sf.path, node.lineno)
                const_uses.setdefault(key, set()).add(side)
        # np.frombuffer(buf, dtype=...) — decode side
        if recv in ("np", "numpy") and f.attr == "frombuffer":
            dt = None
            for kw in node.keywords:
                if kw.arg == "dtype":
                    dt = kw.value
            if dt is None and len(node.args) > 1:
                dt = node.args[1]
            base = _dtype_base(dt) if dt is not None else None
            if base:
                sites.dec_dtypes.setdefault(base, []).append(
                    (sf.path, node.lineno, base))
        # encode-side dtype constructions
        elif recv in ("np", "numpy") and f.attr in _NP_ENCODE_CTORS:
            for kw in node.keywords:
                if kw.arg == "dtype":
                    base = _dtype_base(kw.value)
                    if base:
                        sites.enc_dtypes.add(base)
            if len(node.args) > 1:
                base = _dtype_base(node.args[1])
                if base:
                    sites.enc_dtypes.add(base)


def _tid_registry(sf) -> tuple:
    """serde's _TYPE_IDS: (line, names, to_bytes literals,
    from_bytes literals) or None."""
    reg_line, names = None, set()
    for node in sf.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "_TYPE_IDS"
                and isinstance(node.value, ast.Dict)):
            reg_line = node.lineno
            names = {k.value for k in node.value.keys
                     if isinstance(k, ast.Constant)
                     and isinstance(k.value, str)}
    if reg_line is None:
        return None
    enc, dec = set(), set()
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in ("to_bytes", "from_bytes"):
            bucket = enc if node.name == "to_bytes" else dec
            for sub in ast.walk(node):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str):
                    bucket.add(sub.value)
    return reg_line, names, enc, dec


# -- the pass --------------------------------------------------------------

def check(prog: Program) -> list[Finding]:
    group = [sf for sf in prog.files if _in_group(sf)]
    if not group:
        return []

    consts: dict = {}                   # (modname, NAME) -> (endian, toks)
    for sf in group:
        for node in sf.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and _dotted(node.value.func) in ("struct.Struct",
                                                     "Struct")
                    and node.value.args):
                got = _tokenize_expr(node.value.args[0])
                if got is not None:
                    consts[(sf.modname, node.targets[0].id)] = got

    sites = _Sites()
    const_uses: dict = {}
    for sf in group:
        _collect(sf, consts, const_uses, sites)

    findings: list[Finding] = []

    for path, line, label in sites.native:
        findings.append(Finding(
            "PS204", path, line,
            f"struct format {label!r} has native endianness — the wire "
            "contract is explicit little-endian; prefix with '<'"))

    pack_fmts = set(sites.pack)
    unpack_fmts = set(sites.unpack)
    for toks in sorted(sites.pack, key=str):
        if toks in unpack_fmts \
                or any(_is_subseq(toks, u) for u in unpack_fmts) \
                or _is_concat(toks, unpack_fmts):
            continue
        path, line, label = sites.pack[toks][0]
        findings.append(Finding(
            "PS204", path, line,
            f"pack format {label!r} has no decode side in the wire "
            "modules (not an unpack format, slice of one, or "
            "concatenation of them) — one-sided schema"))
    for toks in sorted(sites.unpack, key=str):
        if toks in pack_fmts \
                or any(_is_subseq(toks, p) for p in pack_fmts) \
                or _is_concat(toks, pack_fmts):
            continue
        path, line, label = sites.unpack[toks][0]
        findings.append(Finding(
            "PS204", path, line,
            f"unpack format {label!r} has no encode side in the wire "
            "modules — decoding bytes nobody writes (or a schema "
            "drifted on one side only)"))

    for base in sorted(sites.dec_dtypes):
        if base in sites.enc_dtypes:
            continue
        path, line, _ = sites.dec_dtypes[base][0]
        findings.append(Finding(
            "PS204", path, line,
            f"np.frombuffer dtype {base!r} has no encode-side array "
            "construction in the wire modules — one-sided schema"))

    for sf in group:
        reg = _tid_registry(sf)
        if reg is None:
            continue
        reg_line, names, enc, dec = reg
        for name in sorted(names):
            missing = [side for side, seen in
                       (("to_bytes", enc), ("from_bytes", dec))
                       if name not in seen]
            if missing:
                findings.append(Finding(
                    "PS204", sf.path, reg_line,
                    f"serde type id {name!r} is never mentioned by "
                    f"{' or '.join(missing)} — a message kind that "
                    "cannot round-trip"))

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
