"""pytest plugin: record lock-acquisition order across the whole test
session and fail it if the migrated production locks ever form an
inconsistent (cyclic) order — a potential deadlock.

Registered from tests/conftest.py via ``pytest_plugins``.  Disable for
a one-off run with ``LOCKGRAPH=0 pytest ...``.
"""

from __future__ import annotations

import os

from kafka_ps_tpu.analysis import lockgraph

# session exit code when the acquisition graph has a cycle (distinct
# from test failures so CI logs point straight at the detector)
EXIT_LOCK_ORDER_CYCLE = 7


def _enabled(config) -> bool:
    return os.environ.get("LOCKGRAPH", "1") != "0"


def pytest_configure(config):
    if _enabled(config):
        lockgraph.enable()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    g = lockgraph.current()
    if g is None:
        return
    tr = terminalreporter
    cycles = g.cycles()
    tr.ensure_newline()
    if not cycles:
        tr.line(f"lockgraph: {g.summary()}, no ordering cycles", green=True)
        return
    tr.section("lock-order cycles (potential deadlocks)", sep="=", red=True)
    for cyc in cycles:
        names = " -> ".join([e.src for e in cyc] + [cyc[0].src])
        tr.line(f"cycle: {names}", red=True)
        for e in cyc:
            tr.line(f"  {e.src} -> {e.dst}  first seen at {e.site} "
                    f"[thread {e.thread}]")
    tr.line(f"lockgraph: {g.summary()}, {len(cycles)} cycle(s)", red=True)


def pytest_sessionfinish(session, exitstatus):
    g = lockgraph.current()
    if g is not None and g.cycles():
        session.exitstatus = EXIT_LOCK_ORDER_CYCLE


def pytest_unconfigure(config):
    # after the terminal summary has printed (unconfigure is the last
    # hook) — matters for in-process pytest.main() runs
    lockgraph.disable()
