"""Static + runtime invariant checking for kafka_ps_tpu.

- ``pscheck``   — per-file AST analyzer (rules PS100-PS106), stdlib-only.
- ``psverify``  — the combined driver: pscheck plus the whole-program
  passes, behind ``python -m kafka_ps_tpu.analysis kafka_ps_tpu/
  [--json] [--lock-coverage edges.json]``:

  * ``threadck`` — thread-ownership/race analysis (PS201/PS202):
    lockset intersection over every shared ``self.<attr>`` access
    site, with ``# guarded-by:`` / ``# owned-by:`` annotations.
  * ``lockflow`` — static held→acquired lock graph, Tarjan cycles
    (PS203), and the static-vs-runtime coverage diff.
  * ``wireck``  — encode/decode wire-schema cross-check (PS204).
  * PS107 — useless-suppression audit over the whole inventory.

- ``program``   — the shared whole-program AST/symbol model the three
  passes consume.
- ``lockgraph`` — runtime lock-acquisition-order recorder (OrderedLock /
  OrderedCondition) with deadlock-cycle detection, reported at pytest
  session end by ``kafka_ps_tpu.analysis.pytest_plugin``.

See docs/ANALYSIS.md for the rule catalog, suppression syntax and
annotation grammar.

This package must stay importable without jax: the CLI runs in the
tier-1 ``--analyze`` leg before any accelerator runtime is touched.
"""

from kafka_ps_tpu.analysis import lockgraph, pscheck  # noqa: F401

__all__ = ["lockgraph", "pscheck", "psverify", "program",
           "threadck", "lockflow", "wireck"]


def __getattr__(name):
    # the whole-program passes are imported lazily so that importing
    # the package (e.g. for OrderedLock) stays as cheap as before
    if name in __all__:
        import importlib
        return importlib.import_module(f"{__name__}.{name}")
    raise AttributeError(name)
