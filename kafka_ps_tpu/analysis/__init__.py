"""Static + runtime invariant checking for kafka_ps_tpu.

- ``pscheck``   — AST analyzer (rules PS100-PS105), stdlib-only;
  CLI: ``python -m kafka_ps_tpu.analysis kafka_ps_tpu/ [--json]``.
- ``lockgraph`` — runtime lock-acquisition-order recorder (OrderedLock /
  OrderedCondition) with deadlock-cycle detection, reported at pytest
  session end by ``kafka_ps_tpu.analysis.pytest_plugin``.

See docs/ANALYSIS.md for the rule catalog and suppression syntax.

This package must stay importable without jax: the CLI runs in the
tier-1 ``--analyze`` leg before any accelerator runtime is touched.
"""

from kafka_ps_tpu.analysis import lockgraph, pscheck  # noqa: F401

__all__ = ["lockgraph", "pscheck"]
