"""kafka_ps_tpu — a TPU-native streaming parameter-server framework.

A from-scratch JAX/XLA re-design of the capability set of
Parameter-Server-Architecture-On-Apache-Kafka (HPI research prototype,
reference at /root/reference): streaming ingestion with rate pacing,
per-worker dynamic sliding data buffers, k-step local training with
delta exchange, central aggregation under three consistency models
(sequential/BSP, bounded-delay/SSP, eventual/ASP) gated by per-worker
vector clocks, and continuous test-set evaluation with CSV metric logs.

The Kafka fabric of the reference (three topics: INPUT_DATA,
WEIGHTS_TOPIC, GRADIENTS_TOPIC — reference BaseKafkaApp.java:27-33) is
replaced by TPU-native transports: `shard_map` + `psum` collectives over
an ICI device mesh for the synchronous path, and host-orchestrated
async dispatch with per-device `device_put` for the stale paths.

Package layout:
  models/    LR model family, metrics (the reference's ml/ package)
  ops/       XLA/Pallas compute kernels (k-step local SGD)
  parallel/  mesh, collectives, consistency gating, vector-clock tracker
  data/      paced stream producer + dynamic sliding buffers (producer/)
  runtime/   server/worker processors, in-process fabric, apps (processors/, apps/)
  utils/     config, CSV logging, checkpointing (improvement over reference)
  cli/       runner entry points mirroring ServerAppRunner/WorkerAppRunner
"""

__version__ = "0.1.0"
