"""Range-sharded multi-server runtime (docs/SHARDING.md).

The reference carries a KeyRange on every message but always runs ONE
server over the full range — the latent hook for partitioned parameters
(messages/KeyRange.java; Li et al., OSDI'14 §4.3 key-range server
groups).  This module promotes the single-process shard_map prototype
(parallel/range_sharded.py) into a real runtime:

  * `ShardPlan` — N contiguous, disjoint key ranges covering the flat
    parameter vector exactly (the LAST shard is clipped, so unlike the
    shard_map prototype no pad keys ever exist on the wire);
  * `ShardRouter` — worker-side delta splitter: one outgoing gradient
    becomes N slice messages, each pushed to the owning shard.  Dense
    deltas split into dense slices; topk-compressed deltas split into
    `SparseDeltaMessage`s routed by index range, so a sparse delta
    touches few shards (empty slices are still sent — every shard's
    consistency gate needs one message per (worker, clock));
  * `WeightsAssembler` — worker-side reassembly: per-shard weights
    slices at a common clock synthesize ONE full-range WeightsMessage.
    Slices at clocks the worker already trained on are redelivery
    (shard crash recovery) — the router resends its cached gradient
    slice to just that shard instead of re-running the step, which is
    what keeps per-shard durable-log recovery bitwise;
  * `ShardedServerGroup` — N ServerNodes, each owning one range slice
    of theta with its own per-worker vector clocks and its own gate
    (all three consistency models evaluate per shard).  N=1 constructs
    today's single full-range server through the SAME code path —
    bitwise-identical theta and CSV logs by construction.

Replay-critical determinism: the split/assemble order is fixed by
(shard id, worker id, clock) alone — pscheck enforces PS104 on this
module (no wall-clock, no RNG, no set iteration in the routing paths).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable

import numpy as np

from kafka_ps_tpu.compress.wire import CODEC_TOPK
from kafka_ps_tpu.runtime import fabric as fabric_mod
from kafka_ps_tpu.runtime.messages import (GradientMessage, KeyRange,
                                           SparseDeltaMessage,
                                           WeightsMessage)
from kafka_ps_tpu.runtime.server import ServerNode
from kafka_ps_tpu.telemetry.flight import FLIGHT


class ShardPlan:
    """Static assignment of the flat key space [0, num_params) to
    `num_shards` contiguous half-open ranges.

    span = ceil(num_params / num_shards); shard i owns
    [i*span, min((i+1)*span, num_params)).  Every key has exactly one
    owner (`shard_of`), the ranges concatenate back to the full vector
    in shard-id order, and the last shard is CLIPPED — the runtime has
    no pad region (contrast parallel/range_sharded.py, whose shard_map
    prototype pads; see its pad-hygiene asserts)."""

    def __init__(self, num_params: int, num_shards: int):
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        if num_shards > num_params:
            raise ValueError(
                f"num_shards {num_shards} > num_params {num_params}")
        self.num_params = num_params
        self.num_shards = num_shards
        self.span = -(-num_params // num_shards)          # ceil division
        self.ranges: tuple[KeyRange, ...] = tuple(
            KeyRange(i * self.span, min((i + 1) * self.span, num_params))
            for i in range(num_shards))

    def shard_of(self, key: int) -> int:
        if not 0 <= key < self.num_params:
            raise ValueError(f"key {key} outside [0, {self.num_params})")
        return key // self.span

    def split_dense(self, msg: GradientMessage) -> list[GradientMessage]:
        """One dense slice per shard (full-range input only).  Slice i
        carries the owning shard's KeyRange and the matching contiguous
        values view; clock/worker/trace ride along unchanged."""
        values = np.asarray(msg.values)
        out = []
        for rng in self.ranges:
            s = GradientMessage(vector_clock=msg.vector_clock,
                                key_range=rng,
                                values=values[rng.start:rng.end],
                                worker_id=msg.worker_id)
            _copy_trace(msg, s)
            out.append(s)
        return out

    def split_sparse(self, msg: GradientMessage) -> list[SparseDeltaMessage]:
        """Route a topk-encoded delta by index range: shard i receives
        only the (index, value) pairs that land in its range, as LOCAL
        offsets.  Shards outside the survivor set get an EMPTY slice —
        their gate still needs the (worker, clock) message, but the
        apply is skipped (the work-reduction that makes sharded topk
        scale on one host, bench.py sharding_ab)."""
        idx, vals = msg.encoded.parts
        idx = np.asarray(idx, dtype=np.int32)
        vals = np.asarray(vals, dtype=np.float32)
        order = np.argsort(idx, kind="stable")      # canonical wire form
        idx, vals = idx[order], vals[order]
        # one pass: searchsorted against the shard boundaries
        bounds = [r.start for r in self.ranges] + [self.num_params]
        cuts = np.searchsorted(idx, bounds)
        out = []
        for i, rng in enumerate(self.ranges):
            lo, hi = cuts[i], cuts[i + 1]
            s = SparseDeltaMessage(
                vector_clock=msg.vector_clock, key_range=rng,
                indices=idx[lo:hi] - rng.start, values=vals[lo:hi],
                worker_id=msg.worker_id)
            _copy_trace(msg, s)
            out.append(s)
        return out


def _copy_trace(src, dst) -> None:
    """Thread the delta.wire flow id onto a routed slice: each slice
    keeps the parent delta's trace context, so Perfetto renders one
    arrow chain per delta slice (send → wire → shard apply)."""
    fid = getattr(src, "trace", None)
    if fid is not None:
        object.__setattr__(dst, "trace", fid)


class ShardRouter:
    """Worker-side delta splitter + redelivery cache (one per worker).

    `send(shard_id, slice_msg)` is the transport: in-process it
    enqueues to (GRADIENTS_TOPIC, shard_id) on the shared fabric;
    socket mode sends on the shard's bridge.  The cache keeps the last
    `cache_clocks` clocks' slices so a recovering shard that redelivers
    an old weights slice gets the BITWISE-identical gradient slice
    resent (never recomputed — recomputation after the buffer moved on
    would diverge the shards)."""

    def __init__(self, plan: ShardPlan,
                 send: Callable[[int, object], None],
                 cache_clocks: int = 64):
        self.plan = plan
        self._send = send
        self._cache: OrderedDict[int, list] = OrderedDict()
        self._cache_clocks = cache_clocks

    def route(self, msg: GradientMessage) -> None:
        r = msg.key_range
        if r.start != 0 or r.end != self.plan.num_params:
            raise ValueError(
                f"router expects full-range deltas, got [{r.start}, {r.end})")
        enc = getattr(msg, "encoded", None)
        if enc is not None and enc.codec_id == CODEC_TOPK:
            slices = self.plan.split_sparse(msg)
        else:
            slices = self.plan.split_dense(msg)
        self._cache[msg.vector_clock] = slices
        while len(self._cache) > self._cache_clocks:
            self._cache.popitem(last=False)
        for shard_id, s in enumerate(slices):
            self._send(shard_id, s)

    def resend(self, shard_id: int, clock: int) -> bool:
        """Redeliver every cached slice for `shard_id` at clocks
        >= `clock` (ascending); True when anything was resent.  A
        recovering shard that redelivers weights at clock c is behind
        by every delta slice from c onward — resending the whole
        cached tail lets it catch up to the surviving shards in one
        pass, and its (worker, clock) duplicate filter drops whatever
        originally got through, so resending is always safe."""
        sent = False
        count = 0
        for c in sorted(self._cache):
            if c >= clock:
                self._send(shard_id, self._cache[c][shard_id])
                sent = True
                count += 1
        if FLIGHT.enabled:
            # host ints only, and the recorder stamps time internally —
            # the routing path itself stays wall-clock-free (PS104)
            FLIGHT.record("router.resend", shard=shard_id,
                          from_clock=clock, count=count)
        return sent


class WeightsAssembler:
    """Worker-side reassembly of per-shard weights slices.

    A worker's weight pull completes when every shard has released its
    slice at a COMMON clock; the assembled full-range WeightsMessage is
    then delivered exactly once per clock (deliver callback).  Stale
    slices (clock <= last delivered) are shard-recovery redelivery:
    `resend(shard, worker, clock)` asks the worker's router to repush
    its cached gradient slice so the lagging shard catches up."""

    def __init__(self, plan: ShardPlan,
                 deliver: Callable[[int, WeightsMessage], None],
                 resend: Callable[[int, int, int], bool] | None = None):
        self.plan = plan
        self._deliver = deliver
        self._resend = resend
        self._slices: dict[int, dict[int, WeightsMessage]] = {}
        self._delivered: dict[int, int] = {}

    def offer(self, shard_id: int, worker: int,
              msg: WeightsMessage) -> bool:
        """Feed one shard's slice; returns True when this completed an
        assembly and the full message was delivered."""
        if FLIGHT.enabled:
            # the per-shard weights ack trail postmortem's "last
            # (worker, clock) the dead shard served" is computed from
            FLIGHT.record("shard.weights", shard=shard_id, worker=worker,
                          clock=msg.vector_clock)
        last = self._delivered.get(worker, -1)
        if msg.vector_clock <= last:
            if self._resend is not None:
                self._resend(shard_id, worker, msg.vector_clock)
            return False
        held = self._slices.setdefault(worker, {})
        held[shard_id] = msg            # latest slice per shard wins
        if len(held) < self.plan.num_shards:
            return False
        clocks = [held[s].vector_clock
                  for s in range(self.plan.num_shards)]
        if min(clocks) != max(clocks):
            return False                # shards not yet at a common clock
        values = np.concatenate([
            # pscheck: disable=PS102 (host-side assembly; slices are host arrays)
            np.asarray(held[s].values)
            for s in range(self.plan.num_shards)])
        full = WeightsMessage(
            vector_clock=clocks[0],
            key_range=KeyRange(0, self.plan.num_params),
            values=values)
        _copy_trace(held[0], full)
        self._slices[worker] = {}
        self._delivered[worker] = clocks[0]
        self._deliver(worker, full)
        return True

    def drop(self, worker: int) -> None:
        """Forget partial state for a worker (eviction purge path)."""
        self._slices.pop(worker, None)


class _ShardWeightsFabric(fabric_mod.Fabric):
    """Send-side facade handed to each in-process shard ServerNode:
    weights slices feed the shared assembler (which synthesizes the
    full-range message into the real fabric), gang notices pass through
    from shard 0 only (all shards compute identical release sets in
    lockstep — N notices for one release moment would be noise), and
    everything else forwards to the inner fabric."""

    def __init__(self, inner: fabric_mod.Fabric, shard_id: int,
                 assembler: WeightsAssembler, forward_gang: bool):
        super().__init__()
        self._inner = inner
        self._shard_id = shard_id
        self._assembler = assembler
        self._forward_gang = forward_gang

    def send(self, topic: str, key: int, message) -> None:
        if topic == fabric_mod.WEIGHTS_TOPIC:
            self._assembler.offer(self._shard_id, key, message)
            return
        self._inner.send(topic, key, message)

    def send_transient(self, topic: str, key: int, message) -> None:
        if topic == fabric_mod.GANG_TOPIC and not self._forward_gang:
            return
        self._inner.send_transient(topic, key, message)

    def pending(self, topic: str, key: int = 0) -> int:
        if topic == fabric_mod.WEIGHTS_TOPIC:
            return 0        # slices never queue; assembly is immediate
        return self._inner.pending(topic, key)

    def purge(self, topic: str, key: int, pred) -> int:
        if topic == fabric_mod.WEIGHTS_TOPIC:
            self._assembler.drop(key)
            return 0
        return self._inner.purge(topic, key, pred)


class ShardedServerGroup:
    """N range-sharded ServerNodes behind one group facade.

    N=1 degenerates to today's single full-range server — same class,
    same constructor arguments, same fabric keys — so the unsharded
    bitwise contract (theta AND CSV rows, all three consistency models)
    holds by construction, pinned by tests/test_sharding.py.

    N>1: shard i owns plan.ranges[i], polls (GRADIENTS_TOPIC, i), and
    sends weights slices through the assembler.  Cross-shard consistent
    snapshots and the group-level eval both happen at the COMMON CLOCK
    FRONTIER (the min across shards of the per-shard stable clock):
    a cut is the vector of per-shard (theta_slice, clock) pairs taken
    when every shard has reached the frontier — concatenation is the
    servable/checkpointable full vector (docs/SHARDING.md)."""

    def __init__(self, cfg, fabric: fabric_mod.Fabric, num_shards: int,
                 test_x=None, test_y=None, log=None,
                 tracer=None, telemetry=None):
        from kafka_ps_tpu.models.task import get_task
        self.cfg = cfg
        self.fabric = fabric
        self.task = get_task(cfg.task, cfg.model)
        self.plan = ShardPlan(self.task.num_params, num_shards)
        self.test_x = test_x
        self.test_y = test_y
        self.log = log or (lambda line: None)
        self.routers: dict[int, ShardRouter] = {}
        self._eval_clock = -1
        self._cut_publisher = None
        self.eval_engine = None   # async eval plane (enable_async_eval)
        if num_shards == 1:
            node = ServerNode(cfg, fabric, test_x, test_y, log,
                              tracer=tracer, telemetry=telemetry)
            self.shards = [node]
            self.single: ServerNode | None = node
            self.assembler = None
            return
        self.single = None
        self.assembler = WeightsAssembler(
            self.plan,
            deliver=lambda w, m: fabric.send(
                fabric_mod.WEIGHTS_TOPIC, w, m),
            resend=self._resend_slice)
        self.shards = [
            ServerNode(cfg, _ShardWeightsFabric(fabric, i, self.assembler,
                                                forward_gang=(i == 0)),
                       None, None, None, tracer=tracer, telemetry=telemetry,
                       key_range=rng, shard_id=i, num_shards=num_shards,
                       grad_key=i)
            for i, rng in enumerate(self.plan.ranges)]

    # -- worker wiring -----------------------------------------------------

    def attach_workers(self, workers) -> None:
        """Give each worker a ShardRouter over this group's fabric keys.
        N=1 leaves workers untouched (the unsharded send path IS the
        N=1 protocol)."""
        if self.plan.num_shards == 1:
            return
        for w in workers:
            router = ShardRouter(
                self.plan,
                send=lambda sid, m: self.fabric.send(
                    fabric_mod.GRADIENTS_TOPIC, sid, m))
            w.shard_router = router
            self.routers[w.worker_id] = router

    def _resend_slice(self, shard_id: int, worker: int,
                      clock: int) -> bool:
        router = self.routers.get(worker)
        return router.resend(shard_id, clock) if router else False

    # -- group state -------------------------------------------------------

    @property
    def iterations(self) -> int:
        """Applied-message budget for drive loops: every (worker, clock)
        delta reaches EVERY shard (empty slices included), so the
        slowest shard's count is the number of fully-applied deltas."""
        return min(s.iterations for s in self.shards)

    def frontier_clock(self) -> int:
        """The common clock frontier: min across shards of the per-shard
        stable clock (serving_clock).  Every shard has incorporated all
        rounds below it — the cross-shard mirror of the single-server
        stable clock."""
        return min(s.serving_clock() for s in self.shards)

    def assembled_theta(self) -> np.ndarray:
        """Concatenate the per-shard theta slices in shard-id order.
        Host-side copy; the per-shard slices stay untouched."""
        return np.concatenate(
            [np.asarray(s.theta) for s in self.shards])

    def snapshot_cut(self) -> list[tuple]:
        """The consistent-cut vector: per-shard (theta reader, clock)
        in shard-id order, read at one drive-loop quiescent point.
        The slice is LAZY (a zero-arg callable): FrontierCutPublisher
        materializes only when the frontier actually advanced — with
        tiered residency attached (docs/TIERING.md), reading a slice
        assembles pages and faults cold ones, so the cuts that publish
        nothing must not touch the stores."""
        return [((lambda s=s: np.asarray(s.theta)), s.serving_clock())
                for s in self.shards]

    def attach_param_stores(self, make_store) -> None:
        """Tiered residency per shard (kafka_ps_tpu/store/): each shard
        gets its own TieredParamStore over its range — built by
        `make_store(shard)` so the caller decides per-shard budgets and
        cold partitions (residency is a per-process resource; the CLI
        splits a process's byte caps evenly across its in-process
        shards, docs/TIERING.md)."""
        for s in self.shards:
            s.attach_param_store(make_store(s))

    # -- serving / eval at the frontier ------------------------------------

    def attach_serving(self, registry) -> None:
        """Cross-shard serving: snapshots publish ASSEMBLED theta at the
        clock frontier (serving/snapshot.FrontierCutPublisher), never a
        torn mix of shard states.  N=1 attaches the registry directly —
        per-release publication, exactly the unsharded plane."""
        if self.single is not None:
            self.single.serving = registry
            return
        from kafka_ps_tpu.serving.snapshot import FrontierCutPublisher
        self._cut_publisher = FrontierCutPublisher(registry)

    def publish_frontier(self) -> None:
        """Publish a consistent cut if the frontier advanced.  Called by
        the drive loop between processing rounds (quiescent point: no
        shard is mid-apply)."""
        if self._cut_publisher is None:
            return
        self._cut_publisher.maybe_publish(self.snapshot_cut())

    def enable_async_eval(self, telemetry=None, tracer=None):
        """Attach the async coalescing eval plane (evaluation/engine.py).
        N=1 arms the inner ServerNode — exactly the unsharded lever.
        N>1 arms the GROUP's frontier eval: maybe_eval submits the
        assembled theta (already a fresh host copy — immutable by
        construction) instead of evaluating inline; the engine's thread
        emits the same CSV rows in frontier-clock order.  Idempotent;
        returns the engine (None without a test set)."""
        if self.eval_engine is not None:
            return self.eval_engine
        if self.single is not None:
            if self.single.test_x is None:
                return None
            from kafka_ps_tpu.evaluation.engine import EvalEngine
            self.eval_engine = self.single.attach_eval_engine(EvalEngine(
                self.single.task, self.single.test_x, self.single.test_y,
                self.single._emit_eval,
                telemetry=telemetry, tracer=tracer))
            return self.eval_engine
        if self.test_x is None:
            return None
        from kafka_ps_tpu.evaluation.engine import EvalEngine
        self.eval_engine = EvalEngine(
            self.task, self.test_x, self.test_y, self._emit_eval,
            telemetry=telemetry, tracer=tracer)
        return self.eval_engine

    def close_eval(self) -> None:
        """Drain pending evals and join the engine thread."""
        if self.eval_engine is not None:
            self.eval_engine.close()

    def _emit_eval(self, clock: int, m) -> None:
        """Group eval row writer — same schema as ServerNode._emit_eval
        (timestamp;partition;vectorClock;loss;fMeasure;accuracy); shared
        by the inline frontier eval and the async engine's thread."""
        import time
        from kafka_ps_tpu.utils import asynclog
        asynclog.submit_or_write(
            self.log,
            # pscheck: disable=PS104 (CSV wall-clock column, not replay state)
            f"{int(time.time() * 1000)};-1;{clock};"
            "{};{};{}", m.loss, m.f1, m.accuracy)

    def maybe_eval(self) -> None:
        """Group-level online eval: when the WORKER-0 frontier (min
        across shards of worker 0's clock) crosses the eval cadence,
        evaluate the assembled theta and emit the server CSV row —
        same schema as the single server.  Documented divergence at
        N>1: the eval observes the assembled theta at the frontier
        moment, not each shard's mid-round prefix (docs/SHARDING.md)."""
        if self.single is not None or self.test_x is None:
            return
        frontier0 = min(s.tracker.tracker[0].vector_clock
                        for s in self.shards)
        latest = frontier0 - (frontier0 % self.cfg.eval_every)
        if latest <= self._eval_clock or latest < 0:
            return
        self._eval_clock = latest
        if self.eval_engine is not None:
            # assembled_theta() is a fresh np.concatenate per call —
            # the engine's queue owns this copy outright
            self.eval_engine.submit(self.assembled_theta(), latest)
            return
        import jax.numpy as jnp
        m = self.task.evaluate(jnp.asarray(self.assembled_theta()),
                               jnp.asarray(self.test_x),
                               jnp.asarray(self.test_y))
        self._emit_eval(latest, m)

    # -- checkpointing -----------------------------------------------------

    def set_checkpoint(self, path: str, every: int = 50) -> None:
        """One checkpoint file per shard (utils/checkpoint.py
        shard_state_path): shard i saves its own slice + tracker +
        committed log offsets, independently recoverable — the
        per-shard durable-log partition's commit point."""
        from kafka_ps_tpu.utils import checkpoint as ckpt
        for i, s in enumerate(self.shards):
            s.checkpoint_path = ckpt.shard_state_path(
                path, i, self.plan.num_shards)
            s.checkpoint_every = every

    def maybe_restore(self) -> bool:
        from kafka_ps_tpu.utils import checkpoint as ckpt
        restored = False
        for s in self.shards:
            if s.checkpoint_path:
                restored |= ckpt.maybe_restore(s.checkpoint_path, s)
        return restored

    def save_checkpoint_now(self) -> None:
        for s in self.shards:
            s.save_checkpoint_now()

    # -- drive loop --------------------------------------------------------

    def start(self) -> None:
        for s in self.shards:
            s.start_training_loop()
        self.publish_frontier()

    def run_serial(self, workers, max_server_iterations: int,
                   pump=None) -> None:
        """Deterministic serial scheduler for the sharded group —
        mirrors app.run_serial's alternation (weights delivery, then
        gradient drain in shard-id order), without the gang claim (the
        gang path coalesces per shard server-side via process_batch;
        see run_serial_gang-less note in docs/SHARDING.md)."""
        self.attach_workers(workers)
        self.start()
        stalled = 0
        while self.iterations < max_server_iterations:
            progressed = False
            for worker in workers:
                msg = self.fabric.poll(fabric_mod.WEIGHTS_TOPIC,
                                       worker.worker_id)
                if msg is not None:
                    worker.on_weights(msg)
                    progressed = True
            for sid, shard in enumerate(self.shards):
                key = 0 if self.single is not None else sid
                while shard.iterations < max_server_iterations:
                    g = self.fabric.poll(fabric_mod.GRADIENTS_TOPIC, key)
                    if g is None:
                        break
                    shard.process(g)
                    progressed = True
            self.maybe_eval()
            self.publish_frontier()
            if pump is not None:
                pump()
            stalled = 0 if progressed else stalled + 1
            if stalled > (1000 if pump is not None else 0):
                raise RuntimeError(
                    "deadlock: no deliverable messages in sharded group")
