"""Wire & state schema — the reference's messages/ package, TPU-first.

The reference addresses parameters as a JSON map of integer key → float
(BaseMessage.java:29-32, SerializableHashMap.java:7-8).  Here `values`
is a **dense numpy slab over a contiguous KeyRange** — the PS key-value
contract survives (keys are positions in the flat 6150-key parameter
vector, range-sharded servers stay expressible), but a message body is
one contiguous buffer that `device_put` ships without any host-side
marshalling.

KeyRange is half-open [start, end) — the reference mixes inclusive and
exclusive conventions (server end = max+1, ServerProcessor.java:198-208;
worker end = max, WorkerTrainingProcessor.java:105-109 — the §3.5.1
off-by-one that drops the last intercept).  We standardise on half-open
everywhere and do NOT reproduce that quirk.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class KeyRange:
    """Half-open [start, end) span of flat parameter keys
    (messages/KeyRange.java, made exclusive)."""

    start: int
    end: int

    def __post_init__(self):
        if self.start < 0 or self.end < self.start:
            raise ValueError(f"invalid KeyRange [{self.start}, {self.end})")

    def contains(self, key: int) -> bool:
        return self.start <= key < self.end

    def __len__(self) -> int:
        return self.end - self.start


@dataclasses.dataclass(frozen=True)
class EncodedValues:
    """Lossy-codec encoding of a message's values (compress/codecs.py):
    codec id + parameter and the device-encoded parts exactly as the
    sender produced them.  Serde serializes these parts verbatim rather
    than re-encoding `values` — int8 quantization is not idempotent over
    its own decoded output, and re-encoding would desync the sender's
    error-feedback residual from what actually crossed the wire."""

    codec_id: int
    param: float
    parts: tuple


@dataclasses.dataclass(frozen=True)
class BaseMessage:
    """vector clock + key range + dense values (BaseMessage.java:17-32).

    `values` is ALWAYS the full-precision view every consumer computes
    with (for a compressed message: the decoded floats, identical on
    both sides of the socket).  `encoded` is transport metadata only —
    present when a codec produced this message, None otherwise."""

    vector_clock: int
    key_range: KeyRange
    values: np.ndarray
    encoded: EncodedValues | None = None

    def __post_init__(self):
        if len(self.values) != len(self.key_range):
            raise ValueError(
                f"values length {len(self.values)} != key range "
                f"[{self.key_range.start}, {self.key_range.end})")

    def get_value(self, key: int) -> float | None:
        """Point lookup kept for KeyRange-API parity (BaseMessage.java:51-57)."""
        if not self.key_range.contains(key):
            return None
        return float(self.values[key - self.key_range.start])


@dataclasses.dataclass(frozen=True)
class WeightsMessage(BaseMessage):
    """server → worker (WeightsMessage.java)."""


@dataclasses.dataclass(frozen=True)
class GradientMessage(BaseMessage):
    """worker → server; carries the sending worker's id
    (GradientMessage.java:13-16)."""

    worker_id: int = 0


@dataclasses.dataclass(frozen=True)
class SparseDeltaMessage:
    """worker → server shard: a sparsified delta slice (range sharding,
    docs/SHARDING.md).  NOT a BaseMessage — `values` here is the sparse
    value list, not a dense slab over the range, so the dense length
    invariant does not apply.

    `indices` are LOCAL offsets within `key_range` (global key =
    key_range.start + index), sorted ascending, unique.  An EMPTY slice
    (no surviving top-k coordinates in this shard's range) is still a
    protocol message: the shard's consistency gate must see one gradient
    per (worker, clock) to advance its vector clocks — the apply is
    skipped, the bookkeeping is not."""

    vector_clock: int
    key_range: KeyRange
    indices: np.ndarray          # int32 local offsets, may be empty
    values: np.ndarray           # float32, same length as indices
    worker_id: int = 0
    encoded: EncodedValues | None = None   # API parity with BaseMessage

    def __post_init__(self):
        if len(self.indices) != len(self.values):
            raise ValueError(
                f"indices length {len(self.indices)} != values length "
                f"{len(self.values)}")


@dataclasses.dataclass(frozen=True)
class CompositeDelta:
    """aggregator → server: one pre-reduced message per (host, clock)
    carrying the deltas of every co-located worker behind that
    aggregator (kafka_ps_tpu/agg/, docs/AGGREGATION.md).

    `members` is the vector-clock map: (worker_id, vector_clock) pairs,
    sorted ascending and unique — the server gate advances each member
    worker's clock from this list exactly as if the deltas had arrived
    individually.  Two shapes share the type:

      * stacked (summed=False, the default): `deltas` carries one
        GradientMessage per member, zipped with `members`.  The server
        expands and applies them per-member in member order, so the
        result is BITWISE-identical to the direct (no-aggregator) path
        for all three consistency models — float addition is not
        associative, so exactness requires preserving the per-member
        apply sequence, not just the sum.
      * summed (summed=True): `deltas` is ONE GradientMessage holding
        the pre-reduced sum over all members (exact by linearity for
        BSP, where every member shares one clock).  One server apply
        per host per clock — the throughput shape — documented as
        numerically exact but not bitwise-pinned to the direct path.

    Compressed transport: each member GradientMessage may carry
    `encoded` parts produced by the AGGREGATOR's per-member
    error-feedback residual (compress/feedback.py) — the aggregator
    owns EF for its workers, replaying the exact encode sequence the
    worker itself would have produced on the direct path."""

    agg_id: int
    members: tuple[tuple[int, int], ...]
    deltas: tuple[GradientMessage, ...]
    summed: bool = False

    def __post_init__(self):
        if not self.members:
            raise ValueError("CompositeDelta needs at least one member")
        if list(self.members) != sorted(set(self.members)):
            raise ValueError("CompositeDelta members must be sorted "
                             "and unique")
        if self.summed:
            if len(self.deltas) != 1:
                raise ValueError("summed CompositeDelta carries exactly "
                                 "one pre-reduced delta")
        else:
            if len(self.deltas) != len(self.members):
                raise ValueError(
                    f"stacked CompositeDelta carries one delta per "
                    f"member: {len(self.deltas)} != {len(self.members)}")
            for (w, c), d in zip(self.members, self.deltas):
                if (d.worker_id, d.vector_clock) != (w, c):
                    raise ValueError(
                        f"member ({w}, {c}) does not match its delta "
                        f"({d.worker_id}, {d.vector_clock})")

    @property
    def fan_in(self) -> int:
        return len(self.members)


@dataclasses.dataclass(frozen=True)
class GangNotice:
    """Server → drive loop: the gate just released `members` (worker id,
    clock) at the same moment, and their per-worker WeightsMessages are
    in the fabric — a dispatcher may claim them as ONE batched device
    step (runtime/gang.py).  Purely advisory: the per-worker messages
    are the protocol; dropping a notice only costs the coalescing, and
    it never crosses a serde boundary (fabric.send_transient)."""

    members: tuple[tuple[int, int], ...]


@dataclasses.dataclass(frozen=True)
class LabeledData:
    """One streamed sample: sparse features + label (LabeledData.java:14-28)."""

    features: dict[int, float]
    label: int
