"""Wire engine — coalesced scatter-gather sends and buffered receive
for the socket transport (runtime/net.py, docs/WIRE.md).

The transport's frame format does not change here; this module changes
how frames cross the syscall boundary:

* `FrameWriter` — a bounded per-connection send queue drained by a
  dedicated writer thread.  Producers append (header, payload) pairs
  under the queue lock and return; the writer pops every queued frame
  and ships the batch in ONE `socket.sendmsg([hdr1, payload1, hdr2,
  payload2, ...])` scatter-gather syscall.  This is pscheck PS105's
  rule ("no blocking I/O under a lock") made structural: the lock is
  held only for the append/pop, never across the kernel call, and a
  slow peer stalls the writer thread instead of every thread that
  happens to send.  Backpressure when the queue is full is explicit:
  protocol frames block with a deadline, advisory frames (PING/PONG
  liveness — regenerated every interval anyway) take a typed drop and
  a counter, mirroring the bridge's `dropped_sends` semantics.
* `RecvBuffer` — a growable receive buffer filled with `recv_into`
  and parsed for ALL complete frames per chunk, replacing the
  2-syscalls-per-frame `_recv_exact` loop on bridge connections.
  Payloads stay zero-copy memoryviews into the buffer; exhausted
  buffers are replaced (never compacted in place) so views handed to
  decode sites — np.frombuffer arrays alias them — remain immutable
  for as long as the decoded messages live.
* `sendmsg_all` — the partial-send-safe scatter-gather primitive, also
  the non-queued `send_frame` path's two-element header/payload send
  (the 13-byte header is never concatenated onto a multi-KB payload).

The byte CONTENT of the stream is identical to the sequential
`send_frame` path — same frames, same order per connection — so a
coalescing fleet interoperates bit-for-bit with a `--no-wire-coalesce`
one, and the bench's `wire_ab` block pins theta + eval CSV bitwise
across the lever (scripts/bench_gate.py).

Telemetry: `wire_frames_per_syscall` (histogram, per flush),
`wire_send_queue_depth` (gauge, bytes queued), `wire_advisory_dropped`
(counter), and a `net.flush` flight event per writer flush
(docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import socket
import struct
import threading
from collections import deque

from kafka_ps_tpu.analysis.lockgraph import OrderedLock
from kafka_ps_tpu.telemetry import NULL_TELEMETRY
from kafka_ps_tpu.telemetry.flight import FLIGHT

# the one frame header, shared with runtime/net.py (which re-exports
# it): <u32 length> <u8 topic> <i64 key>, length counting topic+key+payload
_FRAME = struct.Struct("<IBq")

# segments per sendmsg call: IOV_MAX is 1024 on Linux — stay safely
# under it (2 segments per frame) and split bigger batches across calls
_IOV_CAP = 512

# frames-per-syscall histogram buckets: powers of two up to the best
# case of a full _IOV_CAP batch (256 two-segment frames in one call)
_FPS_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)


def force_close(sock: socket.socket) -> None:
    """shutdown + close: a plain close() does NOT wake a thread blocked
    in recv() on the same socket; shutdown(SHUT_RDWR) delivers EOF to
    it first."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def sendmsg_all(sock: socket.socket, buffers) -> int:
    """Ship every bytes-like in `buffers`, in order, via scatter-gather
    `sendmsg` — partial sends resumed, batches capped at `_IOV_CAP`
    segments.  Returns the number of syscalls issued (the coalescing
    ratio's denominator).  Falls back to one `sendall` of the joined
    bytes on sockets without sendmsg (platform without CMSG support,
    test doubles)."""
    views = [memoryview(b) for b in buffers if len(b)]
    if not views:
        return 0
    if not hasattr(sock, "sendmsg"):
        sock.sendall(b"".join(views))
        return 1
    syscalls = 0
    i, n = 0, len(views)
    while i < n:
        sent = sock.sendmsg(views[i:i + _IOV_CAP])
        syscalls += 1
        if sent <= 0:
            raise ConnectionError("socket closed mid-send")
        while i < n and sent >= len(views[i]):
            sent -= len(views[i])
            i += 1
        if sent:
            views[i] = views[i][sent:]
    return syscalls


class FrameWriter:
    """Bounded per-connection send queue + dedicated writer thread.

    `send()` appends one frame (header packed here) and returns True;
    the writer thread drains the queue in flush batches of at most
    `flush_budget` bytes / `_IOV_CAP` segments per `sendmsg`.  A send
    failure marks the writer dead, force-closes the socket (waking the
    peer connection's reader, whose cleanup drives eviction exactly as
    on the unqueued path), and drains the queue — every later `send`
    returns False, like a send to a dead connection.

    Backpressure (queue at `max_bytes`): protocol frames wait up to
    `send_deadline` seconds for space (False on expiry — the caller
    treats it as a dead connection); `advisory=True` frames drop
    immediately with a typed counter (`wire_advisory_dropped`).

    `close(flush=True)` is flush-before-close: the writer finishes the
    queue — a GOODBYE/CONFIG enqueued before close() reaches the wire
    before the socket goes down."""

    def __init__(self, sock: socket.socket, telemetry=None,
                 max_bytes: int = 8 << 20, flush_budget: int = 1 << 20,
                 send_deadline: float = 5.0):
        self._sock = sock
        self._max_bytes = int(max_bytes)
        self._flush_budget = int(flush_budget)
        self._deadline = float(send_deadline)
        self._q: deque = deque()          # (header, payload) pairs
        self._qbytes = 0
        # guarded-by: _lock (writers hold the queue lock; the dead property is a lock-free monotonic-bool peek)
        self._dead = False
        self._closing = False
        self._lock = OrderedLock("FrameWriter.queue")
        self._cond = threading.Condition(self._lock)
        telemetry = telemetry or NULL_TELEMETRY
        self._m_fps = telemetry.histogram("wire_frames_per_syscall",
                                          buckets=_FPS_BUCKETS)
        self._m_depth = telemetry.gauge("wire_send_queue_depth")
        self._m_dropped = telemetry.counter("wire_advisory_dropped")
        self.advisory_dropped = 0
        self._thread = threading.Thread(target=self._drain, daemon=True,
                                        name="kps-wire-writer")
        self._thread.start()

    @property
    def dead(self) -> bool:
        """True once a send failed: the socket is force-closed and the
        connection's reader-side cleanup is already in flight."""
        return self._dead

    def send(self, topic: int, key: int, payload=b"",
             advisory: bool = False) -> bool:
        """Queue one frame.  False when the writer is dead/closing, the
        protocol-frame deadline expired, or an advisory frame hit a
        full queue (typed drop)."""
        header = _FRAME.pack(_FRAME.size - 4 + len(payload), topic, key)
        size = len(header) + len(payload)
        with self._cond:
            if self._dead or self._closing:
                return False
            if self._qbytes + size > self._max_bytes:
                if advisory:
                    # liveness frames are regenerated next interval —
                    # dropping beats blocking the heartbeat thread
                    self.advisory_dropped += 1
                    self._m_dropped.inc()
                    return False
                ok = self._cond.wait_for(
                    lambda: (self._dead or self._closing
                             or self._qbytes + size <= self._max_bytes),
                    timeout=self._deadline)
                if not ok or self._dead or self._closing:
                    return False
            self._q.append((header, payload))
            self._qbytes += size
            self._m_depth.set(self._qbytes)
            self._cond.notify_all()
        return True

    def close(self, flush: bool = True, timeout: float = 10.0) -> None:
        """Stop the writer.  `flush=True` drains the queue first (the
        flush-before-close ordering); `flush=False` discards it.  Does
        NOT close the socket — the owner does, after this returns."""
        with self._cond:
            if not flush:
                self._q.clear()
                self._qbytes = 0
            self._closing = True
            self._cond.notify_all()
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=timeout)

    # -- the writer thread --------------------------------------------------

    def _pop_batch(self):
        """One flush batch under the queue lock: every queued frame up
        to the byte budget / segment cap.  Returns (segments, nframes,
        nbytes) or None when the writer should exit."""
        with self._cond:
            while not self._q and not self._closing and not self._dead:
                self._cond.wait()
            if self._dead or (self._closing and not self._q):
                return None
            batch = []
            nbytes = 0
            nframes = 0
            while (self._q and nbytes < self._flush_budget
                    and len(batch) + 2 <= _IOV_CAP):
                header, payload = self._q.popleft()
                batch.append(header)
                if len(payload):
                    batch.append(payload)
                nbytes += len(header) + len(payload)
                nframes += 1
            self._qbytes -= nbytes
            self._m_depth.set(self._qbytes)
            self._cond.notify_all()     # wake producers blocked on space
        return batch, nframes, nbytes

    def _drain(self) -> None:
        while True:
            popped = self._pop_batch()
            if popped is None:
                return
            batch, nframes, nbytes = popped
            try:
                # outside the queue lock: a slow peer stalls this
                # thread only (PS105 made structural)
                syscalls = sendmsg_all(self._sock, batch)
            except (ConnectionError, OSError):
                with self._cond:
                    self._dead = True
                    self._q.clear()
                    self._qbytes = 0
                    self._cond.notify_all()
                # wake the connection's reader so its disconnect
                # cleanup runs — same path a failed sendall took
                force_close(self._sock)
                return
            self._m_fps.observe(nframes / max(syscalls, 1))
            if FLIGHT.enabled:
                FLIGHT.record("net.flush", frames=nframes,
                              syscalls=syscalls, bytes=nbytes)


class RecvBuffer:
    """Buffered zero-copy frame reader for one connection.

    `recv_frame()` parses `(topic, key, payload-memoryview)` out of a
    growable buffer filled with `recv_into` — one syscall brings in as
    many frames as the kernel had ready, and every complete frame is
    parsed before the next syscall.  Returns None on a clean EOF at a
    frame boundary; EOF mid-frame raises ConnectionError (a crashed
    peer, never an orderly shutdown) — the exact `_recv_exact`
    contract.

    Buffers are REPLACED when exhausted, never compacted in place:
    payload memoryviews handed to decode sites alias the buffer
    (np.frombuffer), so a buffer with exported views must stay
    immutable until the decoded messages die; only the unconsumed tail
    is copied into the fresh buffer."""

    def __init__(self, sock: socket.socket, chunk: int = 1 << 16):
        self._sock = sock
        self._chunk = int(chunk)
        self._buf = bytearray(self._chunk)
        self._mv = memoryview(self._buf)
        self._pos = 0       # parse offset
        self._end = 0       # filled bytes

    def recv_frame(self):
        """(topic, key, payload) or None on clean EOF."""
        while True:
            avail = self._end - self._pos
            if avail >= 4:
                (length,) = struct.unpack_from("<I", self._buf, self._pos)
                total = 4 + length
                if avail >= total:
                    body = self._mv[self._pos + 4:self._pos + total]
                    topic, key = struct.unpack_from("<Bq", body, 0)
                    self._pos += total
                    return topic, key, body[9:]
                needed = total
            else:
                needed = 4
            if not self._fill(needed):
                return None

    def _fill(self, needed: int) -> bool:
        """Read more bytes (one recv_into), growing/replacing the buffer
        when the frame cannot fit contiguously from `_pos`.  False on a
        clean EOF; raises on EOF with a partial frame buffered."""
        avail = self._end - self._pos
        if self._pos + needed > len(self._buf) or self._end == len(self._buf):
            fresh = bytearray(max(self._chunk, needed))
            fresh[:avail] = self._mv[self._pos:self._end]
            self._buf = fresh
            self._mv = memoryview(fresh)
            self._pos = 0
            self._end = avail
        n = self._sock.recv_into(self._mv[self._end:])
        if n == 0:
            if avail:
                raise ConnectionError(
                    f"mid-frame EOF ({avail} buffered bytes)")
            return False
        self._end += n
        return True
