"""The parameter server — behavioral re-design of ServerProcessor
(processors/ServerProcessor.java:31-229).

State: the flat parameter vector (device-resident, updated by
REPLACEMENT — never mutated in place, so weights messages, evals and
checkpoints can all alias the immutable array), a MessageTracker, and
the consistency gate.  Aggregation: theta[range] += server_lr * delta
with server_lr defaulting to 1/num_workers, making the BSP update the
average of worker deltas (ServerProcessor.java:36,225-228).  Full-range
gradients (the per-node protocol) apply as one jit'd add with no host
synchronization; evaluation is an async dispatch whose results land in
the log when they resolve (utils/asynclog.DeferredSink) — the gate
never waits on an eval.

Consistency dispatch (ServerProcessor.java:95-134):
  * eventual (-1): answer only the sender, immediately;
  * sequential (0): when all gradients for clock t arrived, answer ALL
    workers with clock t+1;
  * bounded delay (k>0): answer every worker with an outstanding reply
    whose next clock is <= k ahead of the slowest worker.

Improvements over the reference (documented divergences):
  * gradient applied over the full half-open key range — the reference
    drops the last intercept via an inclusive/exclusive mismatch
    (SURVEY §3.5.1);
  * the server CSV line logs the real test loss instead of the
    hardcoded -1 (ServerProcessor.java:158-164) — same schema;
  * optional checkpointing (utils/checkpoint.py) instead of the
    reference's unconditional cold start (BaseKafkaApp.java:57).
"""

from __future__ import annotations

import time
from typing import Callable

import jax.numpy as jnp
import numpy as np

from kafka_ps_tpu.parallel.tracker import MessageTracker
from kafka_ps_tpu.runtime import fabric as fabric_mod
from kafka_ps_tpu.runtime.messages import GradientMessage, KeyRange, WeightsMessage
from kafka_ps_tpu.utils import asynclog
from kafka_ps_tpu.utils.config import EVENTUAL, PSConfig
from kafka_ps_tpu.utils.trace import NULL_TRACER

LogSink = Callable[[str], None]


class ServerNode:
    """Central aggregator + consistency gate + online evaluator."""

    def __init__(self, cfg: PSConfig, fabric: fabric_mod.Fabric,
                 test_x: np.ndarray | None = None,
                 test_y: np.ndarray | None = None,
                 log: LogSink | None = None,
                 tracer=None):
        self.tracer = tracer or NULL_TRACER
        self.cfg = cfg
        self.fabric = fabric
        self.tracker = MessageTracker(cfg.num_workers)
        from kafka_ps_tpu.models.task import get_task
        self.task = get_task(cfg.task, cfg.model)
        # device-resident; updated by replacement only (see module doc)
        self.theta = jnp.asarray(self.task.init_params(), dtype=jnp.float32)
        import jax
        self._apply_full = jax.jit(
            lambda t, d: t + self.cfg.server_lr * d)

        # apply + eval as ONE dispatch (per-dispatch host latency bounds
        # the per-node path over a tunneled transport, VERDICT r4 #2)
        def _apply_eval(t, d, tx, ty):
            t2 = t + self.cfg.server_lr * d
            m = self.task.evaluate(t2, tx, ty)
            return t2, m
        self._apply_full_eval = jax.jit(_apply_eval)
        self.test_x = jnp.asarray(test_x) if test_x is not None else None
        self.test_y = jnp.asarray(test_y) if test_y is not None else None
        self.log = log or (lambda line: None)
        self.iterations = 0          # total gradient messages applied
        self.last_metrics = None
        self._loop_started = False   # bootstrap broadcast done once
        # monotonic stamp of the last weights send per worker (heartbeat
        # baseline for the supervisor, runtime/app.py)
        self.weights_sent_at = [time.monotonic()] * cfg.num_workers
        # optional periodic checkpointing (utils/checkpoint.py)
        self.checkpoint_path: str | None = None
        self.checkpoint_every: int = 50   # <= 0: only save on exit
        self._last_checkpoint_iteration = 0
        # in-process runs fold the workers' buffers into the checkpoint
        # (durable training window); split mode leaves this None — each
        # worker process persists its own state file instead
        self.checkpoint_buffers = None
        # durable-log recovery (log/durable_fabric.py): the committed
        # offsets the restored checkpoint covers — replay starts there
        self.restored_log_offsets: dict[str, int] | None = None
        # logical-run identity: survives checkpoint resumes (restore
        # overwrites it), changes on every fresh start — worker-local
        # state files are only valid within the run that wrote them
        self.run_id = time.time_ns()
        # membership-change record (timestamp_ms, "evict"|"readmit"|
        # "resume", worker) — the audit trail the staleness auditor
        # segments elastic runs by (evaluation/validate.py epoch
        # checking).  `membership_log` (a CsvLogSink) persists each
        # event AS IT HAPPENS: an end-of-run write would lose the
        # record on a crash — the very scenario the events exist for
        self.membership_events: list[tuple[int, str, int]] = []
        self.membership_log = None

    # -- bootstrap (ServerProcessor.java:75-87) ----------------------------

    def start_training_loop(self) -> None:
        """Broadcast WeightsMessages to kick off the self-sustaining loop.

        Cold start: every worker is in the already-replied state (tracker
        bootstrap, MessageTracker.java:47-53) and gets clock 0, like the
        reference.  After a checkpoint restore: workers whose reply was
        delivered get their current clock re-sent (the in-flight message
        died with the crash); workers with a *withheld* reply go back
        through the consistency gate — only those currently eligible are
        re-issued, so restored runs respect the same staleness bounds.
        """
        if self._loop_started:
            # resuming a drive loop on a live system: the in-flight
            # messages are still in the fabric; re-broadcasting would
            # double-deliver and break the clock protocol
            return
        self._loop_started = True
        for worker, status in enumerate(self.tracker.tracker):
            if not status.active:
                continue
            # Durable-log restart: the crash did NOT kill in-flight
            # messages — the replayed queue may already hold this
            # worker's reply (log/durable_fabric.recover).  Re-sending
            # it would double-deliver; the replayed copy is the send.
            if self.fabric.pending(fabric_mod.WEIGHTS_TOPIC, worker):
                if not status.weights_message_sent:
                    self.tracker.sent_message(worker, status.vector_clock)
                continue
            if status.weights_message_sent:
                self.fabric.send(fabric_mod.WEIGHTS_TOPIC, worker,
                                 self._weights_message(status.vector_clock))
                self.weights_sent_at[worker] = time.monotonic()
        delay = self.cfg.max_vector_clock_delay
        if delay == EVENTUAL:
            # eventual answers immediately, so any surviving pending
            # reply is re-issued at once
            for worker, s in enumerate(self.tracker.tracker):
                if s.active and not s.weights_message_sent:
                    self.send_weights(worker, s.vector_clock)
        else:
            # sequential == bounded with delay 0: the tracker's own
            # sendable predicate (MessageTracker.java:69-79)
            self._flush_gate()

    def _weights_message(self, vector_clock: int) -> WeightsMessage:
        # device theta is immutable — safe to alias; a host-side theta
        # (checkpoint restore, partial-range splice) is copied so a
        # later in-place edit can't race an in-flight message
        values = (np.array(self.theta)
                  if isinstance(self.theta, np.ndarray) else self.theta)
        return WeightsMessage(
            vector_clock=vector_clock,
            key_range=KeyRange(0, self.task.num_params),
            values=values)

    def send_weights(self, worker: int, clock: int) -> None:
        """The single weights-send site: dispatch + tracker bookkeeping +
        the sent-at stamp the supervisor's heartbeat measures from (time
        a worker spends gate-blocked and idle must not count against
        it)."""
        self.fabric.send(fabric_mod.WEIGHTS_TOPIC, worker,
                         self._weights_message(clock))
        self.weights_sent_at[worker] = time.monotonic()
        self.tracker.sent_message(worker, clock)

    # -- consistency gate (ServerProcessor.java:95-134) --------------------

    def workers_to_respond_to(self, received_vc: int,
                              sender: int) -> set[tuple[int, int]]:
        delay = self.cfg.max_vector_clock_delay
        if delay == EVENTUAL:
            return {(sender, received_vc + 1)}
        if delay == 0:
            if self.tracker.has_received_all_messages(received_vc):
                return {(w, received_vc + 1)
                        for w in self.tracker.active_workers}
            return set()
        return set(self.tracker.get_all_sendable_messages(delay))

    # -- membership: failure detection / elastic recovery ------------------
    # The reference delegates both to the platform (Kafka consumer-group
    # rebalancing + k8s pod restarts, SURVEY §5); here they are runtime
    # APIs driven by the supervisor in runtime/app.py.

    def record_membership_event(self, kind: str, worker: int) -> None:
        ev = (int(time.time() * 1000), kind, worker)
        self.membership_events.append(ev)
        if self.membership_log is not None:
            self.membership_log(f"{ev[0]};{kind};{worker}")

    def remove_worker(self, worker: int) -> None:
        """Evict a failed worker: every consistency gate stops waiting
        for its gradients, and any round it was blocking is released."""
        self.tracker.deactivate_worker(worker)
        self.record_membership_event("evict", worker)
        self.tracer.count("server.workers_removed")
        self._flush_gate()

    def readmit_worker(self, worker: int) -> int:
        """Elastic scale-up: rejoin at the slowest active clock with the
        current weights (the state-store-restore analogue)."""
        # drain any pre-eviction in-flight traffic: a stale gradient (or
        # stale queued weights) becoming "live" again would break the
        # clock protocol
        self.fabric.purge(fabric_mod.GRADIENTS_TOPIC, 0,
                          lambda m: getattr(m, "worker_id", None) == worker)
        self.fabric.purge(fabric_mod.WEIGHTS_TOPIC, worker, lambda m: True)
        clock = self.tracker.reactivate_worker(worker)
        self.record_membership_event("readmit", worker)
        self.tracer.count("server.workers_readmitted")
        self.send_weights(worker, clock)
        return clock

    def _flush_gate(self) -> None:
        """Send every reply the gate now permits (used after membership
        changes — a removal can unblock rounds the dead worker held up)."""
        delay = self.cfg.max_vector_clock_delay
        if delay == EVENTUAL:
            return
        for worker, clock in self.tracker.get_all_sendable_messages(
                max(delay, 0)):
            self.send_weights(worker, clock)

    # -- the hot path (ServerProcessor.java:143-183) -----------------------

    def process(self, msg: GradientMessage) -> None:
        if not self.tracker.tracker[msg.worker_id].active:
            # in-flight gradient from an evicted worker (zombie): drop it
            # rather than corrupt the vector-clock protocol
            self.tracer.count("server.zombie_gradients_dropped")
            return
        if self.tracker.is_duplicate(msg.worker_id, msg.vector_clock):
            # exactly-once under the durable log's at-least-once replay
            # (log/durable_fabric.py): a delta whose clock the tracker
            # already advanced past was applied before the crash (or is
            # a recomputation from a replayed weights message) — drop
            # it instead of double-stepping theta.  Clocks AHEAD of the
            # tracker still raise below (the protocol sanitizer).
            self.tracer.count("server.duplicate_gradients_dropped")
            return
        self.tracker.received_message(msg.worker_id, msg.vector_clock)
        self.tracer.count("server.gradients_applied")

        want_eval = (msg.worker_id == 0 and self.test_x is not None
                     and msg.vector_clock % self.cfg.eval_every == 0)
        m = None
        with self.tracer.span("server.apply", worker=msg.worker_id,
                              clock=msg.vector_clock):
            r = msg.key_range
            if r.start == 0 and r.end == self.task.num_params:
                # per-node protocol: one async jit'd dispatch, no host
                # sync — eval iterations fuse the evaluation in (the
                # nested span keeps server.eval visible to --trace
                # consumers even though the dispatch is shared)
                if want_eval:
                    with self.tracer.span("server.eval",
                                          clock=msg.vector_clock):
                        self.theta, m = self._apply_full_eval(
                            jnp.asarray(self.theta), msg.values,
                            self.test_x, self.test_y)
                else:
                    self.theta = self._apply_full(jnp.asarray(self.theta),
                                                  msg.values)
            else:
                host = np.array(self.theta)
                host[r.start:r.end] += (self.cfg.server_lr
                                        * np.asarray(msg.values))
                self.theta = host
            self.iterations += 1

        if want_eval:
            if m is None:            # partial-range splice path
                with self.tracer.span("server.eval", clock=msg.vector_clock):
                    m = self.task.evaluate(jnp.asarray(self.theta),
                                           self.test_x, self.test_y)
            self.last_metrics = m            # device futures; float() syncs
            # schema: timestamp;partition;vectorClock;loss;fMeasure;accuracy
            # (ServerAppRunner.java:81); partition=-1 like the reference,
            # loss = real test loss (reference hardcodes -1)
            asynclog.submit_or_write(
                self.log,
                f"{int(time.time() * 1000)};-1;{msg.vector_clock};"
                "{};{};{}", m.loss, m.f1, m.accuracy)

        for worker, clock in self.workers_to_respond_to(msg.vector_clock,
                                                        msg.worker_id):
            self.send_weights(worker, clock)

        self.maybe_checkpoint()

    def maybe_checkpoint(self) -> None:
        """Save once every `checkpoint_every` applied iterations —
        crossing-based so any iteration stride (1 in the message path,
        num_workers in the fused path) triggers on schedule."""
        if not self.checkpoint_path or self.checkpoint_every <= 0:
            return
        if (self.iterations - self._last_checkpoint_iteration
                >= self.checkpoint_every):
            self.save_checkpoint_now()

    def save_checkpoint_now(self) -> None:
        """Write the checkpoint, and on a durable fabric
        (log/durable_fabric.py) make it a COMMIT POINT: snapshot the
        consumer offsets the state covers, store them inside the
        checkpoint (authoritative for replay), then durably commit them
        so retention can reap fully-consumed segments.  Order matters —
        offsets are only committed once the checkpoint that covers them
        is on disk, so a crash between the two steps replays extra
        records (at-least-once) instead of losing them."""
        if not self.checkpoint_path:
            return
        from kafka_ps_tpu.utils import checkpoint as ckpt
        offsets = (self.fabric.snapshot_offsets()
                   if getattr(self.fabric, "durable", False) else None)
        ckpt.save(self.checkpoint_path, self,
                  buffers=self.checkpoint_buffers, log_offsets=offsets)
        if offsets is not None:
            self.fabric.commit(offsets)
        self._last_checkpoint_iteration = self.iterations
