"""The parameter server — behavioral re-design of ServerProcessor
(processors/ServerProcessor.java:31-229).

State: the flat parameter vector (device-resident, updated by
REPLACEMENT — never mutated in place, so weights messages, evals and
checkpoints can all alias the immutable array), a MessageTracker, and
the consistency gate.  Aggregation: theta[range] += server_lr * delta
with server_lr defaulting to 1/num_workers, making the BSP update the
average of worker deltas (ServerProcessor.java:36,225-228).  Full-range
gradients (the per-node protocol) apply as one jit'd add with no host
synchronization; evaluation is an async dispatch whose results land in
the log when they resolve (utils/asynclog.DeferredSink) — the gate
never waits on an eval.

Consistency dispatch (ServerProcessor.java:95-134):
  * eventual (-1): answer only the sender, immediately;
  * sequential (0): when all gradients for clock t arrived, answer ALL
    workers with clock t+1;
  * bounded delay (k>0): answer every worker with an outstanding reply
    whose next clock is <= k ahead of the slowest worker.

Improvements over the reference (documented divergences):
  * gradient applied over the full half-open key range — the reference
    drops the last intercept via an inclusive/exclusive mismatch
    (SURVEY §3.5.1);
  * the server CSV line logs the real test loss instead of the
    hardcoded -1 (ServerProcessor.java:158-164) — same schema;
  * optional checkpointing (utils/checkpoint.py) instead of the
    reference's unconditional cold start (BaseKafkaApp.java:57).
"""

from __future__ import annotations

import time
from typing import Callable

import jax.numpy as jnp
import numpy as np

from kafka_ps_tpu.parallel.tracker import MessageTracker
from kafka_ps_tpu.runtime import fabric as fabric_mod
from kafka_ps_tpu.runtime.messages import (CompositeDelta, GangNotice,
                                           GradientMessage, KeyRange,
                                           WeightsMessage)
from kafka_ps_tpu.telemetry import (CLOCK_BUCKETS, NULL_TELEMETRY,
                                    model_name)
from kafka_ps_tpu.telemetry.flight import FLIGHT
from kafka_ps_tpu.telemetry.modelhealth import NULL_MODEL_HEALTH
from kafka_ps_tpu.utils import asynclog
from kafka_ps_tpu.utils.config import EVENTUAL, PSConfig
from kafka_ps_tpu.utils.trace import NULL_TRACER

LogSink = Callable[[str], None]


class ServerNode:
    """Central aggregator + consistency gate + online evaluator."""

    def __init__(self, cfg: PSConfig, fabric: fabric_mod.Fabric,
                 test_x: np.ndarray | None = None,
                 test_y: np.ndarray | None = None,
                 log: LogSink | None = None,
                 tracer=None, telemetry=None,
                 key_range: KeyRange | None = None,
                 shard_id: int = 0, num_shards: int = 1,
                 grad_key: int = 0):
        self.tracer = tracer or NULL_TRACER
        self.telemetry = telemetry or NULL_TELEMETRY
        self.cfg = cfg
        self.fabric = fabric
        self.tracker = MessageTracker(cfg.num_workers)
        # range sharding (runtime/sharding.py, docs/SHARDING.md): this
        # node owns `key_range` of the flat parameter vector — theta,
        # weights messages and the full-range fast path are all relative
        # to it.  The defaults (full range, shard 0 of 1, gradient key
        # 0) are byte-for-byte today's single server.
        self.shard_id = shard_id
        self.num_shards = num_shards
        self._grad_key = grad_key
        # consistency-model observability (docs/OBSERVABILITY.md): the
        # gate-wait and clock-lag distributions are what distinguish BSP
        # from bounded-delay from async at runtime.  Metric children are
        # pre-resolved here so the hot path never touches the registry's
        # family lock (null metrics when telemetry is off).  Sharded
        # servers label every family with their shard id; the unsharded
        # server keeps the historical label set.
        model = model_name(cfg.consistency_model)
        self._model = model          # span/critpath label, stable per node
        shard_labels = ({"shard": str(shard_id)} if num_shards > 1 else {})
        self._m_gate_wait = self.telemetry.histogram(
            "gate_wait_ms", model=model, **shard_labels)
        self._m_clock_lag = self.telemetry.histogram(
            "clock_lag", buckets=CLOCK_BUCKETS, model=model,
            **shard_labels)
        self._m_worker_lag = [
            self.telemetry.gauge("worker_clock_lag", worker=str(w),
                                 **shard_labels)
            for w in range(cfg.num_workers)]
        self._m_grads = [
            self.telemetry.counter("gradients_applied_total", worker=str(w),
                                   **shard_labels)
            for w in range(cfg.num_workers)]
        self._m_snapshots = self.telemetry.counter(
            "snapshots_published_total", **shard_labels)
        self._m_serving_clock = self.telemetry.gauge("serving_clock",
                                                     **shard_labels)
        # (perf_counter stamp, clock) of each worker's last un-answered
        # gradient: gate wait = release time - arrival time (host
        # scalars only); the clock rides along so the retroactive
        # gate.wait trace span can be matched to its delta flow
        # (telemetry/critpath.py keys segments on (worker, clock))
        self._grad_arrived: dict[int, tuple[float, int]] = {}
        # trace context of the gradient currently being processed — the
        # snapshot published by its release inherits it, extending the
        # delta.wire flow into the serving plane
        self._pending_trace = None
        from kafka_ps_tpu.models.task import get_task
        self.task = get_task(cfg.task, cfg.model)
        self._range = (key_range if key_range is not None
                       else KeyRange(0, self.task.num_params))
        # optional tiered residency (kafka_ps_tpu/store/, docs/
        # TIERING.md): None keeps theta a plain device array — today's
        # fully-resident behavior, byte for byte
        self.param_store = None
        # device-resident; updated by replacement only (see module doc).
        # A shard owns only its slice of the init vector (the slice of a
        # host ndarray is a view — same bits as the full init).
        if key_range is None:
            self.theta = jnp.asarray(self.task.init_params(),
                                     dtype=jnp.float32)
        else:
            self.theta = jnp.asarray(
                self.task.init_params()[key_range.start:key_range.end],
                dtype=jnp.float32)
        import jax
        self._apply_full = jax.jit(
            lambda t, d: t + self.cfg.server_lr * d)
        # sparse slice applies (SparseDeltaMessage, range sharding): one
        # jit'd scatter-add per padded bucket size — indices pad with 0
        # and values with 0.0, so duplicate pad entries add exact zeros
        self._sparse_apply_cache: dict = {}

        # apply + eval as ONE dispatch (per-dispatch host latency bounds
        # the per-node path over a tunneled transport, VERDICT r4 #2)
        def _apply_eval(t, d, tx, ty):
            t2 = t + self.cfg.server_lr * d
            m = self.task.evaluate(t2, tx, ty)
            return t2, m
        self._apply_full_eval = jax.jit(_apply_eval)
        # Batched (gang) apply programs, keyed on the static shape of a
        # batch: (k, eval positions, prefix-theta positions).  Each is
        # ONE jit'd dispatch that chains the k per-message updates —
        # chained adds, NOT deltas.sum(0): float addition is not
        # associative, and the acceptance bar is bitwise equality with
        # k sequential _apply_full calls (docs/GANG_DISPATCH.md).
        self._gang_apply_cache: dict = {}
        self.test_x = jnp.asarray(test_x) if test_x is not None else None
        self.test_y = jnp.asarray(test_y) if test_y is not None else None
        self.log = log or (lambda line: None)
        self.iterations = 0          # total gradient messages applied
        self.last_metrics = None
        self._loop_started = False   # bootstrap broadcast done once
        # monotonic stamp of the last weights send per worker (heartbeat
        # baseline for the supervisor, runtime/app.py)
        self.weights_sent_at = [time.monotonic()] * cfg.num_workers
        # optional periodic checkpointing (utils/checkpoint.py)
        self.checkpoint_path: str | None = None
        self.checkpoint_every: int = 50   # <= 0: only save on exit
        self._last_checkpoint_iteration = 0
        # in-process runs fold the workers' buffers into the checkpoint
        # (durable training window); split mode leaves this None — each
        # worker process persists its own state file instead
        self.checkpoint_buffers = None
        # weights-side compression (compress.WeightsCompressor, set by
        # app/CLI wiring when --compress != none): every outgoing
        # WeightsMessage carries quantize-dequantized values + the
        # encoded parts; the master theta here stays full precision
        self.compressor = None
        # {worker: ErrorFeedback} for in-process runs — the residuals
        # ride the checkpoint next to the buffers (split mode persists
        # them in each worker process's state file instead)
        self.checkpoint_residuals = None
        # durable-log recovery (log/durable_fabric.py): the committed
        # offsets the restored checkpoint covers — replay starts there
        self.restored_log_offsets: dict[str, int] | None = None
        # logical-run identity: survives checkpoint resumes (restore
        # overwrites it), changes on every fresh start — worker-local
        # state files are only valid within the run that wrote them
        self.run_id = time.time_ns()
        # membership-change record (timestamp_ms, "evict"|"readmit"|
        # "resume", worker) — the audit trail the staleness auditor
        # segments elastic runs by (evaluation/validate.py epoch
        # checking).  `membership_log` (a CsvLogSink) persists each
        # event AS IT HAPPENS: an end-of-run write would lose the
        # record on a crash — the very scenario the events exist for
        self.membership_events: list[tuple[int, str, int]] = []
        self.membership_log = None
        # online serving plane (kafka_ps_tpu/serving/, docs/SERVING.md):
        # when a SnapshotRegistry is attached, every consistency-gate
        # release publishes the released theta for readers.  None (the
        # default) keeps publish_snapshot a no-op — training is
        # bitwise-identical with serving on or off.
        self.serving = None
        # model-health plane (telemetry/modelhealth.py): per-update
        # diagnostics + drift detection when --model-health armed it.
        # NULL by default — one attribute load on the hot path, and
        # theta stays bitwise-identical either way (the plane only
        # reads values the update already produced).
        self.modelhealth = NULL_MODEL_HEALTH
        # async eval plane (evaluation/engine.py, --eval-async): when an
        # EvalEngine is attached, eval-cadence applies shed the fused
        # eval — the apply dispatch keeps the non-eval shape and the
        # (theta, clock) pair is handed to the engine's queue instead
        # (O(1): theta is an immutable alias by the replacement-only
        # contract above).  None keeps the fused `_apply_full_eval`
        # path — the --no-eval-async A/B arm, bitwise-identical CSV.
        self.eval_engine = None
        # hierarchical aggregation (kafka_ps_tpu/agg/,
        # docs/AGGREGATION.md): stacked composites under BSP are
        # round-buffered here (clock -> {worker: delta}) and applied in
        # worker-id order once the round is complete, so the aggregated
        # path is bitwise-identical to a deterministically-ordered
        # direct run regardless of composite arrival order.
        # `bsp_order` extends the same ordering to DIRECT gradients
        # (the determinism knob the tier1 --agg A/B comparison runs
        # both arms under); `weights_group_send` is the socket bridge's
        # grouped-fanout hook — one T_WEIGHTS_AGG frame per aggregator
        # instead of one T_WEIGHTS per member.
        self._agg_pending: dict[int, dict[int, GradientMessage]] = {}
        self.bsp_order = False
        self.weights_group_send = None

    # -- tiered residency (kafka_ps_tpu/store/, docs/TIERING.md) -----------

    @property
    def theta(self):
        """The owned parameter slice.  A direct array when fully
        resident (today's behavior); assembled on demand from the
        tiered store when one is attached.  Either way the value is
        immutable-by-contract — readers may alias it, writers go
        through the setter (replacement only, see module doc)."""
        if self.param_store is not None:
            return self.param_store.assembled()
        return self._theta

    @theta.setter
    def theta(self, value):
        if self.param_store is not None:
            self.param_store.replace_all(value)
            return
        self._theta = value

    def attach_param_store(self, store) -> None:
        """Switch this node's slice to tiered hot/warm/cold residency.
        Seeds the store from the current theta (attach-any-time is
        safe: before or after a checkpoint restore); afterwards dense
        applies run per page and the configured byte caps bound what
        stays device/host resident while every computed bit stays
        identical (the tier replay contract, docs/TIERING.md)."""
        if (store.key_range.start != self._range.start
                or store.key_range.end != self._range.end):
            raise ValueError(
                f"store range [{store.key_range.start}, "
                f"{store.key_range.end}) != shard range "
                f"[{self._range.start}, {self._range.end})")
        # one-time seed at attach, not the hot path
        store.replace_all(np.asarray(self._theta))
        self.param_store = store
        self._theta = None           # the store owns the values now
        store.rebalance()            # settle residency under the caps

    def attach_model_health(self, plane) -> None:
        """Arm the model-health plane (telemetry/modelhealth.py): the
        apply path starts feeding it per-update diagnostics and eval
        metrics.  Detach by re-attaching NULL_MODEL_HEALTH."""
        self.modelhealth = plane

    def attach_eval_engine(self, engine):
        """Arm the async eval plane (evaluation/engine.py): eval-cadence
        applies stop fusing the eval and submit (theta, clock) to the
        engine instead; the engine calls `_emit_eval` back in strict
        clock order.  Returns the engine (attach-and-keep idiom)."""
        self.eval_engine = engine
        return engine

    def _emit_eval(self, clock: int, m) -> None:
        """The ONE eval emission point — every fused path and the async
        engine's thread funnel through here, so CSV rows, last_metrics
        and the model-health plane see one sequence regardless of the
        lever.  Schema: timestamp;partition;vectorClock;loss;fMeasure;
        accuracy (ServerAppRunner.java:81); partition=-1 like the
        reference, loss = real test loss (reference hardcodes -1).
        Metric fields may be device futures — asynclog defers the
        fetch; modelhealth's sampler floats its copies off-path."""
        self.last_metrics = m
        asynclog.submit_or_write(
            self.log,
            f"{int(time.time() * 1000)};-1;{clock};"
            "{};{};{}", m.loss, m.f1, m.accuracy)
        if self.modelhealth.enabled:
            self.modelhealth.observe_eval(m.loss, m.f1)

    # -- bootstrap (ServerProcessor.java:75-87) ----------------------------

    def start_training_loop(self) -> None:
        """Broadcast WeightsMessages to kick off the self-sustaining loop.

        Cold start: every worker is in the already-replied state (tracker
        bootstrap, MessageTracker.java:47-53) and gets clock 0, like the
        reference.  After a checkpoint restore: workers whose reply was
        delivered get their current clock re-sent (the in-flight message
        died with the crash); workers with a *withheld* reply go back
        through the consistency gate — only those currently eligible are
        re-issued, so restored runs respect the same staleness bounds.
        """
        if self._loop_started:
            # resuming a drive loop on a live system: the in-flight
            # messages are still in the fabric; re-broadcasting would
            # double-deliver and break the clock protocol
            return
        self._loop_started = True
        released: list[tuple[int, int]] = []
        for worker, status in enumerate(self.tracker.tracker):
            if not status.active:
                continue
            # Durable-log restart: the crash did NOT kill in-flight
            # messages — the replayed queue may already hold this
            # worker's reply (log/durable_fabric.recover).  Re-sending
            # it would double-deliver; the replayed copy is the send.
            if self.fabric.pending(fabric_mod.WEIGHTS_TOPIC, worker):
                if not status.weights_message_sent:
                    self.tracker.sent_message(worker, status.vector_clock)
                continue
            if status.weights_message_sent:
                self.fabric.send(fabric_mod.WEIGHTS_TOPIC, worker,
                                 self._weights_message(status.vector_clock))
                self.weights_sent_at[worker] = time.monotonic()
                released.append((worker, status.vector_clock))
        delay = self.cfg.max_vector_clock_delay
        if delay == EVENTUAL:
            # eventual answers immediately, so any surviving pending
            # reply is re-issued at once
            for worker, s in enumerate(self.tracker.tracker):
                if s.active and not s.weights_message_sent:
                    self.send_weights(worker, s.vector_clock)
                    released.append((worker, s.vector_clock))
        else:
            # sequential == bounded with delay 0: the tracker's own
            # sendable predicate (MessageTracker.java:69-79)
            released.extend(self._flush_gate(notify=False))
        # the bootstrap broadcast is one simultaneous release moment for
        # every consistency model — one notice covers all of it
        self._emit_gang_notice(sorted(released))
        # first snapshot: the weights the loop starts from (cold start or
        # checkpoint restore) are servable before any gradient arrives
        self.publish_snapshot()

    def _weights_message(self, vector_clock: int) -> WeightsMessage:
        if self.param_store is not None:
            # assembled() is a FRESH host vector per call — nothing else
            # aliases it, so no defensive copy is needed
            values = self.param_store.assembled()
        else:
            # device theta is immutable — safe to alias; a host-side
            # theta (checkpoint restore, partial-range splice) is copied
            # so a later in-place edit can't race an in-flight message
            # pscheck: disable=PS102 (host->host defensive copy, no device sync)
            values = (np.array(self.theta)
                      if isinstance(self.theta, np.ndarray) else self.theta)
        encoded = None
        if self.compressor is not None:
            # every worker trains on the decoded (quantize-dequantized)
            # copy — in-process consumers get it by reference, socket
            # peers decode the SAME parts to the same floats
            values, encoded = self.compressor.encode(values)
        return WeightsMessage(
            vector_clock=vector_clock,
            key_range=self._range,
            values=values, encoded=encoded)

    def send_weights(self, worker: int, clock: int) -> None:
        """The single weights-send site: dispatch + tracker bookkeeping +
        the sent-at stamp the supervisor's heartbeat measures from (time
        a worker spends gate-blocked and idle must not count against
        it)."""
        self.fabric.send(fabric_mod.WEIGHTS_TOPIC, worker,
                         self._weights_message(clock))
        self.weights_sent_at[worker] = time.monotonic()
        self.tracker.sent_message(worker, clock)
        self._observe_gate_release(worker)
        if FLIGHT.enabled:
            FLIGHT.record("gate.release", shard=self.shard_id,
                          worker=worker, clock=clock)
            FLIGHT.beat("gate")

    def _observe_gate_release(self, worker: int) -> None:
        """Gate-wait sample: how long this worker's gradient sat at the
        gate before its reply went out (BSP waits for the round, bounded
        delay waits for the slowest-within-k, eventual ~0).  Bootstrap
        and readmission sends have no arrival stamp and record
        nothing.

        Also emits the retroactive `gate.wait` trace span — the gate
        holds weights RELEASES, not applies (gradients apply on
        arrival), so the hold time only exists as a span once the
        release happens.  The tracer's default clock is the same
        perf_counter the arrival stamp used, so span_at gets two values
        on one epoch."""
        if not self.telemetry.enabled:
            return
        entry = self._grad_arrived.pop(worker, None)
        if entry is not None:
            arrived, clock = entry
            now = time.perf_counter()
            self._m_gate_wait.observe((now - arrived) * 1e3)
            self.tracer.span_at("gate.wait", arrived, now, worker=worker,
                                clock=clock, model=self._model,
                                shard=self.shard_id)

    def gate_waiting(self) -> int:
        """How many active workers are currently parked at the gate
        (gradient received, reply withheld) — the demand predicate the
        gate watchdog checks liveness against (telemetry/health.py).
        Host ints only; safe from any thread (racy reads see a
        consistent-enough count)."""
        return sum(1 for w in self.tracker.active_workers
                   if not self.tracker.tracker[w].weights_message_sent)

    # -- consistency gate (ServerProcessor.java:95-134) --------------------

    def workers_to_respond_to(self, received_vc: int,
                              sender: int) -> set[tuple[int, int]]:
        delay = self.cfg.max_vector_clock_delay
        if delay == EVENTUAL:
            return {(sender, received_vc + 1)}
        if delay == 0:
            if self.tracker.has_received_all_messages(received_vc):
                return {(w, received_vc + 1)
                        for w in self.tracker.active_workers}
            return set()
        return set(self.tracker.get_all_sendable_messages(delay))

    # -- membership: failure detection / elastic recovery ------------------
    # The reference delegates both to the platform (Kafka consumer-group
    # rebalancing + k8s pod restarts, SURVEY §5); here they are runtime
    # APIs driven by the supervisor in runtime/app.py.

    def record_membership_event(self, kind: str, worker: int) -> None:
        ev = (int(time.time() * 1000), kind, worker)
        self.membership_events.append(ev)
        if self.membership_log is not None:
            self.membership_log(f"{ev[0]};{kind};{worker}")

    def remove_worker(self, worker: int) -> None:
        """Evict a failed worker: every consistency gate stops waiting
        for its gradients, and any round it was blocking is released."""
        self.tracker.deactivate_worker(worker)
        self.record_membership_event("evict", worker)
        self.tracer.count("server.workers_removed")
        if self._agg_pending:
            # drop the evictee's buffered round members and re-check
            # completeness — an eviction must not strand a BSP round
            # the dead worker was the last missing member of
            for bucket in self._agg_pending.values():
                bucket.pop(worker, None)
            self._flush_agg_rounds()
        self._flush_gate()

    def readmit_worker(self, worker: int) -> int:
        """Elastic scale-up: rejoin at the slowest active clock with the
        current weights (the state-store-restore analogue)."""
        # drain any pre-eviction in-flight traffic: a stale gradient (or
        # stale queued weights) becoming "live" again would break the
        # clock protocol
        self.fabric.purge(fabric_mod.GRADIENTS_TOPIC, self._grad_key,
                          lambda m: getattr(m, "worker_id", None) == worker)
        self.fabric.purge(fabric_mod.WEIGHTS_TOPIC, worker, lambda m: True)
        clock = self.tracker.reactivate_worker(worker)
        self.record_membership_event("readmit", worker)
        self.tracer.count("server.workers_readmitted")
        self.send_weights(worker, clock)
        return clock

    def _flush_gate(self, notify: bool = True) -> list[tuple[int, int]]:
        """Send every reply the gate now permits (used after membership
        changes — a removal can unblock rounds the dead worker held up).
        Returns the release set; `notify=False` suppresses the gang
        notice so a caller folding several release sources into one
        simultaneous moment (start_training_loop) emits a single one."""
        delay = self.cfg.max_vector_clock_delay
        if delay == EVENTUAL:
            return []
        release = sorted(self.tracker.get_all_sendable_messages(
            max(delay, 0)))
        for worker, clock in release:
            self.send_weights(worker, clock)
        if notify:
            self._emit_gang_notice(release)
            if release:
                self.publish_snapshot()
        return release

    # -- gang dispatch (runtime/gang.py, docs/GANG_DISPATCH.md) ------------

    def _emit_gang_notice(self, release: list[tuple[int, int]]) -> None:
        """Publish a batched-weights notification for a multi-member
        release set, ALONGSIDE the per-worker messages (which remain the
        protocol — the notice is advisory and never serialized)."""
        if self.cfg.use_gang and len(release) > 1:
            self.fabric.send_transient(
                fabric_mod.GANG_TOPIC, 0, GangNotice(members=tuple(release)))
            self.tracer.count("server.gang_release_sets")

    def dispatch_release_set(self, release) -> None:
        """The consistency dispatch, as an explicit release set: sorted
        per-worker sends (worker-id order keeps serial scheduling
        deterministic) plus the gang notice when several workers were
        released at the same moment.

        When a `weights_group_send` hook is attached (the socket
        bridge's aggregator fan-out, net.ServerBridge), it gets first
        claim on the set: members it ships inside grouped frames come
        back as a handled set and receive bookkeeping only — the same
        tracker/stamp/metric sequence send_weights runs, minus the
        per-worker fabric send the grouped frame replaced."""
        release = sorted(release)
        handled = self._group_send(release, self._weights_message)
        for worker, clock in release:
            if worker in handled:
                self._mark_grouped_release(worker, clock)
            else:
                self.send_weights(worker, clock)
        self._emit_gang_notice(release)
        if release:
            self.publish_snapshot()

    def _group_send(self, release, builder) -> set:
        """Offer a sorted release set to the grouped-fanout hook.
        `builder(clock)` produces the WeightsMessage a grouped frame
        carries (one body per distinct clock; the hook re-uses it
        across members).  Returns the worker ids the hook shipped."""
        if self.weights_group_send is None or not release:
            return set()
        return self.weights_group_send(release, builder)

    def _mark_grouped_release(self, worker: int, clock: int) -> None:
        """Bookkeeping for a release whose bytes went out inside a
        grouped aggregator frame: everything send_weights does except
        the fabric send."""
        self.weights_sent_at[worker] = time.monotonic()
        self.tracker.sent_message(worker, clock)
        self._observe_gate_release(worker)
        if FLIGHT.enabled:
            FLIGHT.record("gate.release", shard=self.shard_id,
                          worker=worker, clock=clock, grouped=True)
            FLIGHT.beat("gate")

    # -- serving plane (kafka_ps_tpu/serving/, docs/SERVING.md) ------------

    def serving_clock(self) -> int:
        """The stable clock a snapshot is stamped with: the slowest
        ACTIVE worker's vector clock.  Every weights message released at
        or before this moment carries a clock >= it, so a reader holding
        a snapshot at clock c knows all workers have incorporated rounds
        < c — the read-side mirror of the bounded-delay invariant."""
        active = self.tracker.active_workers
        if not active:
            return 0
        return min(self.tracker.tracker[w].vector_clock for w in active)

    def publish_snapshot(self, theta=None, clock=None, trace=None) -> None:
        """Publish (theta, stable clock) to the attached snapshot
        registry; no-op when serving is off.  Called at every gate
        release — per-message, gang, fused — plus bootstrap/cold-start.
        O(1) host-side (the snapshot aliases the immutable device
        theta), so attaching a registry cannot perturb training.
        `trace` (default: the context of the gradient being processed)
        rides on the snapshot so the serving plane can close the
        delta.wire flow at first read."""
        registry = self.serving
        if registry is None:
            return
        if trace is None:
            trace = self._pending_trace
        clock = self.serving_clock() if clock is None else clock
        registry.publish(self.theta if theta is None else theta,
                         clock, trace=trace)
        if trace is not None:
            # the flow's publish step: critpath reads the snapshot-
            # publish moment off this event (the segment between apply
            # and the first serving read, telemetry/critpath.py)
            self.tracer.flow_step("delta.wire", trace, step="publish",
                                  clock=int(clock))
        self.tracer.count("serving.snapshots_published")
        if self.telemetry.enabled:
            self._m_snapshots.inc()
            self._m_serving_clock.set(clock)
        if FLIGHT.enabled:
            FLIGHT.record("snapshot.publish", shard=self.shard_id,
                          clock=int(clock))

    # -- the hot path (ServerProcessor.java:143-183) -----------------------

    def process(self, msg: GradientMessage) -> None:
        if isinstance(msg, CompositeDelta):
            self.process_composite(msg)
            return
        if (self.bsp_order and self.cfg.max_vector_clock_delay == 0
                and getattr(msg, "indices", None) is None
                and msg.key_range.start == self._range.start
                and msg.key_range.end == self._range.end):
            # deterministic BSP ordering (docs/AGGREGATION.md): direct
            # gradients join the same per-round buffer composites use,
            # so a direct run and an aggregated run apply every round
            # in identical worker-id order — the A/B determinism knob
            if self._buffer_round_member(msg):
                self._flush_agg_rounds()
            return
        if not self.tracker.tracker[msg.worker_id].active:
            # in-flight gradient from an evicted worker (zombie): drop it
            # rather than corrupt the vector-clock protocol
            self.tracer.count("server.zombie_gradients_dropped")
            return
        if self.tracker.is_duplicate(msg.worker_id, msg.vector_clock):
            # exactly-once under the durable log's at-least-once replay
            # (log/durable_fabric.py): a delta whose clock the tracker
            # already advanced past was applied before the crash (or is
            # a recomputation from a replayed weights message) — drop
            # it instead of double-stepping theta.  Clocks AHEAD of the
            # tracker still raise below (the protocol sanitizer).
            self.tracer.count("server.duplicate_gradients_dropped")
            return
        self.tracker.received_message(msg.worker_id, msg.vector_clock)
        self.tracer.count("server.gradients_applied")
        if self.telemetry.enabled:
            self._observe_arrival(msg.worker_id, msg.vector_clock)
        if FLIGHT.enabled:
            self._flight_arrival(msg.worker_id, msg.vector_clock)
        if self.modelhealth.enabled:
            # host arrays (socket path) compute inline; device arrays
            # are observed by reference and resolved off-path
            self.modelhealth.observe_update(msg.worker_id, msg.values)
        fid = getattr(msg, "trace", None)
        self._pending_trace = fid

        want_eval = (msg.worker_id == 0 and self.test_x is not None
                     and msg.vector_clock % self.cfg.eval_every == 0)
        # async lever: with an engine attached the apply keeps the
        # non-eval program shape and the eval is deferred to the
        # engine's queue after the dispatch
        defer_eval = want_eval and self.eval_engine is not None
        fused_eval = want_eval and not defer_eval
        m = None
        deferred_theta = None
        with self.tracer.span("server.apply", worker=msg.worker_id,
                              clock=msg.vector_clock,
                              shard=self.shard_id, model=self._model):
            r = msg.key_range
            if getattr(msg, "indices", None) is not None:
                # sparse delta slice (SparseDeltaMessage, range sharding):
                # O(nnz) scatter-add onto this shard's slice — an EMPTY
                # slice advanced the gate above and skips the device
                # dispatch entirely (the work-reduction sharded topk
                # scaling rides on, docs/SHARDING.md)
                self._apply_sparse(msg, fid)
            elif (r.start == self._range.start
                    and r.end == self._range.end):
                # per-node protocol: one async jit'd dispatch, no host
                # sync — eval iterations fuse the evaluation in (the
                # nested span keeps server.eval visible to --trace
                # consumers even though the dispatch is shared)
                if self.param_store is not None:
                    m, deferred_theta = self._apply_tiered(
                        msg.values, fused_eval, defer_eval,
                        msg.vector_clock)
                elif fused_eval:
                    with self.tracer.span("server.eval",
                                          clock=msg.vector_clock):
                        self.theta, m = self._apply_full_eval(
                            jnp.asarray(self.theta), msg.values,
                            self.test_x, self.test_y)
                else:
                    self.theta = self._apply_full(jnp.asarray(self.theta),
                                                  msg.values)
                self.tracer.count("dispatch.device")
                if fid is not None:
                    # step the delta flow: the wire arrow lands on the
                    # net.recv slice, this one on the apply slice
                    self.tracer.flow_step("delta.wire", fid,
                                          clock=msg.vector_clock)
            else:
                # sub-range splice, relative to this node's owned range
                lo = r.start - self._range.start
                hi = r.end - self._range.start
                if lo < 0 or hi > len(self._range):
                    raise ValueError(
                        f"gradient range [{r.start}, {r.end}) outside "
                        f"shard range [{self._range.start}, "
                        f"{self._range.end})")
                # pscheck: disable=PS102 (KeyRange splice is the documented host path)
                host = np.array(self.theta)
                # pscheck: disable=PS102 (KeyRange splice is the documented host path)
                host[lo:hi] += self.cfg.server_lr * np.asarray(msg.values)
                self.theta = host
            self.iterations += 1

        if fused_eval:
            if m is None:            # partial-range splice path
                with self.tracer.span("server.eval", clock=msg.vector_clock):
                    m = self.task.evaluate(jnp.asarray(self.theta),
                                           self.test_x, self.test_y)
                    self.tracer.count("dispatch.device")
            self._emit_eval(msg.vector_clock, m)
        elif defer_eval:
            # immutable alias hand-off; the tiered path surfaces the
            # freshly-applied assembled vector so the engine never
            # re-assembles pages (and the splice path's theta is a
            # fresh host copy — also safe to alias)
            self.eval_engine.submit(
                self.theta if deferred_theta is None else deferred_theta,
                msg.vector_clock)

        self.dispatch_release_set(
            self.workers_to_respond_to(msg.vector_clock, msg.worker_id))
        self._pending_trace = None

        self.maybe_checkpoint()

    def _apply_tiered(self, delta, fused_eval: bool, defer_eval: bool,
                      clock: int):
        """Full-range dense apply against the tiered store.  Returns
        (metrics, deferred_theta) — at most one is non-None.

        Non-eval: per-page `t_p + lr * d_p` dispatches.  `_apply_full`
        is pointwise, so page-sliced applies produce bitwise-identical
        elements to the one full-slice apply — the tier bitwise
        contract (docs/TIERING.md).  Hot pages update device-to-device;
        warm/cold pages are materialized by the store (cold ones fault
        in from the log).

        Fused eval: assemble once and run the SAME fused
        `_apply_full_eval` program as the resident path, then scatter
        the result back — identical jaxpr on identical input bits, so
        the CSV metrics row matches the fully-resident run exactly.

        Deferred eval (--eval-async): the same assemble-once structure,
        but the apply keeps the non-eval program and the freshly-built
        t2 is returned for the engine's queue — an immutable device
        array the store's later page updates can never touch."""
        store = self.param_store
        if fused_eval:
            with self.tracer.span("server.eval", clock=clock):
                t2, m = self._apply_full_eval(
                    jnp.asarray(store.assembled()), delta,
                    self.test_x, self.test_y)
                store.replace_all(t2)
            return m, None
        if defer_eval:
            t2 = self._apply_full(jnp.asarray(store.assembled()), delta)
            store.replace_all(t2)
            return None, t2
        base = self._range.start
        for i, kr, value in store.pin_pages(self._range):
            lo, hi = kr.start - base, kr.end - base
            store.update_page(i, self._apply_full(jnp.asarray(value),
                                                  delta[lo:hi]))
        return None, None

    def _apply_sparse(self, msg, fid) -> None:
        """Apply a SparseDeltaMessage slice: theta[idx] += lr * vals as
        ONE jit'd scatter-add, compiled per padded bucket size (next
        power of two) so varying nnz across slices reuses a handful of
        programs.  Pad entries scatter an exact 0.0 onto index 0 —
        numerically exact (a padded slot may canonicalize -0.0; the
        sparse path carries no bitwise contract, docs/SHARDING.md).
        Empty slices skip the dispatch: the gate bookkeeping already
        ran, which is all an owning shard needs from a delta whose
        surviving top-k coordinates all live elsewhere."""
        k = len(msg.indices)
        if k == 0:
            self.tracer.count("dispatch.skipped_empty_slice")
        elif self.param_store is not None:
            self._apply_sparse_tiered(msg)
        else:
            bucket = 1 << max(3, int(k - 1).bit_length())
            idx = np.zeros((bucket,), dtype=np.int32)
            vals = np.zeros((bucket,), dtype=np.float32)
            idx[:k] = msg.indices
            vals[:k] = msg.values
            self.theta = self._sparse_apply_fn(bucket)(
                jnp.asarray(self.theta), idx, vals)
            self.tracer.count("dispatch.device")
        if fid is not None:
            # the arrow chain per delta SLICE: wire arrow lands on the
            # shard's net.recv, this step on its (possibly skipped) apply
            self.tracer.flow_step("delta.wire", fid,
                                  clock=msg.vector_clock,
                                  shard=self.shard_id)

    def _apply_sparse_tiered(self, msg) -> None:
        """Sparse scatter against the tiered store: group the slice's
        surviving indices by page (np.unique — sorted, deterministic)
        and run the bucketed scatter-add per touched page.  Pages the
        survivor set skips stay untouched — and therefore cool: this
        access skew is exactly what the heat policy feeds on
        (docs/TIERING.md)."""
        store = self.param_store
        # wire slices are host arrays; no device sync happens here
        idx = np.asarray(msg.indices, dtype=np.int64)
        vals = np.asarray(msg.values, dtype=np.float32)
        pages = idx // store.page_params
        for page in np.unique(pages):
            page = int(page)
            sel = pages == page
            local = (idx[sel] - page * store.page_params).astype(np.int32)
            n = len(local)
            bucket = 1 << max(3, int(n - 1).bit_length())
            bidx = np.zeros((bucket,), dtype=np.int32)
            bvals = np.zeros((bucket,), dtype=np.float32)
            bidx[:n] = local
            bvals[:n] = vals[sel]
            (_, _, value), = store.pin_pages(store.page_range(page))
            store.update_page(page, self._sparse_apply_fn(bucket)(
                jnp.asarray(value), bidx, bvals))
        self.tracer.count("dispatch.device")

    def _sparse_apply_fn(self, bucket: int):
        fn = self._sparse_apply_cache.get(bucket)
        if fn is None:
            import jax
            lr = self.cfg.server_lr

            def scatter(t, idx, vals):
                # pad entries are (0, 0.0) duplicates — scatter-add
                # tolerates duplicate indices, each contributing +0.0
                return t.at[idx].add(lr * vals)

            fn = jax.jit(scatter)
            self._sparse_apply_cache[bucket] = fn
        return fn

    def _flight_arrival(self, worker: int, clock: int) -> None:
        """Flight-recorder view of one gradient arrival: the full vector
        clock at gate-decision time (list index = worker id, evicted
        workers' clocks frozen where they stopped) plus this worker's
        lag — all host ints read off the tracker (no device values,
        PS106).  Kept to a flat int list: this runs per gradient, and
        the flight_overhead bench gates it at < 2% of server iters/s."""
        states = self.tracker.tracker
        clocks = [s.vector_clock for s in states]
        waiting = sum(1 for s in states
                      if s.active and not s.weights_message_sent)
        FLIGHT.record("gate.arrive", shard=self.shard_id, worker=worker,
                      clock=clock, lag=max(clocks) - clock,
                      waiting=waiting, clocks=clocks)
        FLIGHT.beat("gate")

    def _observe_arrival(self, worker: int, clock: int) -> None:
        """Per-gradient consistency observations, all host integers:
        arrival stamp (gate-wait baseline), this worker's clock lag
        behind the fastest active worker, and the applied-count."""
        self._grad_arrived[worker] = (time.perf_counter(), clock)
        self._m_grads[worker].inc()
        active = self.tracker.active_workers
        if active:
            fastest = max(self.tracker.tracker[w].vector_clock
                          for w in active)
            for w in active:
                lag = fastest - self.tracker.tracker[w].vector_clock
                self._m_worker_lag[w].set(lag)
            self._m_clock_lag.observe(
                fastest - self.tracker.tracker[worker].vector_clock)

    # -- hierarchical aggregation (kafka_ps_tpu/agg/, docs/AGGREGATION.md) --

    def process_composite(self, comp: CompositeDelta) -> None:
        """Apply one aggregator composite: the gate advances every
        member worker's clock from the composite's vector-clock map
        exactly as if the member deltas had arrived individually.

        Stacked composites expand into their per-member deltas: under
        BSP they enter the round buffer (worker-id-ordered applies,
        bitwise-pinned to the ordered direct path); under bounded
        delay/eventual they apply in member order via `process_batch`
        (itself bitwise-identical to per-message processing).  Summed
        composites apply as ONE pre-reduced add per host per clock —
        exact by linearity, not bitwise-pinned."""
        self.tracer.count("server.composites_received")
        if FLIGHT.enabled:
            FLIGHT.record("agg.composite", shard=self.shard_id,
                          agg=comp.agg_id, fan_in=comp.fan_in,
                          summed=comp.summed)
        if comp.summed:
            self._process_summed(comp)
            return
        resent: set = set()
        if self.cfg.max_vector_clock_delay == 0:
            buffered = False
            for d in comp.deltas:
                buffered |= self._buffer_round_member(d, resent)
            if buffered:
                self._flush_agg_rounds()
            return
        live = [d for d in comp.deltas
                if self._composite_member_live(d.worker_id,
                                               d.vector_clock, resent)]
        if live:
            self.process_batch(live)

    def _composite_member_live(self, worker: int, clock: int,
                               resent: set | None = None) -> bool:
        """Zombie/duplicate filter for one composite member, with the
        aggregator-restart liveness rule: a duplicate whose reply was
        already issued gets the current weights RE-sent — the original
        reply may have died inside the SIGKILL'd aggregator, and
        without a re-send the worker would wait forever (the worker
        side deduplicates redelivered weights, docs/COMPRESSION.md).
        `resent` bounds the re-send to once per worker per composite:
        a reconnecting worker's cache resend can land its whole tail of
        already-applied clocks inside one composite."""
        status = self.tracker.tracker[worker]
        if not status.active:
            self.tracer.count("server.zombie_gradients_dropped")
            return False
        if self.tracker.is_duplicate(worker, clock):
            self.tracer.count("server.duplicate_gradients_dropped")
            if status.weights_message_sent and (resent is None
                                                or worker not in resent):
                if resent is not None:
                    resent.add(worker)
                self.send_weights(worker, status.vector_clock)
            return False
        return True

    def _buffer_round_member(self, msg: GradientMessage,
                             resent: set | None = None) -> bool:
        """Queue one BSP-round member (from a composite expansion or a
        `bsp_order` direct gradient) for the ordered flush."""
        if not self._composite_member_live(msg.worker_id,
                                           msg.vector_clock, resent):
            return False
        bucket = self._agg_pending.setdefault(msg.vector_clock, {})
        if msg.worker_id in bucket:
            self.tracer.count("server.duplicate_gradients_dropped")
            return False
        bucket[msg.worker_id] = msg
        return True

    def _flush_agg_rounds(self) -> None:
        """Apply every complete buffered round, lowest clock first, in
        worker-id order — ONE process_batch per round, so evals land on
        the same prefix thetas and releases at the same moments as a
        worker-id-ordered serial direct run."""
        while self._agg_pending:
            clock = min(self._agg_pending)
            bucket = self._agg_pending[clock]
            expected = {w for w in self.tracker.active_workers
                        if self.tracker.tracker[w].vector_clock == clock}
            if not expected or not expected.issubset(bucket):
                return
            del self._agg_pending[clock]
            self.process_batch([bucket[w] for w in sorted(expected)])

    def _process_summed(self, comp: CompositeDelta) -> None:
        """One pre-reduced apply for a whole host's round contribution.
        All members must share one clock (the aggregator only sums a
        single-clock flush); a partially-duplicate composite is a
        protocol violation — the sum cannot be partially applied."""
        clocks = {c for _, c in comp.members}
        if len(clocks) != 1:
            raise ValueError(
                f"summed composite spans clocks {sorted(clocks)}")
        clock = next(iter(clocks))
        live, dup = [], []
        for worker, c in comp.members:
            if not self.tracker.tracker[worker].active:
                raise ValueError(
                    f"summed composite includes evicted worker {worker}")
            (dup if self.tracker.is_duplicate(worker, c)
             else live).append(worker)
        if not live:
            # whole-composite redelivery (aggregator restart): already
            # applied — re-issue any already-released replies that may
            # have died with the aggregator, drop the delta
            self.tracer.count("server.duplicate_gradients_dropped")
            for worker in dup:
                status = self.tracker.tracker[worker]
                if status.weights_message_sent:
                    self.send_weights(worker, status.vector_clock)
            return
        if dup:
            raise ValueError(
                f"summed composite partially applied: duplicates {dup} "
                f"alongside live members {live}")
        delta = comp.deltas[0]
        for worker in live:
            self.tracker.received_message(worker, clock)
            self.tracer.count("server.gradients_applied")
            if self.telemetry.enabled:
                self._observe_arrival(worker, clock)
            if FLIGHT.enabled:
                self._flight_arrival(worker, clock)
        fid = getattr(delta, "trace", None)
        self._pending_trace = fid
        want_eval = (0 in live and self.test_x is not None
                     and clock % self.cfg.eval_every == 0)
        defer_eval = want_eval and self.eval_engine is not None
        fused_eval = want_eval and not defer_eval
        m = None
        with self.tracer.span("server.apply", agg=comp.agg_id,
                              fan_in=len(live), clock=clock,
                              shard=self.shard_id, model=self._model):
            if fused_eval:
                with self.tracer.span("server.eval", clock=clock):
                    self.theta, m = self._apply_full_eval(
                        jnp.asarray(self.theta), delta.values,
                        self.test_x, self.test_y)
            else:
                self.theta = self._apply_full(jnp.asarray(self.theta),
                                              delta.values)
            self.tracer.count("dispatch.device")
            self.iterations += len(live)
        if fused_eval:
            self._emit_eval(clock, m)
        elif defer_eval:
            # self.theta is replaced (never mutated) by later applies, so
            # handing the alias to the engine's queue is safe — the
            # snapshot-registry immutability contract (serving/snapshot.py)
            self.eval_engine.submit(self.theta, clock)
        release: set = set()
        for worker in live:
            release |= self.workers_to_respond_to(clock, worker)
        self.dispatch_release_set(release)
        self._pending_trace = None
        self.maybe_checkpoint()

    def process_batch(self, msgs: list[GradientMessage]) -> None:
        """Apply several queued gradients as ONE chained jit dispatch
        (gang dispatch, docs/GANG_DISPATCH.md) — bitwise-identical to
        calling `process` per message, cheaper by k-1 device round-trips.

        Per-message semantics are preserved exactly:
          * validation (zombie/duplicate drops) and the consistency gate
            run INCREMENTALLY per message, in queue order — the gate for
            message i sees the tracker state messages 0..i left behind,
            so release decisions match the per-message path;
          * gate bookkeeping (tracker.sent_message) happens at decision
            time, but the fabric sends are deferred until the batched
            apply yields each release's PREFIX theta — a mid-batch
            release observes theta after exactly the deltas the
            per-message path would have applied before it;
          * evals land at the same clocks, computed on the same prefix
            thetas, logged in the same row order;
          * the update itself is a chain of adds inside one jit —
            NOT deltas.sum(0), which is mathematically identical but
            not bitwise (float addition is non-associative).
        Checkpointing runs once at batch end (the crossing-based
        trigger still fires on schedule); cadence is not part of the
        bitwise contract.  Partial-range gradients (range sharding)
        fall back to per-message processing.
        """
        if self.param_store is not None:
            # the gang chain wants the whole slice in one device array;
            # with tiered residency attached, fall back to per-message
            # processing — bitwise-equivalent by the gang contract
            # itself (docs/GANG_DISPATCH.md, tests/test_gang.py), just
            # without the k-1 round-trip saving
            for m in msgs:
                self.process(m)
            return
        full = all(getattr(m, "indices", None) is None
                   and m.key_range.start == self._range.start
                   and m.key_range.end == self._range.end
                   for m in msgs)
        if not full:
            for m in msgs:
                self.process(m)
            return
        # duplicate detection must see the clock advancement the EARLIER
        # batch members will cause — a redelivered gradient can appear
        # twice in one recovered backlog (at-least-once replay), and the
        # per-message path would apply the first and drop the second.
        # Simulate the advancement here; the tracker itself moves below.
        live = []
        ahead: dict[int, int] = {}
        for m in msgs:
            if not self.tracker.tracker[m.worker_id].active:
                self.tracer.count("server.zombie_gradients_dropped")
                continue
            expected = ahead.get(
                m.worker_id, self.tracker.tracker[m.worker_id].vector_clock)
            if m.vector_clock < expected:
                self.tracer.count("server.duplicate_gradients_dropped")
                continue
            ahead[m.worker_id] = m.vector_clock + 1
            live.append(m)
        if len(live) < 2:
            for m in live:           # process() re-validates (no-op here)
                self.process(m)
            return

        k = len(live)
        defer_eval = self.eval_engine is not None
        eval_events: list[tuple[int, int]] = []   # (position, clock)
        release_events: list[tuple[int, list[tuple[int, int]]]] = []
        snap_clocks: dict[int, int] = {}
        for i, m in enumerate(live):
            self.tracker.received_message(m.worker_id, m.vector_clock)
            self.tracer.count("server.gradients_applied")
            if self.telemetry.enabled:
                self._observe_arrival(m.worker_id, m.vector_clock)
            if FLIGHT.enabled:
                self._flight_arrival(m.worker_id, m.vector_clock)
            if self.modelhealth.enabled:
                self.modelhealth.observe_update(m.worker_id, m.values)
            if (m.worker_id == 0 and self.test_x is not None
                    and m.vector_clock % self.cfg.eval_every == 0):
                eval_events.append((i, m.vector_clock))
            release = sorted(self.workers_to_respond_to(m.vector_clock,
                                                        m.worker_id))
            for w, c in release:
                self.tracker.sent_message(w, c)
            if release:
                release_events.append((i, release))
                if self.serving is not None:
                    # stable clock at gate-DECISION time: tracker state
                    # here matches the per-message path after message i
                    # (sent_message never moves clocks), so the published
                    # (theta_i, clock) sequence is bitwise-identical to
                    # processing the batch one message at a time
                    snap_clocks[i] = self.serving_clock()
        # releases at the last position see the final theta; earlier
        # ones need their prefix returned from the jit.  Deferred evals
        # turn their positions into prefix requests too — the engine
        # evaluates the SAME prefix theta the fused program would have,
        # it just does so off the apply path.
        prefix_need = {i for i, _ in release_events if i < k - 1}
        if defer_eval:
            prefix_need |= {i for i, _ in eval_events if i < k - 1}
            eval_positions: tuple = ()
        else:
            eval_positions = tuple(i for i, _ in eval_events)
        prefix_positions = tuple(sorted(prefix_need))
        fn = self._gang_apply_fn(k, eval_positions, prefix_positions)
        # same span name as the per-message path — one entry now covers
        # k chained applies (the `gang` arg distinguishes the two)
        with self.tracer.span("server.apply", gang=k,
                              workers=[m.worker_id for m in live],
                              model=self._model):
            final_theta, prefixes, metrics = fn(
                jnp.asarray(self.theta), self.test_x, self.test_y,
                *[m.values for m in live])
            self.iterations += k
            for m in live:
                fid = getattr(m, "trace", None)
                if fid is not None:
                    self.tracer.flow_step("delta.wire", fid,
                                          clock=m.vector_clock)
        self.tracer.count("dispatch.device")
        self.tracer.count("server.gang_batched_applies")
        self.theta = final_theta
        prefix_theta = dict(zip(prefix_positions, prefixes))
        release_at = dict(release_events)
        eval_at = dict(eval_events)
        mi = 0
        batch_released: list[tuple[int, int]] = []
        for i, m in enumerate(live):
            if i in eval_at and not defer_eval:
                # the eval itself ran fused inside the batched apply;
                # this span marks where its results enter the protocol
                with self.tracer.span("server.eval",
                                      clock=m.vector_clock, fused=True):
                    met = metrics[mi]
                    mi += 1
                    self._emit_eval(m.vector_clock, met)
            elif i in eval_at:
                # deferred: hand the engine the prefix theta this clock
                # observed — the exact array the fused program would have
                # evaluated (final_theta for the last position)
                self.eval_engine.submit(
                    prefix_theta.get(i, final_theta), eval_at[i])
            rel = release_at.get(i)
            if rel:
                theta_i = prefix_theta.get(i, final_theta)
                handled = self._group_send(
                    rel, lambda clock: self._prepared_message(clock,
                                                              theta_i))
                for worker, clock in rel:
                    if worker in handled:
                        # gate bookkeeping (tracker.sent_message) ran at
                        # decision time above — stamp/metrics only here,
                        # matching _send_weights_prepared
                        self.weights_sent_at[worker] = time.monotonic()
                        self._observe_gate_release(worker)
                        if FLIGHT.enabled:
                            FLIGHT.record("gate.release",
                                          shard=self.shard_id,
                                          worker=worker, clock=clock,
                                          gang=True, grouped=True)
                            FLIGHT.beat("gate")
                    else:
                        self._send_weights_prepared(worker, clock,
                                                    theta_i)
                batch_released.extend(rel)
                if self.serving is not None:
                    # gang-path publication point: the prefix theta this
                    # release observed, at the clock captured when the
                    # gate opened — one snapshot per release event, same
                    # as the per-message path
                    self.publish_snapshot(theta_i, snap_clocks[i],
                                          trace=getattr(m, "trace", None))
        # ONE notice for everything this batch released: the release
        # events are simultaneous from the drive loop's point of view
        # (all sends above happened before any worker ran), and the gang
        # stacks per-member thetas, so mid-batch releases with prefix
        # thetas coalesce as well as end-of-batch ones.  This is what
        # lets the eventual model gang in steady state — its per-message
        # releases are all singletons.
        self._emit_gang_notice(sorted(batch_released))
        self.maybe_checkpoint()

    def _gang_apply_fn(self, k: int, eval_positions: tuple,
                       prefix_positions: tuple):
        """One jit'd program per batch shape: chain k updates, returning
        (final theta, prefix thetas at `prefix_positions`, metrics at
        `eval_positions`) — a single dispatch whatever the batch asks."""
        key = (k, eval_positions, prefix_positions)
        fn = self._gang_apply_cache.get(key)
        if fn is None:
            import jax
            lr = self.cfg.server_lr
            task = self.task
            eval_set = frozenset(eval_positions)
            prefix_set = frozenset(prefix_positions)

            def chain(t, tx, ty, *deltas):
                prefixes, metrics = [], []
                for i, d in enumerate(deltas):
                    t = t + lr * d
                    if i in prefix_set:
                        prefixes.append(t)
                    if i in eval_set:
                        metrics.append(task.evaluate(t, tx, ty))
                return t, prefixes, metrics

            fn = jax.jit(chain)
            self._gang_apply_cache[key] = fn
        return fn

    def _prepared_message(self, clock: int, theta) -> WeightsMessage:
        """WeightsMessage over an already-computed (prefix) theta —
        the builder the gang release path hands to grouped fan-out.
        Repeated calls on one theta array reuse the compressor's
        identity cache, so a multi-member release encodes once."""
        encoded = None
        if self.compressor is not None:
            # prefix thetas of one batch are distinct arrays, but a
            # multi-member release at the SAME position reuses the
            # compressor's identity cache
            theta, encoded = self.compressor.encode(theta)
        return WeightsMessage(vector_clock=clock, key_range=self._range,
                              values=theta, encoded=encoded)

    def _send_weights_prepared(self, worker: int, clock: int,
                               theta) -> None:
        """Fabric send for a release whose gate bookkeeping already ran
        (process_batch records tracker.sent_message at gate-decision
        time; the send waits for the batched apply to yield the prefix
        theta this release observes)."""
        self.fabric.send(fabric_mod.WEIGHTS_TOPIC, worker,
                         self._prepared_message(clock, theta))
        self.weights_sent_at[worker] = time.monotonic()
        self._observe_gate_release(worker)
        if FLIGHT.enabled:
            FLIGHT.record("gate.release", shard=self.shard_id,
                          worker=worker, clock=clock, gang=True)
            FLIGHT.beat("gate")

    def maybe_checkpoint(self) -> None:
        """Save once every `checkpoint_every` applied iterations —
        crossing-based so any iteration stride (1 in the message path,
        num_workers in the fused path) triggers on schedule."""
        if not self.checkpoint_path or self.checkpoint_every <= 0:
            return
        if (self.iterations - self._last_checkpoint_iteration
                >= self.checkpoint_every):
            self.save_checkpoint_now()

    def save_checkpoint_now(self) -> None:
        """Write the checkpoint, and on a durable fabric
        (log/durable_fabric.py) make it a COMMIT POINT: snapshot the
        consumer offsets the state covers, store them inside the
        checkpoint (authoritative for replay), then durably commit them
        so retention can reap fully-consumed segments.  Order matters —
        offsets are only committed once the checkpoint that covers them
        is on disk, so a crash between the two steps replays extra
        records (at-least-once) instead of losing them."""
        if not self.checkpoint_path:
            return
        from kafka_ps_tpu.utils import checkpoint as ckpt
        offsets = (self.fabric.snapshot_offsets()
                   if getattr(self.fabric, "durable", False) else None)
        ckpt.save(self.checkpoint_path, self,
                  buffers=self.checkpoint_buffers, log_offsets=offsets,
                  residuals=self.checkpoint_residuals)
        if offsets is not None:
            self.fabric.commit(offsets)
        self._last_checkpoint_iteration = self.iterations
