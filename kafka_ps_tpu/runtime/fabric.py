"""In-process message fabric — the semantic stand-in for the Kafka topics.

The reference's only inter-process transport is three Kafka topics
(BaseKafkaApp.java:27-33): WEIGHTS (point-to-point by worker key),
GRADIENTS (many-to-one gather, 1 partition, ServerApp.java:38) and
INPUT_DATA (data distribution).  The properties the consistency models
rely on — addressed delivery, per-key FIFO ordering, asynchronous
buffering that lets workers run unsynchronized — are preserved by plain
thread-safe deques.  On TPU the payload hops this fabric carries are the
host-side control plane only; the actual tensors move host↔device via
`device_put` and device↔device via ICI collectives (parallel/bsp.py).

Doubles as the deterministic test harness the reference declared a
dependency for but never used (kafka-streams-test-utils, build.gradle:51
— SURVEY §4): tests drive `poll` directly for fully deterministic
scheduling.

Key conventions: WEIGHTS is keyed by worker id everywhere.  GRADIENTS
is keyed 0 for the single server; a range-sharded group
(runtime/sharding.py, docs/SHARDING.md) keys it by SHARD id — shard i
polls (GRADIENTS_TOPIC, i) and workers' routers address slices to the
owning shard, so the many-to-one gather becomes N independent per-key
FIFOs with no fabric change.
"""

from __future__ import annotations

from collections import deque
from typing import Any

from kafka_ps_tpu.analysis.lockgraph import OrderedCondition
from kafka_ps_tpu.utils.trace import NULL_TRACER, Tracer

WEIGHTS_TOPIC = "weights"
GRADIENTS_TOPIC = "gradients"
INPUT_DATA_TOPIC = "input-data"
# Advisory gang-release notices (runtime/gang.py): in-process control
# traffic with no reference-topic analogue — never serialized, never
# durable (a lost notice only costs a coalescing opportunity).
GANG_TOPIC = "gang"


class Fabric:
    """Keyed FIFO queues with blocking and non-blocking consumption.

    Per-topic send counters on the tracer give the message-flow view the
    reference got from its Confluent interceptors (BaseKafkaApp.java:73-78).
    """

    def __init__(self, tracer: Tracer | None = None):
        self._queues: dict[tuple[str, int], deque] = {}
        # named per class so DurableFabric orderings get their own node
        # in the lock-acquisition graph (analysis/lockgraph.py)
        self._cond = OrderedCondition(f"{type(self).__name__}.cond")
        self._tracer = tracer or NULL_TRACER

    def _q(self, topic: str, key: int) -> deque:
        return self._queues.setdefault((topic, key), deque())

    def send(self, topic: str, key: int, message: Any) -> None:
        self._tracer.count(f"send.{topic}")
        with self._cond:
            self._q(topic, key).append(message)
            self._cond.notify_all()

    def send_transient(self, topic: str, key: int, message: Any) -> None:
        """Enqueue WITHOUT durability semantics — advisory in-process
        traffic (GANG_TOPIC notices) that subclasses must not log or
        serialize.  Identical to `send` on the volatile fabric."""
        self.send(topic, key, message)

    def poll(self, topic: str, key: int = 0) -> Any | None:
        """Non-blocking: next message for (topic, key) or None."""
        with self._cond:
            q = self._q(topic, key)
            return q.popleft() if q else None

    def poll_blocking(self, topic: str, key: int = 0,
                      timeout: float | None = None) -> Any | None:
        with self._cond:
            q = self._q(topic, key)
            if not q:
                self._cond.wait_for(lambda: bool(q), timeout=timeout)
            return q.popleft() if q else None

    def purge(self, topic: str, key: int, pred) -> int:
        """Remove queued messages matching pred; returns how many (used
        to drain an evicted worker's in-flight messages on readmission)."""
        with self._cond:
            q = self._q(topic, key)
            kept = [m for m in q if not pred(m)]
            removed = len(q) - len(kept)
            q.clear()
            q.extend(kept)
            return removed

    def contains(self, topic: str, key: int, pred) -> bool:
        """True if any queued message matches pred (non-destructive)."""
        with self._cond:
            return any(pred(m) for m in self._q(topic, key))

    def pending(self, topic: str, key: int = 0) -> int:
        with self._cond:
            return len(self._q(topic, key))

    def total_pending(self, topic: str) -> int:
        with self._cond:
            return sum(len(q) for (t, _), q in self._queues.items() if t == topic)
