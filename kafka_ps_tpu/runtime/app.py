"""System assembly + drive loops — the reference's apps/ layer
(BaseKafkaApp/ServerApp/WorkerApp topologies, BaseKafkaApp.java:23-87)
without the broker.

Wires: CSV stream producer → per-worker sliding buffers (the INPUT_DATA
hop), WorkerNodes ↔ ServerNode over the in-process fabric (the
WEIGHTS/GRADIENTS hops), with three drive modes:

  * `run_serial` — deterministic single-thread scheduler (the test
    harness the reference never built, SURVEY §4);
  * `run_threaded` — one thread per worker + server on the main thread,
    mirroring the reference's 4 stream threads (BaseKafkaApp.java:70);
    real wall-clock overlap for the async consistency models via JAX
    async dispatch;
  * `run_fused_bsp` — the TPU-native fast path for the sequential model:
    whole iterations as single jit'd shard_map steps (parallel/bsp.py),
    buffers re-slabbed between steps.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from kafka_ps_tpu.data.buffer import SlidingBuffer
from kafka_ps_tpu.data.stream import CsvStreamProducer
from kafka_ps_tpu.parallel import bsp
from kafka_ps_tpu.runtime import fabric as fabric_mod
from kafka_ps_tpu.runtime.server import LogSink, ServerNode
from kafka_ps_tpu.runtime.worker import WorkerNode
from kafka_ps_tpu.telemetry import NULL_TELEMETRY
from kafka_ps_tpu.utils import asynclog
from kafka_ps_tpu.utils.asynclog import DeferredSink
from kafka_ps_tpu.utils.config import PSConfig, SEQUENTIAL
from kafka_ps_tpu.utils.trace import NULL_TRACER


class StreamingPSApp:
    """One process hosting the server + N logical workers, like the
    reference's single-JVM local deployment (SURVEY §4)."""

    def __init__(self, cfg: PSConfig,
                 test_x: np.ndarray | None = None,
                 test_y: np.ndarray | None = None,
                 server_log: LogSink | None = None,
                 worker_log: LogSink | None = None,
                 clock_ms=None,
                 tracer=None,
                 fabric=None,
                 telemetry=None):
        self.tracer = tracer or NULL_TRACER
        self.telemetry = telemetry or NULL_TELEMETRY
        self.cfg = cfg
        # callers may supply a durable fabric (log/durable_fabric.py,
        # `--durable-log`); default stays the volatile in-memory one
        self.fabric = fabric or fabric_mod.Fabric(tracer=self.tracer)
        self.buffers = [
            SlidingBuffer(cfg.model.num_features, cfg.buffer,
                          clock_ms=clock_ms, telemetry=self.telemetry,
                          worker=w)
            for w in range(cfg.num_workers)]
        # deferred sinks: the per-node hot path logs device futures
        # (loss/F1/accuracy) without blocking on them — flushed when
        # ready and force-flushed at drive-loop exit (utils/asynclog)
        server_log = DeferredSink(server_log or (lambda line: None))
        worker_log = DeferredSink(worker_log or (lambda line: None))
        self.server = ServerNode(cfg, self.fabric, test_x, test_y, server_log,
                                 tracer=self.tracer,
                                 telemetry=self.telemetry)
        self.workers = [
            WorkerNode(w, cfg, self.fabric, self.buffers[w], test_x, test_y,
                       worker_log, tracer=self.tracer,
                       telemetry=self.telemetry)
            for w in range(cfg.num_workers)]
        # compressed delta transport (kafka_ps_tpu/compress/): one shared
        # weights compressor on the server, one error-feedback residual
        # per worker.  {} when --compress none — everything above runs
        # untouched (messages carry no encoded payloads).
        self.compressors: dict[int, object] = {}
        if cfg.compress and cfg.compress != "none":
            from kafka_ps_tpu import compress
            codec = compress.get_codec(compress.parse_codec(cfg.compress),
                                       self.server.task.num_params)
            self.server.compressor = compress.WeightsCompressor(codec)
            for w in self.workers:
                w.compressor = compress.ErrorFeedback(codec)
                self.compressors[w.worker_id] = w.compressor
            # residuals are worker state: in-process runs fold them into
            # the server-side checkpoint next to the buffers
            self.server.checkpoint_residuals = self.compressors
        self._stop = threading.Event()
        # fused-program cache: re-entering run_fused_bsp (resume, bench
        # trials, alternating with other drive modes) must reuse the
        # SAME jit wrappers — a fresh jax.jit(shard_map(...)) re-traces
        # the whole multi-round program every call (hundreds of ms at
        # MLP-4096) even when the XLA compile cache hits
        self._fused_programs: dict = {}
        self._reroute_counter = 0
        # durable resume: leading stream rows to drop because the log
        # already holds them (the CSV producer deterministically
        # re-produces the identical global row order, so "skip the
        # first N" is exactly-once re-ingestion; set by recover_durable)
        self._ingest_skip = 0
        self.worker_failures: list[tuple[int, BaseException | str]] = []
        # online serving plane (kafka_ps_tpu/serving/): built on demand
        # by enable_serving(); None keeps the app purely a trainer
        self.serving_engine = None
        # async coalescing eval engine (kafka_ps_tpu/evaluation/engine.py):
        # default-on when there is a test set — eval leaves the apply
        # critical path.  `--no-eval-async` keeps the fused programs.
        self.eval_engine = None
        if cfg.eval_async and test_x is not None:
            self.enable_async_eval()
        # rolling critical-path sampler, built lazily on first status()
        # heartbeat with telemetry on (telemetry/critpath.py)
        self._critpath = None
        # Multi-host: the subset of logical workers this process hosts
        # (None = all).  Every host streams the same CSV with the same
        # global round-robin, keeping only its own workers' rows — the
        # per-broker-partition analogue (parallel/multihost.py).
        self.local_workers: set[int] | None = None

    # -- ingestion sink (the INPUT_DATA topic hop) -------------------------

    def data_sink(self, worker: int, features: dict[int, float],
                  label: int) -> None:
        if self._ingest_skip > 0:
            # durable resume: this row is already in the log (and, via
            # checkpoint + replay, in a buffer) — drop the re-produced
            # copy instead of ingesting it twice
            self._ingest_skip -= 1
            self.tracer.count("data.replay_skipped_rows")
            return
        status = self.server.tracker.tracker[worker]
        if not status.active:
            # partition reassignment: rows destined for an evicted worker
            # go round-robin to the survivors (the Kafka consumer-group
            # rebalance analogue, SURVEY §5).  Reroute BEFORE the local
            # filter: every host sees the same stream and membership, so
            # the deterministic counter picks the same survivor
            # everywhere and exactly one host keeps the row.
            active = self.server.tracker.active_workers
            worker = active[self._reroute_counter % len(active)]
            self._reroute_counter += 1
            self.tracer.count("data.rerouted_rows")
        if self.local_workers is not None and worker not in self.local_workers:
            return                  # another host's partition
        if getattr(self.fabric, "durable", False):
            # the INPUT_DATA hop: log the row under its FINAL key (post
            # reroute) and mark it consumed immediately — it is applied
            # to the buffer on the next line, so the ingest group's
            # offset is the count of buffered rows
            from kafka_ps_tpu.runtime.messages import LabeledData
            offset = self.fabric.persist(
                fabric_mod.INPUT_DATA_TOPIC, worker,
                LabeledData(features=features, label=label))
            self.fabric.mark_consumed(
                fabric_mod.INPUT_DATA_TOPIC, worker, offset)
        self.buffers[worker].add(features, label)

    def make_producer(self, csv_path: str, has_header: bool = True,
                      sleep=time.sleep) -> CsvStreamProducer:
        return CsvStreamProducer(
            csv_path, self.cfg.num_workers, self.data_sink,
            time_per_event_ms=self.cfg.stream.time_per_event_ms,
            prefill_per_worker=self.cfg.stream.prefill_per_worker,
            has_header=has_header, sleep=sleep)

    def wait_for_prefill(self, min_per_worker: int = 1,
                         timeout: float = 60.0) -> None:
        """The reference sleeps 20 s after starting the producer
        (ServerAppRunner.java:95); we wait on the actual invariant."""
        deadline = time.monotonic() + timeout
        waiting = [w for w in self.server.tracker.active_workers
                   if self.local_workers is None or w in self.local_workers]
        while any(self.buffers[w].count < min_per_worker for w in waiting):
            if time.monotonic() > deadline:
                raise TimeoutError("buffers not prefilled in time")
            time.sleep(0.01)

    def wait_for_stream_settle(self, producer,
                               timeout: float = 120.0) -> None:
        """Wait until the producer's unthrottled prefill burst is done
        (prefill rows sent, stream ended, or producer stopped) before
        training starts.  Training mid-burst races each iteration's
        buffer snapshot against the tail of the burst, making early
        windows timing-dependent — the reference avoided the same race
        with a blanket 20 s sleep (ServerAppRunner.java:95).  A paced
        stream slower than `timeout` just starts training (live tail
        ingestion is the steady state, only the burst is waited out)."""
        prefill = self.cfg.num_workers * self.cfg.stream.prefill_per_worker
        deadline = time.monotonic() + timeout
        while (producer.rows_sent < prefill
               and not producer.finished.is_set()
               and not producer.stopped.is_set()):
            if time.monotonic() > deadline:
                return
            time.sleep(0.005)

    # -- durable-log recovery (log/durable_fabric.py) ----------------------

    def recover_durable(self) -> dict[str, int]:
        """Crash recovery over a durable fabric, run once AFTER the
        checkpoint restore and BEFORE the producer starts:

          * re-enqueue the unconsumed WEIGHTS / GRADIENTS tail (the
            in-flight messages the dead process held);
          * replay the unconsumed INPUT_DATA tail into the restored
            buffers (rows ingested after the last checkpoint);
          * arm the re-ingestion skip so the restarted producer drops
            the rows the log already holds.

        The replay floor is the checkpoint's recorded offsets when the
        restore found any (`server.restored_log_offsets`), else the
        durably committed ones.  Returns replay counts per topic."""
        ckpt_offsets = self.server.restored_log_offsets
        counts = self.fabric.recover(ckpt_offsets)
        replayed_rows = 0
        total_logged = 0
        for topic, key in self.fabric.manager.partitions(
                fabric_mod.INPUT_DATA_TOPIC):
            total_logged += self.fabric.manager.get(topic, key).next_offset
            for offset, row in self.fabric.replay(topic, key, ckpt_offsets):
                self.buffers[key].add(row.features, row.label)
                self.fabric.mark_consumed(topic, key, offset)
                replayed_rows += 1
        self._ingest_skip = total_logged
        counts[fabric_mod.INPUT_DATA_TOPIC] = replayed_rows
        return counts

    # -- serving plane (kafka_ps_tpu/serving/, docs/SERVING.md) ------------

    def enable_serving(self):
        """Attach the online serving plane: a SnapshotRegistry on the
        server (publish at every gate release) plus a PredictionEngine
        batching reads against it.  Sized by cfg.serving.  Idempotent;
        returns the engine."""
        if self.serving_engine is not None:
            return self.serving_engine
        from kafka_ps_tpu.serving.engine import PredictionEngine
        from kafka_ps_tpu.serving.snapshot import SnapshotRegistry
        scfg = self.cfg.serving
        registry = SnapshotRegistry(capacity=scfg.ring_capacity)
        self.server.serving = registry
        self.serving_engine = PredictionEngine(
            self.server.task, registry,
            max_batch=scfg.max_batch,
            deadline_s=scfg.deadline_ms / 1000.0,
            queue_limit=scfg.queue_limit,
            shed_deadline_s=(scfg.shed_deadline_ms / 1000.0
                             if scfg.shed_deadline_ms else None),
            auto=scfg.auto,
            tracer=self.tracer, telemetry=self.telemetry)
        return self.serving_engine

    def close_serving(self) -> None:
        """Stop the engine's batcher thread (holds jit'd callables —
        must be joined before interpreter exit, docs/TESTING.md)."""
        if self.serving_engine is not None:
            self.serving_engine.close()

    # -- async eval plane (evaluation/engine.py, docs/EVALUATION.md) -------

    def enable_async_eval(self):
        """Attach the async coalescing eval engine to the server: eval-
        cadence applies submit (theta, clock) snapshots to its bounded
        queue instead of fusing the eval, and a dedicated thread
        coalesces pending snapshots into batched vmap dispatches,
        emitting CSV rows back through `server._emit_eval` in strict
        clock order (bitwise-identical to the fused path).  Idempotent;
        returns the engine (None without a test set)."""
        if self.eval_engine is not None:
            return self.eval_engine
        if self.server.test_x is None:
            return None
        from kafka_ps_tpu.evaluation.engine import EvalEngine
        self.eval_engine = self.server.attach_eval_engine(EvalEngine(
            self.server.task, self.server.test_x, self.server.test_y,
            self.server._emit_eval,
            telemetry=self.telemetry, tracer=self.tracer))
        return self.eval_engine

    def close_eval(self) -> None:
        """Drain pending evals and join the engine thread (holds jit'd
        callables — same interpreter-exit discipline as serving)."""
        if self.eval_engine is not None:
            self.eval_engine.close()

    # -- tiered residency (kafka_ps_tpu/store/, docs/TIERING.md) -----------

    def enable_tiering(self, cold_dir: str | None = None):
        """Attach a TieredParamStore to the server per cfg.tier and
        start its policy thread.  `cold_dir` hosts the cold partition
        (required when the warm tier is capped; under --durable-log the
        CLI passes `<log-dir>/param-cold`).  No-op when both caps are 0
        — theta stays fully resident.  Returns the store (or None)."""
        if not self.cfg.tier.enabled:
            return None
        if self.server.param_store is not None:
            return self.server.param_store
        from kafka_ps_tpu.runtime.messages import KeyRange
        from kafka_ps_tpu.store import ColdStore, TieredParamStore
        tcfg = self.cfg.tier
        cold = ColdStore.open(cold_dir) if cold_dir is not None else None
        store = TieredParamStore(
            np.asarray(self.server.theta),
            KeyRange(0, self.server.task.num_params),
            hot_bytes=tcfg.hot_bytes, warm_bytes=tcfg.warm_bytes,
            page_params=tcfg.page_params, cold=cold,
            telemetry=self.telemetry,
            rebalance_interval_s=tcfg.rebalance_interval_s)
        self.server.attach_param_store(store)
        store.start_policy_thread()
        return store

    def close_tiering(self) -> None:
        """Join the policy thread and close an owned cold log."""
        if self.server.param_store is not None:
            self.server.param_store.close()

    # -- membership --------------------------------------------------------

    def readmit_worker(self, worker_id: int) -> int:
        """Elastic scale-up through the app: rejoin the worker on the
        server AND reset its compile-grace baseline so the supervisor
        grants the first post-rejoin iteration the 10x jit grace."""
        clock = self.server.readmit_worker(worker_id)
        self.workers[worker_id].iterations_at_join = \
            self.workers[worker_id].iterations
        self.workers[worker_id].last_progress = time.monotonic()
        return clock

    # -- live observability (utils/status.py) ------------------------------

    def status(self) -> dict:
        """One sample of the runtime's pulse — rendered by StatusReporter
        as the periodic `[status]` stderr line (`--status_every`)."""
        tr = self.server.tracker
        active = tr.active_workers
        out = {
            "iters": self.server.iterations,
            "clocks": [f"{w}:{tr.tracker[w].vector_clock}"
                       for w in range(self.cfg.num_workers)],
            "active": f"{len(active)}/{self.cfg.num_workers}",
            "pending": {
                "weights": self.fabric.total_pending(
                    fabric_mod.WEIGHTS_TOPIC),
                "gradients": self.fabric.total_pending(
                    fabric_mod.GRADIENTS_TOPIC)},
            "buffers": [b.count for b in self.buffers],
        }
        if self.eval_engine is not None:
            out["eval_lag"] = self.eval_engine.lag_clocks
        if self.serving_engine is not None:
            s = self.serving_engine.stats()
            # cumulative count under a *_per_s key: StatusReporter
            # renders the derived rate since the last heartbeat (QPS)
            out["predictions_per_s"] = s["requests"]
            out["serving"] = {
                "occ": s["occupancy"], "p50_ms": s["p50_ms"],
                "p99_ms": s["p99_ms"], "stale": s["rejections"]}
        if self.telemetry.enabled:
            # flattened registry heartbeat (counter totals + histogram
            # p50/n) rides the same [status] line as the runtime pulse
            out["metrics"] = self.telemetry.summary()
            # rolling critical path: per-heartbeat histogram deltas name
            # the segment dominating *this* window (telemetry/critpath)
            if self._critpath is None:
                from kafka_ps_tpu.telemetry.critpath import RollingCritpath
                self._critpath = RollingCritpath(self.telemetry)
            out["critpath"] = self._critpath.sample()
        if self.server.modelhealth.enabled:
            # model-health pulse (telemetry/modelhealth.py): update
            # norms, aggregate-direction cosine, drift verdict
            out["modelhealth"] = self.server.modelhealth.summary()
        return out

    def _start_status(self, status_every: float | None):
        from kafka_ps_tpu.utils.status import StatusReporter
        return StatusReporter(status_every or 0.0, self.status).start()

    # -- drive loops -------------------------------------------------------

    def flush_logs(self) -> None:
        """Force every deferred log line out (blocks on the device) —
        drive loops call this on exit so callers see complete logs.
        Pending async evals drain FIRST: their rows enter the server
        sink's queue before the sink itself is flushed."""
        if self.eval_engine is not None:
            self.eval_engine.drain()
        for sink in (self.server.log, *{id(w.log): w.log
                                        for w in self.workers}.values()):
            flush = getattr(sink, "flush", None)
            if flush is not None:
                flush()

    def close_logs(self) -> None:
        """Close the deferred sinks: joins their drain threads (which
        dispatch device fetches) and closes the wrapped file sinks.  The
        CLI calls this at exit so the process never finalizes with a
        live thread inside XLA (docs/TESTING.md)."""
        self.close_eval()
        for sink in (self.server.log, *{id(w.log): w.log
                                        for w in self.workers}.values()):
            close = getattr(sink, "close", None)
            if close is not None:
                close()

    def _make_gang(self):
        """The gang dispatcher for this run, or None when coalescing is
        off (`--no-gang`) — built lazily so non-gang runs never import
        runtime/gang.py."""
        if not self.cfg.use_gang:
            return None
        from kafka_ps_tpu.runtime.gang import GangDispatcher
        return GangDispatcher(self.workers, self.fabric, self.cfg,
                              tracer=self.tracer, telemetry=self.telemetry)

    def run_serial(self, max_server_iterations: int,
                   pump=None, status_every: float | None = None) -> None:
        """Deterministic scheduler: alternate weights delivery / gradient
        processing until the server has applied `max_server_iterations`
        gradient messages.  `pump()` (optional) feeds more stream rows
        between rounds.

        With gang dispatch on (the default) the schedule drains each
        release set whole: gang notices are claimed first (one batched
        worker dispatch per set), then stragglers run per-message, then
        the queued gradients are drained as one batch for the server's
        batched apply (runtime/server.process_batch).  `--no-gang` keeps
        the original strictly per-message alternation."""
        reporter = self._start_status(status_every)
        stalled_rounds = 0
        gang = self._make_gang()
        try:
            self.server.start_training_loop()
            while self.server.iterations < max_server_iterations:
                progressed = False
                if gang is not None and gang.drain_serial():
                    progressed = True
                for worker in self.workers:
                    msg = self.fabric.poll(fabric_mod.WEIGHTS_TOPIC,
                                           worker.worker_id)
                    if msg is not None:
                        worker.on_weights(msg)
                        progressed = True
                if gang is None:
                    while self.server.iterations < max_server_iterations:
                        g = self.fabric.poll(fabric_mod.GRADIENTS_TOPIC, 0)
                        if g is None:
                            break
                        self.server.process(g)
                        progressed = True
                else:
                    # drain the whole backlog, capped so a full batch
                    # cannot overshoot the iteration budget (bench runs
                    # rely on exact counts); drops (zombies/duplicates)
                    # under-fill a round and the outer loop tops it up
                    batch = []
                    while (self.server.iterations + len(batch)
                           < max_server_iterations):
                        g = self.fabric.poll(fabric_mod.GRADIENTS_TOPIC, 0)
                        if g is None:
                            break
                        batch.append(g)
                    if len(batch) > 1:
                        self.server.process_batch(batch)
                        progressed = True
                    elif batch:
                        self.server.process(batch[0])
                        progressed = True
                if pump is not None:
                    pump()
                # pump() can only add buffer rows, never fabric messages,
                # so a stretch of unprogressed rounds is a protocol
                # deadlock even with a pump attached.
                stalled_rounds = 0 if progressed else stalled_rounds + 1
                if stalled_rounds > (1000 if pump is not None else 0):
                    raise RuntimeError("deadlock: no deliverable messages")
        finally:
            reporter.stop()
            self.flush_logs()

    def run_threaded(self, max_server_iterations: int,
                     poll_timeout: float = 0.1,
                     failure_policy: str = "halt",
                     heartbeat_timeout: float | None = None,
                     status_every: float | None = None) -> None:
        """One thread per worker (the reference's stream threads); server
        on the calling thread, doubling as the supervisor.

        Failure handling (the reference delegates this to Kafka
        consumer-group rebalancing + k8s restarts, SURVEY §5):
          * `failure_policy="halt"` — any worker exception stops the run
            and re-raises (the previous behavior, and the right default
            for tests);
          * `failure_policy="rebalance"` — a crashed worker (exception)
            or a hung worker (no completed iteration within
            `heartbeat_timeout` seconds despite pending weights
            messages) is evicted: the consistency gates stop waiting for
            it, its stream partition reroutes to the survivors
            (data_sink), and its in-flight gradients are dropped as
            zombies.  Training continues on the remaining workers.
        """
        if failure_policy not in ("halt", "rebalance"):
            raise ValueError(f"unknown failure_policy {failure_policy!r}")
        self._stop.clear()
        self.worker_failures = []    # this run's eviction record

        worker_errors: list[BaseException] = []
        failed_q: deque[tuple[int, BaseException]] = deque()
        gang = self._make_gang()
        if gang is not None:
            from kafka_ps_tpu.runtime.gang import GangMemberError
        else:
            GangMemberError = ()     # never raised without a gang

        def worker_loop(worker: WorkerNode):
            try:
                while not self._stop.is_set():
                    msg = self.fabric.poll_blocking(
                        fabric_mod.WEIGHTS_TOPIC, worker.worker_id,
                        timeout=poll_timeout)
                    if msg is not None:
                        if gang is not None:
                            # first arrival covered by a gang notice
                            # leads the set; otherwise runs solo
                            gang.offer(worker, msg)
                        else:
                            worker.on_weights(msg)
            except BaseException as e:   # surface worker death to the server
                # a gang member's failure surfaces on the LEADER's thread;
                # attribute it to the member, not the messenger
                wid = (e.worker_id if isinstance(e, GangMemberError)
                       else worker.worker_id)
                if failure_policy == "rebalance":
                    failed_q.append((wid, e))
                else:
                    worker_errors.append(e)
                    self._stop.set()

        threads = {w.worker_id: threading.Thread(
                       target=worker_loop, args=(w,), daemon=True,
                       name=f"worker-{w.worker_id}")
                   for w in self.workers}
        for t in threads.values():
            t.start()

        def evict(worker_id: int, reason) -> None:
            if not self.server.tracker.tracker[worker_id].active:
                return              # already evicted (e.g. heartbeat beat
                                    # the thread's own crash report)
            try:
                self.server.remove_worker(worker_id)
            except ValueError:      # last active worker: halt instead
                self._stop.set()
                worker_errors.append(
                    reason if isinstance(reason, BaseException)
                    else RuntimeError(f"worker {worker_id}: {reason}"))
                return
            self.worker_failures.append((worker_id, reason))

        def supervise() -> None:
            # crashed workers enqueue themselves before their thread
            # exits, so failed_q is the complete crash-detection channel
            while failed_q:
                w, err = failed_q.popleft()
                evict(w, err)
            if heartbeat_timeout is None:
                return
            now = time.monotonic()
            for w in list(self.server.tracker.active_workers):
                # Hung = owes a gradient (weights_message_sent) AND the
                # owed gradient is not already queued behind a slow
                # server AND no liveness signal within the timeout.
                # Staleness is measured from the LATEST of (worker's own
                # last progress, server's weights-send stamp) so time a
                # worker spent gate-blocked and idle doesn't count
                # against it.  A worker on its first iteration SINCE
                # (re)admission gets 10x grace: that call may pay jit
                # compilation (fresh start or a new code path after
                # rejoin).  heartbeat_timeout must still exceed the
                # worst-case steady-state single-iteration compute time.
                wk = self.workers[w]
                grace = (10.0 if wk.iterations == wk.iterations_at_join
                         else 1.0)
                baseline = max(self.workers[w].last_progress,
                               self.server.weights_sent_at[w])
                hung = (self.server.tracker.tracker[w].weights_message_sent
                        and not self.fabric.contains(
                            fabric_mod.GRADIENTS_TOPIC, 0,
                            lambda m, w=w: m.worker_id == w)
                        and now - baseline > heartbeat_timeout * grace)
                if hung:
                    evict(w, f"no heartbeat for {heartbeat_timeout}s")

        reporter = self._start_status(status_every)
        try:
            self.server.start_training_loop()
            while self.server.iterations < max_server_iterations:
                if self._stop.is_set():
                    break
                g = self.fabric.poll_blocking(fabric_mod.GRADIENTS_TOPIC, 0,
                                              timeout=poll_timeout)
                if g is not None:
                    if gang is None:
                        self.server.process(g)
                    else:
                        # piggyback whatever else is already queued onto
                        # this wake-up: one batched apply instead of one
                        # apply per gradient (no waiting — only messages
                        # that have ALREADY arrived join the batch)
                        batch = [g]
                        while (self.server.iterations + len(batch)
                               < max_server_iterations):
                            g2 = self.fabric.poll(
                                fabric_mod.GRADIENTS_TOPIC, 0)
                            if g2 is None:
                                break
                            batch.append(g2)
                        if len(batch) > 1:
                            self.server.process_batch(batch)
                        else:
                            self.server.process(g)
                if failure_policy == "rebalance":
                    supervise()
        finally:
            reporter.stop()
            self._stop.set()
            # generous: an in-flight on_weights may be paying first-call
            # jit compilation on a loaded machine (the 5 s join of
            # rounds 2-4 could expire and leave the thread running)
            for t in threads.values():
                t.join(timeout=60.0)
            self.flush_logs()
        if worker_errors:
            raise RuntimeError("worker thread failed") from worker_errors[0]

    def run_fused_bsp(self, max_server_iterations: int, mesh=None,
                      log_metrics: bool = True,
                      status_every: float | None = None) -> None:
        """Sequential consistency as fused shard_map steps.  Each step is
        one full BSP iteration (all workers advance one clock).

        A 2-D mesh (workers x params axes, parallel/mesh.worker_param_mesh)
        selects the range-sharded server: parameters sharded over the
        params axis (the reference's latent KeyRange design,
        messages/KeyRange.java), all_gather pull / psum-slice push
        (parallel/range_sharded.py).  Single-process only.
        """
        import jax
        import jax.numpy as jnp

        from kafka_ps_tpu.parallel import range_sharded
        from kafka_ps_tpu.parallel.mesh import PARAM_AXIS

        if self.cfg.consistency_model != SEQUENTIAL:
            raise ValueError("fused path implements the sequential model only")
        range_mode = mesh is not None and PARAM_AXIS in mesh.shape
        if range_mode and jax.process_count() > 1:
            raise ValueError(
                "range-sharded fused mode is single-process (the params "
                "axis would need a per-host theta-shard assembly)")
        # membership-aware: only active workers participate (a restored
        # checkpoint may carry evictions; their buffers are starved by
        # the data reroute and their tracker slots must stay frozen)
        active = self.server.tracker.active_workers
        task = self.server.task
        progs = self._fused_programs.setdefault(
            ("range" if range_mode else "bsp", len(active), mesh), {})
        if range_mode:
            if "step" not in progs:
                progs["step"] = range_sharded.make_range_sharded_step(
                    self.cfg.model, len(active), self.cfg.server_lr, mesh,
                    task=task)
            theta = range_sharded.shard_theta(
                mesh, jnp.asarray(self.server.theta), task)
        else:
            if "step" not in progs:
                progs["step"] = bsp.make_bsp_step(
                    self.cfg.model, len(active), self.cfg.server_lr,
                    mesh=mesh, task=task)
            theta = jnp.asarray(self.server.theta)
        step = progs["step"]
        # under BSP all active clocks are uniform; resume from the
        # restored one
        clock = min(self.server.tracker.clocks[w] for w in active)
        # Multi-process job: this process hosts only the workers mapped
        # to its local mesh devices — it feeds their buffers and builds
        # the global arrays from its local slabs
        # (jax.make_array_from_process_local_data); the device program
        # is identical either way.
        multiproc = mesh is not None and jax.process_count() > 1
        if multiproc:
            from kafka_ps_tpu.parallel import multihost
            local_pos = multihost.local_worker_ids(len(active), mesh)
            feed = [active[i] for i in local_pos]
            # the data filter (set by the CLI before the producer
            # started) must match this derivation — a stale filter from
            # pre-restore membership starves buffers this process owns
            if (self.local_workers is not None
                    and set(feed) != set(self.local_workers)):
                raise RuntimeError(
                    f"local_workers {sorted(self.local_workers)} diverges "
                    f"from the mesh-derived feed set {sorted(feed)} — "
                    "membership changed after the data filter was set")
        else:
            feed = active
        # device-resident slab cache: between stream arrivals the loop
        # re-trains on identical buffers (the reference's steady state,
        # WorkerTrainingProcessor.java:63-97) — re-uploading ~16 MB of
        # unchanged slabs per iteration would make host->device transfer
        # the bottleneck.  num_tuples_seen strictly increases on every
        # insert, so it is the buffer content version.
        reporter = self._start_status(status_every)
        try:
            self._run_fused_loop(max_server_iterations, mesh, log_metrics,
                                 range_mode, multiproc, step, theta, clock,
                                 active, feed, task, progs)
        finally:
            reporter.stop()

    # rounds per fused chunk dispatch: big enough to amortize the
    # per-dispatch host latency (~tens of ms over a tunneled transport),
    # small enough that stream arrivals are picked up promptly
    FUSED_CHUNK_ROUNDS = 8

    def _run_fused_loop(self, max_server_iterations, mesh, log_metrics,
                        range_mode, multiproc, step, theta, clock, active,
                        feed, task, progs) -> None:
        import jax
        import jax.numpy as jnp

        from kafka_ps_tpu.parallel import range_sharded

        # Chunking: stretches with no eval boundary run CHUNK rounds as
        # ONE lax.scan dispatch (bsp.make_bsp_multi_step /
        # range_sharded.make_range_sharded_step(rounds=CHUNK)) — without
        # it the runtime pays a full dispatch round-trip per round and
        # falls to ~1/4 of the kernel rate at MLP-4096 (BENCH r5; the
        # "framework adds no overhead that survives scale" claim,
        # docs/ROOFLINE.md).  Eval cadences land exactly: a chunk never
        # crosses an eval clock, and eval_every=1 degenerates to the
        # per-round path.
        CHUNK = self.FUSED_CHUNK_ROUNDS

        def get_multi_step():
            if "multi_step" not in progs:
                if range_mode:
                    progs["multi_step"] = \
                        range_sharded.make_range_sharded_step(
                            self.cfg.model, len(active),
                            self.cfg.server_lr, mesh, rounds=CHUNK,
                            task=task)
                else:
                    progs["multi_step"] = bsp.make_bsp_multi_step(
                        self.cfg.model, len(active), self.cfg.server_lr,
                        CHUNK, mesh=mesh, task=task)
            return progs["multi_step"]

        x = y = mask = None
        slab_versions: list[int] | None = None
        while self.server.iterations < max_server_iterations:
            versions = [self.buffers[w].num_tuples_seen for w in feed]
            # The version cache stays valid multi-process: the global
            # array build below (make_array_from_process_local_data) is
            # process-local — device_put of this host's shards only, no
            # cross-process rendezvous — so hosts may disagree about
            # re-uploading without hanging, and a host whose buffers are
            # unchanged reuses device slabs with identical content.
            if versions != slab_versions:
                slabs = []
                for w in feed:
                    sx, sy, sm = self.buffers[w].snapshot()
                    if sm.sum() == 0:
                        raise RuntimeError(
                            f"There is no data in the buffer of worker {w}")
                    slabs.append((sx, sy, sm))
                x = np.stack([s[0] for s in slabs])
                y = np.stack([s[1] for s in slabs])
                mask = np.stack([s[2] for s in slabs])
                if multiproc:
                    from kafka_ps_tpu.parallel import multihost
                    x, y, mask = multihost.shard_worker_batches_global(
                        mesh, x, y, mask)
                elif range_mode:
                    x, y, mask = range_sharded.shard_worker_batches(
                        mesh, x, y, mask)
                elif mesh is not None:
                    x, y, mask = bsp.shard_worker_batches(mesh, x, y, mask)
                else:
                    x, y, mask = (jnp.asarray(x), jnp.asarray(y),
                                  jnp.asarray(mask))
                slab_versions = versions
            # rounds until the run cap / the next eval clock
            rounds_left = -((self.server.iterations - max_server_iterations)
                            // len(active))
            r = min(CHUNK, rounds_left)
            if log_metrics and self.server.test_x is not None:
                r = min(r, self.cfg.eval_every
                        - (clock % self.cfg.eval_every))
            use_chunk = r == CHUNK
            if not use_chunk:
                r = 1
            losses = None
            with self.tracer.span("bsp.step", clock=clock + 1, rounds=r):
                if use_chunk:
                    theta, losses = get_multi_step()(theta, x, y, mask)
                    mean_loss = losses[-1]
                else:
                    theta, mean_loss = step(theta, x, y, mask)
                if self.tracer.enabled or (multiproc and log_metrics):
                    # sync so the span measures the real step, not the
                    # async dispatch.  Multi-process runs with logging
                    # ALSO sync here: the psum makes every process's
                    # step k finish together on device, and blocking
                    # the hosts on it keeps their row timestamps
                    # aligned per clock — fully async hosts submit all
                    # their rows (and stamp them) way ahead of the
                    # device, and the auditor's cross-file
                    # timestamp-sorted spread becomes fiction.
                    # Untraced single-process runs keep pipelining.
                    mean_loss = float(mean_loss)
            self.tracer.count("bsp.steps")
            clock += r
            self.server.iterations += r * len(active)
            # theta is updated by replacement everywhere (runtime/server
            # module doc), so the device array is stored directly — no
            # per-step device->host copy
            if range_mode:
                self.server.theta = range_sharded.unshard_theta(theta, task)
            else:
                self.server.theta = theta
            for w in active:
                self.workers[w].iterations += r
                self.server.tracker.tracker[w].vector_clock = clock
                self.server.tracker.tracker[w].weights_message_sent = True
            # fused-path publication point: the chunk boundary is the
            # gate release (all active workers advanced to `clock`)
            self.server.publish_snapshot()
            self.server.maybe_checkpoint()
            if log_metrics and self.server.test_x is not None:
                is_eval = clock % self.cfg.eval_every == 0
                m = None
                if is_eval:
                    # range mode: theta is the padded sharded vector;
                    # eval on the reassembled flat layout (just stored)
                    eval_theta = (jnp.asarray(self.server.theta)
                                  if range_mode else theta)
                    m = self.server.task.evaluate(
                        eval_theta, self.server.test_x, self.server.test_y)
                    self.server.last_metrics = m
                now = int(time.time() * 1000)
                # multi-process: the server line is process 0's alone
                # (identical replicated metrics; one writer per file).
                # Metric fields stay device futures (asynclog) so the
                # next chunk dispatches while the eval completes.
                if is_eval and (not multiproc or jax.process_index() == 0):
                    asynclog.submit_or_write(
                        self.server.log, f"{now};-1;{clock};{{}};{{}};{{}}",
                        m.loss, m.f1, m.accuracy)
                # Worker log lines, same schema AND CADENCE as the
                # per-node path (WorkerTrainingProcessor.java:85-92):
                # one row per worker per CLOCK — off-cadence clocks log
                # the reference's -1 placeholders, eval clocks the
                # shared test metrics (identical across workers under
                # BSP — replicated weights).  Rows go out CLOCK-major
                # so a same-millisecond batch keeps the logged spread
                # within the BSP bound (the staleness auditor orders
                # ties by file order).  A chunk logs each of its r
                # rounds with that round's mean local loss.  Each
                # process logs only the workers it hosts (its sink path
                # is process-suffixed in multi-host mode, cli/run.py).
                # Log-schema caveat: numTuplesSeen is CHUNK-granular
                # here, not round-granular — all r rows of a chunk stamp
                # the buffer version sampled after the chunk dispatch,
                # because the per-round values no longer exist (the
                # rounds ran fused on device against one slab snapshot).
                # The per-node path stamps it per iteration; consumers
                # correlating loss against data volume should treat the
                # fused path's column as a step function with CHUNK-wide
                # treads.
                for i in range(r):
                    ci = clock - r + 1 + i
                    round_loss = (losses[i] if losses is not None
                                  else mean_loss)
                    ci_eval = is_eval and ci == clock
                    f1 = m.f1 if ci_eval else -1.0
                    acc = m.accuracy if ci_eval else -1.0
                    for w in feed:
                        asynclog.submit_or_write(
                            self.workers[w].log,
                            f"{now};{w};{ci};{{}};{{}};{{}};"
                            f"{self.buffers[w].num_tuples_seen}",
                            round_loss, f1, acc)
        self.flush_logs()    # deferred rows out before the loop returns

    def stop(self) -> None:
        self._stop.set()
