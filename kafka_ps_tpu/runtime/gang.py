"""Gang-scheduled dispatch — coalesce simultaneous gate releases into
one batched XLA step on the per-node path (docs/GANG_DISPATCH.md).

The consistency gate routinely releases several workers at the same
moment: ALL of them under sequential (BSP), a subset under bounded
delay whenever the slowest worker catches up, every active worker at
bootstrap.  The per-message path pays one `update_and_eval` dispatch
per released worker; over a tunneled transport each dispatch is a host
round-trip, which is what bounds the measured per-node rate (BENCH_r05
148.5 iters/s at eval cadence 1).  This is the classic parameter-server
batching lever (Li et al., OSDI'14); under bounded staleness the sets
that coalesce are exactly the SSP release sets of Ho et al. (NIPS'13).

A `GangDispatcher` claims a release set (advertised by the server's
advisory `GangNotice` on GANG_TOPIC alongside the per-worker messages),
runs `_prepare` on every member (each keeps its private buffer slab and
`num_tuples_seen` version), stacks the member slabs, and runs ONE
vmapped solver dispatch over the (k, …) batch — theta broadcasts when
the set shares one weights array (sequential consistency: the server
aliases the same device theta into every member's message), stacks
otherwise (bounded/eventual sets with differing clocks).  The k deltas
and metric futures are unstacked INSIDE the jit (one dispatch, k
buffers out), then `_finish` runs per member in worker-id order — the
same per-worker CSV rows and the same per-worker GradientMessages, in
the same order, as the per-message path.  Bitwise equivalence with the
per-message path is a tested invariant (tests/test_gang.py), not an
approximation: vmap runs the identical per-element program.

Threaded mode coalesces by first arrival: the thread that pops a
weights message covered by a notice becomes the gang leader and polls
the fabric for siblings already enqueued — no timer sleeps on the hot
path.  Members whose threads beat the leader to their own messages
simply run solo there; a gang is an optimization, never a barrier.

Range sharding (runtime/sharding.py): every shard's gate computes the
identical release sets in lockstep (same gradients, same clocks), so
only SHARD 0 forwards its GangNotice — N notices for one release
moment would be noise — and the worker-side claim fires once the
assembler has synthesized the full-range weights at the common clock.
Server-side, gang applies coalesce per shard (each shard's
process_batch chains its own slice applies); there is no cross-shard
barrier in the dispatch path.

Aggregation tier (kafka_ps_tpu/agg/, docs/AGGREGATION.md): a composite
release counts as its MEMBER SET, not as one event — when the gate
applies a CompositeDelta (or flushes a BSP round buffer) the released
workers it unblocks form a single release set and emit ONE GangNotice
covering every member, exactly as if the per-member deltas had arrived
back to back; `gang.batched_members` therefore accounts fan-in
correctly under aggregation with no special casing here.  The relay's
grouped weights fan-out (T_WEIGHTS_AGG) is invisible to this module:
by the time a member worker polls its weights message the relay has
already expanded the group into per-worker frames with re-stamped
clocks, so notice claiming matches on (worker, clock) as always.
"""

from __future__ import annotations

import functools

from kafka_ps_tpu.analysis.lockgraph import OrderedLock
from kafka_ps_tpu.runtime import fabric as fabric_mod
from kafka_ps_tpu.runtime import worker as worker_mod
from kafka_ps_tpu.utils.trace import NULL_TRACER


class GangMemberError(RuntimeError):
    """A gang member failed inside another worker's thread — carries the
    member's id so the threaded supervisor evicts the right worker."""

    def __init__(self, worker_id: int, cause: BaseException):
        super().__init__(f"gang member {worker_id} failed: {cause!r}")
        self.worker_id = worker_id


@functools.lru_cache(maxsize=None)
def _gang_solver_fns(task_name: str, cfg, use_pallas: bool,
                     grid: bool = True):
    """Batched counterparts of worker._solver_fns, one compile per
    (task, cfg, member-count) — four jit'd entry points over TUPLES of
    per-member arrays (stacked inside the jit, so stacking costs no
    extra dispatch; unstacked inside the jit, so fan-out costs none
    either):

      update_stacked(thetas, xs, ys, masks)
      update_bcast(theta, xs, ys, masks)            # shared theta
      update_eval_stacked(thetas, xs, ys, masks, test_x, test_y)
      update_eval_bcast(theta, xs, ys, masks, test_x, test_y)

    The non-pallas variants vmap the SAME composite function the
    single-dispatch path jits (vmap preserves per-element semantics —
    the bitwise-equivalence test in tests/test_gang.py is the
    contract).  With use_pallas the solver goes through the batched
    grid kernels (ops/fused_update.*_batched, grid over the worker
    axis); `grid=False` selects the vmap-of-kernel fallback for
    backends where the grid variant is unsupported."""
    import jax
    import jax.numpy as jnp

    from kafka_ps_tpu.models.task import get_task
    task = get_task(task_name, cfg)

    if use_pallas:
        from kafka_ps_tpu.ops import fused_update
        single = {"logreg": fused_update.local_update,
                  "mlp": fused_update.mlp_local_update}[task_name]
        if grid:
            batched = {"logreg": fused_update.local_update_batched,
                       "mlp": fused_update.mlp_local_update_batched
                       }[task_name]

            def solver_b(thetas, xs, ys, masks):
                return batched(thetas, xs, ys, masks, cfg=cfg)
        else:
            solver_b = jax.vmap(
                lambda t, x, y, m: single(t, x, y, m, cfg=cfg))

        def solver_1(theta, x, y, mask):
            return single(theta, x, y, mask, cfg=cfg)
    else:
        solver_1 = task.local_update
        solver_b = jax.vmap(solver_1)

    # the exact composite the single path jits (worker._solver_fns):
    # k-step solver + full-test-set eval of theta+delta, one program
    def composite(theta, x, y, mask, test_x, test_y):
        delta, loss = solver_1(theta, x, y, mask)
        m = task.evaluate(theta + delta, test_x, test_y)
        return delta, loss, m.f1, m.accuracy

    def unstack(a, k):
        return tuple(a[i] for i in range(k))

    def tstack(items):
        # componentwise stack: identical to jnp.stack for plain member
        # slabs, and stacks QuantizedSlab (int8 slab storage,
        # compress/slab.py) field-by-field — vmap then maps over the
        # leading axis of every leaf, preserving per-element semantics
        return jax.tree.map(lambda *leaves: jnp.stack(leaves), *items)

    @jax.jit
    def update_stacked(thetas, xs, ys, masks):
        k = len(xs)
        deltas, losses = solver_b(jnp.stack(thetas), tstack(xs),
                                  jnp.stack(ys), jnp.stack(masks))
        return unstack(deltas, k), unstack(losses, k)

    @jax.jit
    def update_bcast(theta, xs, ys, masks):
        k = len(xs)
        if use_pallas:
            thetas = jnp.broadcast_to(theta[None], (k,) + theta.shape)
            deltas, losses = solver_b(thetas, tstack(xs),
                                      jnp.stack(ys), jnp.stack(masks))
        else:
            deltas, losses = jax.vmap(solver_1, in_axes=(None, 0, 0, 0))(
                theta, tstack(xs), jnp.stack(ys), jnp.stack(masks))
        return unstack(deltas, k), unstack(losses, k)

    @jax.jit
    def update_eval_stacked(thetas, xs, ys, masks, test_x, test_y):
        k = len(xs)
        T = jnp.stack(thetas)
        X, Y, M = tstack(xs), jnp.stack(ys), jnp.stack(masks)
        if use_pallas:
            deltas, losses = solver_b(T, X, Y, M)
            met = jax.vmap(lambda t, d: task.evaluate(t + d, test_x,
                                                      test_y))(T, deltas)
            f1s, accs = met.f1, met.accuracy
        else:
            deltas, losses, f1s, accs = jax.vmap(
                composite, in_axes=(0, 0, 0, 0, None, None))(
                    T, X, Y, M, test_x, test_y)
        return (unstack(deltas, k), unstack(losses, k),
                unstack(f1s, k), unstack(accs, k))

    @jax.jit
    def update_eval_bcast(theta, xs, ys, masks, test_x, test_y):
        k = len(xs)
        X, Y, M = tstack(xs), jnp.stack(ys), jnp.stack(masks)
        if use_pallas:
            thetas = jnp.broadcast_to(theta[None], (k,) + theta.shape)
            deltas, losses = solver_b(thetas, X, Y, M)
            met = jax.vmap(lambda t, d: task.evaluate(t + d, test_x,
                                                      test_y)
                           )(thetas, deltas)
            f1s, accs = met.f1, met.accuracy
        else:
            deltas, losses, f1s, accs = jax.vmap(
                composite, in_axes=(None, 0, 0, 0, None, None))(
                    theta, X, Y, M, test_x, test_y)
        return (unstack(deltas, k), unstack(losses, k),
                unstack(f1s, k), unstack(accs, k))

    return {"update_stacked": update_stacked,
            "update_bcast": update_bcast,
            "update_eval_stacked": update_eval_stacked,
            "update_eval_bcast": update_eval_bcast}


def _gangable(worker) -> bool:
    """A worker whose `on_weights` has been overridden on the INSTANCE
    (test fault injectors, wrapper hooks) must keep the per-message
    entry point — the gang's `_prepare`/`_finish` split would silently
    bypass the wrapper.  Such workers are never claimed into a gang;
    their messages stay queued for the normal single-dispatch path."""
    return "on_weights" not in vars(worker)


class GangDispatcher:
    """Claims release sets and runs them as batched dispatches.

    Serial drive: `drain_serial()` pops each GangNotice, claims every
    member's weights message, and dispatches the whole set — fully
    deterministic.  Threaded drive: worker threads route messages
    through `offer()`; the first arrival covered by a notice leads the
    gang, claiming only siblings ALREADY enqueued (non-blocking polls,
    no sleeps — latecomers run solo on their own threads)."""

    def __init__(self, workers, fabric, cfg, tracer=None, telemetry=None):
        self.workers = {w.worker_id: w for w in workers}
        self.fabric = fabric
        self.cfg = cfg
        self.tracer = tracer or NULL_TRACER
        from kafka_ps_tpu.telemetry import NULL_TELEMETRY
        self.telemetry = telemetry or NULL_TELEMETRY
        self._m_dispatches = self.telemetry.counter("gang_dispatches_total")
        self._m_members = self.telemetry.counter("gang_members_total")
        self._offer_lock = OrderedLock("GangDispatcher.offer")
        # (worker_id, clock) -> the full member tuple of its notice
        self._notices: dict[tuple[int, int], tuple] = {}
        # error-feedback compression needs crash-recovery replay to
        # re-run the EXACT device programs the live run dispatched; a
        # recovery claim can merge releases the live run dispatched
        # separately (the restarted gate re-fires them inside one
        # batched apply), so compressed runs group members by clock —
        # one dispatch per release set — instead of letting a single
        # stacked program span clocks
        self._per_clock = bool(getattr(cfg, "compress", "none")
                               not in (None, "", "none"))
        # grid pallas batching fell over at runtime -> vmap-of-kernel
        self._grid = True

    # -- drive-loop entries ------------------------------------------------

    def drain_serial(self) -> bool:
        """Consume every queued gang notice, claiming each release set
        whole (the serial loop drains the set before dispatching).
        Returns True if any dispatch ran."""
        progressed = False
        while True:
            notice = self.fabric.poll(fabric_mod.GANG_TOPIC, 0)
            if notice is None:
                return progressed
            members = []
            for w, _ in notice.members:
                if not _gangable(self.workers[w]):
                    continue    # left queued for the per-message loop
                msg = self.fabric.poll(fabric_mod.WEIGHTS_TOPIC, w)
                if msg is None:
                    continue
                if self.workers[w]._redelivered_weights(msg):
                    continue    # recovery duplicate: cached resend only
                members.append((self.workers[w], msg))
            if not members:
                continue            # set already consumed elsewhere
            if len(members) == 1:
                members[0][0].on_weights(members[0][1])
            else:
                self.dispatch(members)
            progressed = True

    def offer(self, worker, msg) -> None:
        """Threaded entry: first-arrival leadership.  The calling thread
        pops the notice covering (worker, clock) — if there is one — and
        claims siblings' weights messages still sitting in the fabric.
        Members whose threads already popped their own message run solo
        there (their notice entry is dropped so they cannot re-claim a
        stale set).  All bookkeeping is non-blocking under one lock; the
        batched dispatch itself runs outside it."""
        if not _gangable(worker):
            worker.on_weights(msg)
            return
        if worker._redelivered_weights(msg):
            return              # recovery duplicate: cached resend only
        with self._offer_lock:
            self._refresh_notices()
            # entries superseded by this worker's own progress can never
            # match again — drop them so the map stays bounded
            for kc in [kc for kc in self._notices
                       if kc[0] == worker.worker_id
                       and kc[1] < msg.vector_clock]:
                del self._notices[kc]
            spec = self._notices.pop((worker.worker_id, msg.vector_clock),
                                     None)
            members = None
            if spec is not None:
                members = [(worker, msg)]
                for w, _ in spec:
                    if w == worker.worker_id:
                        continue
                    if not _gangable(self.workers[w]):
                        continue    # its own thread delivers per-message
                    sib = self.fabric.poll(fabric_mod.WEIGHTS_TOPIC, w)
                    if sib is None:
                        continue
                    if self.workers[w]._redelivered_weights(sib):
                        continue    # recovery duplicate: cached resend
                    members.append((self.workers[w], sib))
                for w, c in spec:   # claimed: latecomers run solo
                    self._notices.pop((w, c), None)
        if members is None or len(members) == 1:
            worker.on_weights(msg)
        else:
            self.dispatch(members)

    def _refresh_notices(self) -> None:
        while True:
            notice = self.fabric.poll(fabric_mod.GANG_TOPIC, 0)
            if notice is None:
                return
            for member in notice.members:
                self._notices[member] = notice.members

    # -- the batched step --------------------------------------------------

    def dispatch(self, members) -> None:
        """One batched device step for a claimed release set, preserving
        per-message semantics exactly: members sort by worker id (the
        serial per-message processing order), `_prepare`/`_finish` are
        the worker's own halves, and the solver runs the same
        per-element program vmapped.  Mixed eval cadence (bounded-delay
        sets span clocks) partitions into at most one eval and one
        non-eval dispatch; a partition of one keeps the single-dispatch
        path.  Partial-range messages (range sharding) cannot stack —
        the whole set degrades to per-message processing."""
        members = sorted(members, key=lambda wm: wm[0].worker_id)
        if any(m.key_range.start != 0
               or m.key_range.end != w.task.num_params
               for w, m in members):
            for w, m in members:
                w.on_weights(m)
            return

        failures: list[GangMemberError] = []
        prepared = []
        for w, m in members:
            try:
                prepared.append((w, m) + tuple(w._prepare(m)))
            except BaseException as e:   # the healthy members still run
                failures.append(GangMemberError(w.worker_id, e))
        results: dict[int, tuple] = {}
        if self._per_clock:
            grouped: dict[tuple, list] = {}
            for p in prepared:
                grouped.setdefault((p[7], p[1].vector_clock),
                                   []).append(p)
            for (with_eval, _), grp in grouped.items():
                self._dispatch_group(grp, with_eval, results)
        else:
            eval_grp = [p for p in prepared if p[7]]
            noeval_grp = [p for p in prepared if not p[7]]
            for grp, with_eval in ((eval_grp, True), (noeval_grp, False)):
                if grp:
                    self._dispatch_group(grp, with_eval, results)
        # _finish in member order: CSV rows and GradientMessages hit
        # their queues in exactly the per-message order
        for p in prepared:
            w, msg, _, _, _, _, seen, _ = p
            w._finish(msg, seen,
                      *results[(w.worker_id, msg.vector_clock)])
        if failures:
            raise failures[0]

    def _dispatch_group(self, grp, with_eval: bool, results: dict) -> None:
        k = len(grp)
        if k == 1:
            w, msg, theta, x, y, mask, _, _ = grp[0]
            update_fn, update_eval_fn = worker_mod._solver_fns(
                self.cfg.task, self.cfg.model, self.cfg.use_pallas)
            with self.tracer.span("worker.local_update",
                                  worker=w.worker_id,
                                  clock=msg.vector_clock):
                if with_eval:
                    delta, loss, f1, acc = update_eval_fn(
                        theta, x, y, mask, w.test_x, w.test_y)
                else:
                    delta, loss = update_fn(theta, x, y, mask)
                    f1 = acc = -1.0
            self.tracer.count("dispatch.device")
            results[(w.worker_id, msg.vector_clock)] = (delta, loss,
                                                        f1, acc)
            return

        thetas = [p[2] for p in grp]
        xs = tuple(p[3] for p in grp)
        ys = tuple(p[4] for p in grp)
        masks = tuple(p[5] for p in grp)
        # sequential release sets alias ONE server theta into every
        # member message (server._weights_message), so identity — not a
        # device-side compare — detects the broadcast case
        shared = all(t is thetas[0] for t in thetas)
        lead = grp[0][0]

        def run(fns):
            if with_eval:
                if shared:
                    return fns["update_eval_bcast"](
                        thetas[0], xs, ys, masks, lead.test_x, lead.test_y)
                return fns["update_eval_stacked"](
                    tuple(thetas), xs, ys, masks, lead.test_x, lead.test_y)
            if shared:
                return fns["update_bcast"](thetas[0], xs, ys, masks)
            return fns["update_stacked"](tuple(thetas), xs, ys, masks)

        # same span name as the per-message path — one entry now covers
        # k members (the `gang` arg distinguishes the two in traces)
        with self.tracer.span("worker.local_update", gang=k,
                              workers=[p[0].worker_id for p in grp]):
            try:
                out = run(_gang_solver_fns(self.cfg.task, self.cfg.model,
                                           self.cfg.use_pallas,
                                           grid=self._grid))
            except Exception:
                if not (self.cfg.use_pallas and self._grid):
                    raise
                # grid-over-worker-axis pallas unsupported here: fall
                # back to vmap-of-kernel, once, and stay there
                self._grid = False
                out = run(_gang_solver_fns(self.cfg.task, self.cfg.model,
                                           self.cfg.use_pallas,
                                           grid=False))
        self.tracer.count("dispatch.device")
        self.tracer.count("gang.batched_dispatches")
        self.tracer.count("gang.batched_members", k)
        if self.telemetry.enabled:
            self._m_dispatches.inc()
            self._m_members.inc(k)
        if with_eval:
            deltas, losses, f1s, accs = out
        else:
            deltas, losses = out
            f1s = accs = (-1.0,) * k
        # keyed by (worker, clock): a recovery claim can hold TWO
        # messages for one worker (a merged notice spanning releases),
        # and each one's result must reach its own _finish
        for p, d, loss, f1, a in zip(grp, deltas, losses, f1s, accs):
            results[(p[0].worker_id, p[1].vector_clock)] = (d, loss, f1, a)
