"""Worker compute node — behavioral re-design of WorkerTrainingProcessor
(processors/WorkerTrainingProcessor.java:24-138).

On each WeightsMessage: overwrite local parameters with the server's,
snapshot the worker's sliding buffer (a static-shape masked slab — no
per-row range scan), run the jit'd k-step local update on device, log
the worker CSV line, and send the delta back as a GradientMessage with
the same vector clock on the gather topic.

Device-resident hot path (VERDICT r2 weak #6): the iteration performs
NO host synchronization — theta and the delta stay jax arrays end to
end (the in-process fabric carries device arrays; serde fetches only at
a socket boundary), the buffer slab is cached on device and re-uploaded
only when `num_tuples_seen` changes, and the log line's loss/F1/
accuracy are deferred futures (utils/asynclog.DeferredSink) so the
evaluation of iteration t overlaps the training of t+1 instead of
blocking it.

The reference's empty-buffer invariant (IllegalStateException,
WorkerTrainingProcessor.java:131-133) is preserved as RuntimeError.
"""

from __future__ import annotations

import functools
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from kafka_ps_tpu.compress import slab as slab_mod
from kafka_ps_tpu.data.buffer import SlidingBuffer
from kafka_ps_tpu.runtime import fabric as fabric_mod
from kafka_ps_tpu.runtime.messages import GradientMessage, KeyRange, WeightsMessage
from kafka_ps_tpu.telemetry import NULL_MODEL_HEALTH, NULL_TELEMETRY
from kafka_ps_tpu.utils import asynclog
from kafka_ps_tpu.utils.config import PSConfig
from kafka_ps_tpu.utils.trace import NULL_TRACER

LogSink = Callable[[str], None]

@functools.lru_cache(maxsize=None)
def _solver_fns(task_name: str, cfg, use_pallas: bool):
    """One compiled program per (task, cfg) — shared by every WorkerNode
    so N logical workers pay one trace/compile, not N.

    Returns (update, update_and_eval).  The fused variant runs the
    k-step local solver AND the full-test-set evaluation of theta+delta
    as ONE dispatch: on a tunneled transport each dispatch costs a host
    round-trip, and the old 3-dispatch iteration (update, theta+delta,
    evaluate) capped the per-node path at ~11 iters/s (VERDICT r4
    weak #2).  Metric semantics are unchanged — each worker still
    evaluates its own post-fit model, like the reference's in-iteration
    eval (LogisticRegressionTaskSpark.java:186)."""
    from kafka_ps_tpu.models.task import get_task
    task = get_task(task_name, cfg)
    if use_pallas:
        from kafka_ps_tpu.ops import fused_update
        kernel = {"logreg": fused_update.local_update,
                  "mlp": fused_update.mlp_local_update}[task_name]

        def update_fn(theta, x, y, mask):
            return kernel(theta, x, y, mask, cfg=cfg)
    else:
        update_fn = task.local_update

    def update_and_eval(theta, x, y, mask, test_x, test_y):
        delta, loss = update_fn(theta, x, y, mask)
        m = task.evaluate(theta + delta, test_x, test_y)
        return delta, loss, m.f1, m.accuracy

    return jax.jit(update_fn), jax.jit(update_and_eval)


class WorkerNode:
    """One logical worker: private buffer + full model replica + jit'd
    local solver."""

    def __init__(self, worker_id: int, cfg: PSConfig, fabric: fabric_mod.Fabric,
                 buffer: SlidingBuffer,
                 test_x: np.ndarray | None = None,
                 test_y: np.ndarray | None = None,
                 log: LogSink | None = None,
                 tracer=None, telemetry=None):
        self.tracer = tracer or NULL_TRACER
        self.telemetry = telemetry or NULL_TELEMETRY
        # pre-resolved children: one leaf-lock inc / observe per
        # iteration when telemetry is on, nothing when off
        self._m_updates = self.telemetry.counter(
            "worker_updates_total", worker=str(worker_id))
        self._m_update_ms = self.telemetry.histogram(
            "worker_update_ms", worker=str(worker_id))
        # model-health plane (telemetry/modelhealth.py): in split mode
        # each worker process runs its own plane over its local
        # training rows — set by the CLI wiring when --model-health
        self.modelhealth = NULL_MODEL_HEALTH
        self.worker_id = worker_id
        self.cfg = cfg
        self.fabric = fabric
        self.buffer = buffer
        from kafka_ps_tpu.models.task import get_task
        self.task = get_task(cfg.task, cfg.model)
        if cfg.use_pallas and cfg.task not in ("logreg", "mlp"):
            raise ValueError(
                "use_pallas implements the logreg and mlp local updates "
                f"(ops/fused_update.py), got task {cfg.task!r}")
        self.theta = np.zeros((self.task.num_params,), dtype=np.float32)
        self.test_x = jnp.asarray(test_x) if test_x is not None else None
        self.test_y = jnp.asarray(test_y) if test_y is not None else None
        self.log = log or (lambda line: None)
        # Device-resident slab (compress/slab.SlabStore,
        # docs/PERFORMANCE.md): the buffer slab lives on device in
        # cfg.slab_dtype storage, keyed by the buffer's mutation
        # counter.  Steady state uploads only the dirty rows
        # (O(changed rows) bytes) via a jit'd scatter; the full
        # re-upload remains the bootstrap/restore/mass-churn fallback.
        self._slab_version: int | None = None
        self._slab_store = slab_mod.SlabStore(
            cfg.slab_dtype, buffer.cfg.max_size, buffer.num_features,
            telemetry=self.telemetry)
        self.iterations = 0
        # iterations counted at (re)admission: the supervisor grants the
        # jit-compile grace to the first iteration *since joining*, not
        # just the process-lifetime first (runtime/app.py supervisor)
        self.iterations_at_join = 0
        # failure-detection heartbeat (read by the supervisor in
        # runtime/app.py): wall-clock of the last completed iteration
        self.last_progress = time.monotonic()
        # gradient-side compression (compress.ErrorFeedback, set by the
        # app/CLI wiring when --compress != none): each outgoing delta
        # is error-compensated, encoded and decoded on device; the
        # residual is part of this worker's checkpointable state
        self.compressor = None
        # (clock, GradientMessage) of the newest compressed send: crash
        # recovery redelivers weights clocks the worker already trained
        # on (the recovering gate re-releases what the replay also
        # re-enqueues).  Stateless workers just recompute and let the
        # server's clock filter drop the duplicate, but an EF residual
        # must advance exactly once per clock — duplicates resend this
        # cached message instead (_redelivered_weights)
        self._last_sent = None
        # range sharding (runtime/sharding.ShardRouter, set by the
        # group/CLI wiring when the server side runs N>1 shards): each
        # outgoing delta splits into per-shard slices pushed to the
        # owning shards instead of one full-range send.  None keeps the
        # unsharded send path — the N=1 protocol, bitwise today's.
        self.shard_router = None

    def _prepare(self, msg: WeightsMessage):
        """Pre-dispatch half of an iteration, shared by the single-
        dispatch path (on_weights) and the gang path (runtime/gang.py):
        heartbeat, theta overwrite, slab snapshot/version cache.
        Returns (theta, x, y, mask, num_tuples_seen, want_eval)."""
        # heartbeat: starting an iteration counts as liveness, so a slow
        # (e.g. first-compile) iteration is measured from its own start
        self.last_progress = time.monotonic()
        # Overwrite the local replica with the server's parameters
        # (WorkerTrainingProcessor.java:72).  Full-range messages (the
        # per-node protocol) replace the replica wholesale — a no-op
        # device_put when the in-process fabric delivered a device
        # array; partial KeyRanges take the host splice path.
        r = msg.key_range
        if r.start == 0 and r.end == self.task.num_params:
            self.theta = jnp.asarray(msg.values)
        else:
            # pscheck: disable=PS102 (KeyRange splice is the documented host path)
            host = np.array(self.theta)
            # pscheck: disable=PS102 (KeyRange splice is the documented host path)
            host[r.start:r.end] = np.asarray(msg.values)
            self.theta = host

        seen = self.buffer.num_tuples_seen
        if self.buffer.count == 0:
            # Empty-buffer invariant (WorkerTrainingProcessor.java:131-133).
            raise RuntimeError(
                f"There is no data in the buffer of worker {self.worker_id}")
        ver = self.buffer.version
        if ver != self._slab_version:
            store = self._slab_store
            if not (self.cfg.slab_incremental and store.ready):
                store.upload_full(*self.buffer.snapshot(clear_dirty=True))
            else:
                slots, xr, yr, mr = self.buffer.drain_dirty()
                if 2 * len(slots) >= store.capacity:
                    # mass churn (target-shrink delete storms, restore):
                    # one contiguous upload beats a near-full scatter
                    store.upload_full(
                        *self.buffer.snapshot(clear_dirty=True))
                elif len(slots):
                    store.apply_rows(slots, xr, yr, mr)
            self._slab_version = ver
        x, y, mask = self._slab_store.arrays()
        want_eval = (self.test_x is not None
                     and msg.vector_clock % self.cfg.eval_every == 0)
        return jnp.asarray(self.theta), x, y, mask, seen, want_eval

    def _finish(self, msg: WeightsMessage, seen: int,
                delta, loss, f1, acc) -> None:
        """Post-dispatch half, shared by both paths: the per-worker CSV
        row (fields stay device futures), the iteration count, and the
        per-worker GradientMessage — identical whether the solver ran
        solo or stacked inside a gang."""
        # schema: timestamp;partition;vectorClock;loss;fMeasure;accuracy;
        # numTuplesSeen (WorkerAppRunner.java:80,
        # WorkerTrainingProcessor.java:85-92)
        asynclog.submit_or_write(
            self.log,
            f"{int(time.time() * 1000)};{self.worker_id};"
            f"{msg.vector_clock};{{}};{{}};{{}};{seen}",
            loss, f1, acc)
        self.iterations += 1
        if self.modelhealth.enabled:
            # device futures observed by reference; the plane's sampler
            # thread floats them off the training path
            self.modelhealth.observe_eval(loss, f1)

        encoded = None
        if self.compressor is not None:
            # what the server applies is the DECODED delta (identical on
            # both sides of a socket); the quantization error stays here
            # as the residual folded into the next iteration's delta
            delta, encoded = self.compressor.step(delta)
        out = GradientMessage(
            vector_clock=msg.vector_clock,
            key_range=KeyRange(0, self.task.num_params),
            values=delta,
            encoded=encoded,
            worker_id=self.worker_id)
        if self.shard_router is not None:
            # split by key range and push each slice to its owning
            # shard (the router also caches the slices for shard-crash
            # redelivery, runtime/sharding.py)
            self.shard_router.route(out)
        else:
            self.fabric.send(fabric_mod.GRADIENTS_TOPIC, 0, out)
        if self.compressor is not None:
            self._last_sent = (msg.vector_clock, out)
        if self.telemetry.enabled:
            self._m_updates.inc()
        self.last_progress = time.monotonic()

    def _redelivered_weights(self, msg: WeightsMessage) -> bool:
        """True when `msg` is a weights clock this worker already
        trained on and the step must NOT run again.  Only compressed
        workers dedup: re-running a step would advance the
        error-feedback residual a second time for the same clock,
        which is exactly the bitwise-replay corruption crash recovery
        must avoid (tests/test_log_recovery.py).  The newest clock's
        cached gradient is resent so a gate waiting on this worker
        still completes (the server's clock filter drops it if the
        original got through); older clocks are stale and dropped."""
        if self.compressor is None:
            return False
        last = self._last_sent
        if last is None or msg.vector_clock > last[0]:
            return False
        if msg.vector_clock == last[0]:
            if self.shard_router is not None:
                self.shard_router.route(last[1])
            else:
                self.fabric.send(fabric_mod.GRADIENTS_TOPIC, 0, last[1])
        return True

    def on_weights(self, msg: WeightsMessage) -> None:
        if self._redelivered_weights(msg):
            return
        theta, x, y, mask, seen, want_eval = self._prepare(msg)

        # Post-fit test metrics, like the reference's per-iteration eval
        # inside calculateGradients (LogisticRegressionTaskSpark.java:186).
        # eval_every > 1 skips the full-test-set evaluation on
        # off-cadence clocks, logging the reference's own "-1 = not
        # computed" placeholder (ServerProcessor.java:158-164 uses it
        # for loss).  All numeric fields stay device futures — the line
        # is formatted when they resolve (utils/asynclog.DeferredSink).
        # Eval iterations fuse solver + evaluate into ONE dispatch
        # (_solver_fns): per-dispatch host latency is what bounds the
        # per-node path on a tunneled transport.
        update_fn, update_eval_fn = _solver_fns(
            self.cfg.task, self.cfg.model, self.cfg.use_pallas)
        f1, acc = -1.0, -1.0
        t0 = time.perf_counter()
        with self.tracer.span("worker.local_update", worker=self.worker_id,
                              clock=msg.vector_clock):
            if want_eval:
                delta, loss, f1, acc = update_eval_fn(
                    theta, x, y, mask, self.test_x, self.test_y)
            else:
                delta, loss = update_fn(theta, x, y, mask)
        self.tracer.count("dispatch.device")
        if self.telemetry.enabled:
            # dispatch wall time, host clocks only — the async dispatch
            # is NOT synced for this (bitwise/latency non-perturbing)
            self._m_update_ms.observe((time.perf_counter() - t0) * 1e3)

        self._finish(msg, seen, delta, loss, f1, acc)
