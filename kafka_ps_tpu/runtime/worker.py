"""Worker compute node — behavioral re-design of WorkerTrainingProcessor
(processors/WorkerTrainingProcessor.java:24-138).

On each WeightsMessage: overwrite local parameters with the server's,
snapshot the worker's sliding buffer (a static-shape masked slab — no
per-row range scan), run the jit'd k-step local update on device, log
the worker CSV line, and send the delta back as a GradientMessage with
the same vector clock on the gather topic.

The reference's empty-buffer invariant (IllegalStateException,
WorkerTrainingProcessor.java:131-133) is preserved as RuntimeError.
"""

from __future__ import annotations

import time
from typing import Callable

import jax.numpy as jnp
import numpy as np

from kafka_ps_tpu.data.buffer import SlidingBuffer
from kafka_ps_tpu.runtime import fabric as fabric_mod
from kafka_ps_tpu.runtime.messages import GradientMessage, KeyRange, WeightsMessage
from kafka_ps_tpu.utils.config import PSConfig
from kafka_ps_tpu.utils.trace import NULL_TRACER

LogSink = Callable[[str], None]


class WorkerNode:
    """One logical worker: private buffer + full model replica + jit'd
    local solver."""

    def __init__(self, worker_id: int, cfg: PSConfig, fabric: fabric_mod.Fabric,
                 buffer: SlidingBuffer,
                 test_x: np.ndarray | None = None,
                 test_y: np.ndarray | None = None,
                 log: LogSink | None = None,
                 tracer=None):
        self.tracer = tracer or NULL_TRACER
        self.worker_id = worker_id
        self.cfg = cfg
        self.fabric = fabric
        self.buffer = buffer
        from kafka_ps_tpu.models.task import get_task
        self.task = get_task(cfg.task, cfg.model)
        if cfg.use_pallas and cfg.task != "logreg":
            raise ValueError(
                "use_pallas implements the logreg local update only "
                f"(ops/fused_update.py), got task {cfg.task!r}")
        self.theta = np.zeros((self.task.num_params,), dtype=np.float32)
        self.test_x = jnp.asarray(test_x) if test_x is not None else None
        self.test_y = jnp.asarray(test_y) if test_y is not None else None
        self.log = log or (lambda line: None)
        self.iterations = 0
        # iterations counted at (re)admission: the supervisor grants the
        # jit-compile grace to the first iteration *since joining*, not
        # just the process-lifetime first (runtime/app.py supervisor)
        self.iterations_at_join = 0
        # failure-detection heartbeat (read by the supervisor in
        # runtime/app.py): wall-clock of the last completed iteration
        self.last_progress = time.monotonic()

    def on_weights(self, msg: WeightsMessage) -> None:
        # heartbeat: starting an iteration counts as liveness, so a slow
        # (e.g. first-compile) iteration is measured from its own start
        self.last_progress = time.monotonic()
        # Overwrite the local replica with the server's parameters
        # (WorkerTrainingProcessor.java:72).
        r = msg.key_range
        self.theta[r.start:r.end] = msg.values

        x, y, mask = self.buffer.snapshot()
        if mask.sum() == 0:
            # Empty-buffer invariant (WorkerTrainingProcessor.java:131-133).
            raise RuntimeError(
                f"There is no data in the buffer of worker {self.worker_id}")

        if self.cfg.use_pallas:    # logreg-only, enforced in __init__
            from kafka_ps_tpu.ops import fused_update

            def update_fn(theta, xx, yy, mm):
                return fused_update.local_update(theta, xx, yy, mm,
                                                 cfg=self.cfg.model)
        else:
            update_fn = self.task.local_update
        with self.tracer.span("worker.local_update", worker=self.worker_id,
                              clock=msg.vector_clock):
            delta, loss = update_fn(
                jnp.asarray(self.theta), jnp.asarray(x), jnp.asarray(y),
                jnp.asarray(mask))
            delta = np.asarray(delta)

        # Post-fit test metrics, like the reference's per-iteration eval
        # inside calculateGradients (LogisticRegressionTaskSpark.java:186).
        # eval_every > 1 skips the (wall-clock-dominating) full-test-set
        # evaluation on off-cadence clocks, logging the reference's own
        # "-1 = not computed" placeholder (ServerProcessor.java:158-164
        # uses it for loss).
        f1, acc = -1.0, -1.0
        if (self.test_x is not None
                and msg.vector_clock % self.cfg.eval_every == 0):
            m = self.task.evaluate(jnp.asarray(self.theta + delta),
                                   self.test_x, self.test_y)
            f1, acc = float(m.f1), float(m.accuracy)

        # schema: timestamp;partition;vectorClock;loss;fMeasure;accuracy;
        # numTuplesSeen (WorkerAppRunner.java:80,
        # WorkerTrainingProcessor.java:85-92)
        self.log(f"{int(time.time() * 1000)};{self.worker_id};"
                 f"{msg.vector_clock};{float(loss)};{f1};{acc};"
                 f"{self.buffer.num_tuples_seen}")
        self.iterations += 1

        self.fabric.send(
            fabric_mod.GRADIENTS_TOPIC, 0,
            GradientMessage(
                vector_clock=msg.vector_clock,
                key_range=KeyRange(0, self.task.num_params),
                values=delta,
                worker_id=self.worker_id))
        self.last_progress = time.monotonic()
