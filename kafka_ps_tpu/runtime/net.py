"""Socket transport — the cross-host hop for the ASYNC consistency
models (bounded delay / eventual), carrying the binary serde frames
(runtime/serde.py) over TCP.

This is the last Kafka property with no in-process counterpart: the
reference's server JVM and worker JVMs exchange WEIGHTS / GRADIENTS /
INPUT_DATA through the broker from different machines
(kubernetes/server.yaml + worker.yaml, broker kafka:9092).  The fused
BSP path scales out through jax.distributed collectives instead
(parallel/multihost.py) — but the async modes are host-orchestrated by
design, so their multi-host story is exactly this: a server process
(aggregator + consistency gate + producer) and worker processes
(buffers + local solvers), point-to-point sockets in place of topics.

Wire format, little-endian:
    frame  := <u32 length> <u8 topic> <i64 key> <payload>
    topic  := 1 WEIGHTS | 2 GRADIENTS | 3 INPUT_DATA | 4 HELLO | 5 READY
              | 6 PING | 7 PONG | 8 CONFIG | 9 PREDICT | 10 PREDICTION
              | 11 DATA_BATCH
    payload:= serde.to_bytes(message)   (HELLO: <i64 n> <i64 ids[n]>
                                                [<u8 codec_id> <f32 param>];
                                         READY/PING/PONG: empty;
                                         CONFIG: <f64 ping_interval_s>
                                                 <i64 run_id>
                                                 [<u8 codec_id> <f32 param>];
                                         DATA_BATCH: columnar <i64 -nrows>
                                         + packed index/value/label
                                         columns (serde.
                                         encode_labeled_rows); the
                                         legacy <i64 nrows> then per row
                                         <i32 len><serde bytes> layout
                                         is still accepted on receive;
                                         PREDICT / PREDICTION: see the
                                         encode_/decode_ helpers below)
`key` is the logical worker id (the Kafka record key, CsvProducer.java:61);
for PREDICT/PREDICTION it is the client's request id (echoed back).

Codec negotiation (docs/COMPRESSION.md): HELLO optionally carries the
worker's `--compress` codec; the server's CONFIG reply echoes the codec
the pair will actually use — the server's own codec when both sides
named the SAME one, `none` otherwise.  Both trailers are read with
unpack_from, so an old peer simply never sees them and the pair falls
back to uncompressed f32 frames — a `--compress none` fleet is
byte-identical to before this field existed.

Trace-context negotiation (docs/OBSERVABILITY.md) rides the same
pattern: one `<u8 offer>` byte AFTER the codec trailer on HELLO (the
worker offers 1 iff its tracer is on) and on CONFIG (the server answers
1 iff the offer arrived AND its own tracer is on).  When the pair
negotiates tracing ON, every WEIGHTS / GRADIENTS payload gains a
16-byte `<u64 flow_id> <u64 parent_span>` suffix after the serde bytes;
the receiver strips it before decoding and emits the matching Chrome
flow event, so a delta's worker -> server -> serving lifecycle renders
as one connected arrow chain in Perfetto after the merge CLI
(`python -m kafka_ps_tpu.telemetry merge`).  Old peers never offer and
never see a suffix — a legacy fleet stays byte-identical.

Range sharding (docs/SHARDING.md): a sharded deployment runs N of
these bridges — one per shard-server process — and every worker
process holds N WorkerBridge connections.  The frames themselves need
no new fields: the shard/range header rides INSIDE the serde payload
(every weights/gradient message carries a KeyRange; sparse slices are
tid-6 SparseDeltaMessage frames whose key_range names the owning
shard's span), so an unsharded peer speaks the same wire format.
GRADIENT sends go out per-bridge via `WorkerBridge.send_gradients`
(the ShardRouter's hook) and WEIGHTS slices land per-bridge into the
assembler via `set_weights_sink`.

Delivery properties preserved from the reference fabric: addressed
per-worker delivery, per-connection FIFO (TCP), asynchronous buffering
(the consistency gate never blocks on a send).  Cites:
ServerProcessor.java:172-182 (weights send), WorkerTrainingProcessor
.java:95-97 (gradient send, record key 0), CsvProducer.java:61-65.
"""

from __future__ import annotations

import dataclasses
import socket
import struct
import sys
import threading
import time

from kafka_ps_tpu.analysis.lockgraph import OrderedLock
from kafka_ps_tpu.compress.wire import NONE as CODEC_SPEC_NONE
from kafka_ps_tpu.compress.wire import CODEC_NONE, CodecSpec
from kafka_ps_tpu.runtime import fabric as fabric_mod
from kafka_ps_tpu.runtime import serde
# the wire engine (docs/WIRE.md): coalescing writer, buffered reader,
# scatter-gather send, and the shared frame header + force_close
from kafka_ps_tpu.runtime.wire import (_FRAME, FrameWriter, RecvBuffer,
                                       force_close, sendmsg_all)
from kafka_ps_tpu.telemetry import NULL_TELEMETRY
from kafka_ps_tpu.telemetry.flight import FLIGHT
from kafka_ps_tpu.utils.trace import NULL_TRACER

(T_WEIGHTS, T_GRADIENTS, T_DATA, T_HELLO, T_READY,
 T_PING, T_PONG, T_CONFIG, T_PREDICT, T_PREDICTION,
 T_DATA_BATCH, T_WEIGHTS_AGG) = range(1, 13)
# the full frame-topic table: data topics map to their fabric names,
# control/serving topics to wire-only names (test_net_framing.py keeps
# this exhaustive against the T_* constants)
TOPIC_NAMES = {T_WEIGHTS: fabric_mod.WEIGHTS_TOPIC,
               T_GRADIENTS: fabric_mod.GRADIENTS_TOPIC,
               T_DATA: fabric_mod.INPUT_DATA_TOPIC,
               T_HELLO: "hello", T_READY: "ready",
               T_PING: "ping", T_PONG: "pong", T_CONFIG: "config",
               T_PREDICT: "predict", T_PREDICTION: "prediction",
               T_DATA_BATCH: "input-data-batch",
               T_WEIGHTS_AGG: "weights-agg"}

# the optional codec trailer on HELLO and CONFIG (negotiation above)
_CODEC_TRAILER = struct.Struct("<Bf")
# the optional trace-offer/answer byte AFTER the codec trailer
_TRACE_TRAILER = struct.Struct("<B")
# the per-message trace context suffixed to WEIGHTS/GRADIENTS payloads
# when the pair negotiated tracing: <u64 flow_id> <u64 parent_span>
_TRACE_CTX = struct.Struct("<QQ")
# the optional shared-memory request byte AFTER the trace trailer on
# HELLO, and the matching offer AFTER the trace trailer on CONFIG:
# <u8 granted> <16s nonce> <64s NUL-padded segment name>.  Same
# append-and-length-check pattern as the codec/trace trailers: legacy
# peers on either side never see the bytes and stay on sockets
# (serving/shm.py, docs/SERVING.md "Dispatch economics")
_SHM_TRAILER = struct.Struct("<B")
_SHM_OFFER = struct.Struct("<B16s64s")
# the optional aggregator-role byte AFTER the shm trailer on HELLO
# (kafka_ps_tpu/agg/, docs/AGGREGATION.md): 1 marks the connection as
# a per-host aggregator relay.  Its registered ids are the MEMBER
# workers behind it (weights/data route through it), its disconnect
# does NOT evict them (the members are alive behind a restarting
# relay; they resend through the next one), and grouped fan-out may
# target it with ONE T_WEIGHTS_AGG frame per release.  Same
# append-and-length-check pattern as every other trailer: plain
# workers never send the byte and nothing changes for them.
_AGG_TRAILER = struct.Struct("<B")
# T_CONFIG re-sent mid-stream with this run id is a GOODBYE: the run is
# over and the peer is closing on purpose.  An aggregator relay sends
# it downstream before closing (agg/relay.py) so its member workers can
# tell a finished run from a crashed relay — the latter drops the
# members' ONLY connection exactly like end-of-run would, and without
# this marker they could not know to hold the run open and reconnect
# (cli/socket_mode._run_worker_sharded).  Real run ids are time_ns() or
# checkpointed positives; -1 can never collide.
GOODBYE_RUN_ID = -1
# T_WEIGHTS_AGG payload: <q n> then n x <q worker><q clock>, then ONE
# serde weights body shared by all members — the aggregator re-stamps
# the body's vector clock per member (serde._HEADER keeps the clock at
# byte offset 5 for plain AND compressed weights) and re-broadcasts,
# so a k-member release costs one upstream send instead of k.
_AGG_MEMBER = struct.Struct("<qq")

# -- serving-plane payloads (kafka_ps_tpu/serving/, docs/SERVING.md) -------
# PREDICT: the feature row plus the request's staleness bound; sentinel
# -1 encodes "unbounded" (clocks are non-negative, ages positive)
_PREDICT_HEADER = struct.Struct("<qdq")   # min_clock, max_age_s, n features
# PREDICTION: status + (label, confidence, snapshot clock, snapshot time)
_PREDICTION = struct.Struct("<Bqdqd")
PREDICT_OK, PREDICT_STALE, PREDICT_FAILED, PREDICT_OVERLOADED = 0, 1, 2, 3
# optional model-id trailer AFTER the feature row (multi-model serving,
# docs/SERVING.md) — same append-and-length-check pattern as the codec
# trailer, so frames from peers that never send it decode as model 0
_MODEL_TRAILER = struct.Struct("<q")


def encode_predict_request(x, min_clock: int | None = None,
                           max_age_s: float | None = None,
                           model_id: int = 0) -> bytes:
    import numpy as np
    row = np.asarray(x, dtype=np.float32).reshape(-1)
    return (_PREDICT_HEADER.pack(
        -1 if min_clock is None else int(min_clock),
        -1.0 if max_age_s is None else float(max_age_s),
        row.size) + row.tobytes()
        + _MODEL_TRAILER.pack(int(model_id)))


def decode_predict_request(payload: bytes):
    """(features, min_clock | None, max_age_s | None, model_id)."""
    import numpy as np
    min_clock, max_age_s, n = _PREDICT_HEADER.unpack_from(payload, 0)
    row = np.frombuffer(payload, dtype=np.float32, count=n,
                        offset=_PREDICT_HEADER.size)
    model_id = 0
    tail = _PREDICT_HEADER.size + row.nbytes
    if len(payload) >= tail + _MODEL_TRAILER.size:
        (model_id,) = _MODEL_TRAILER.unpack_from(payload, tail)
    return (row, None if min_clock < 0 else min_clock,
            None if max_age_s < 0 else max_age_s, model_id)


def encode_prediction(status: int, label: int = -1, confidence: float = 0.0,
                      vector_clock: int = -1, wall_time: float = 0.0) -> bytes:
    return _PREDICTION.pack(status, label, confidence, vector_clock,
                            wall_time)


def decode_prediction(payload: bytes):
    """(status, label, confidence, vector_clock, wall_time)."""
    return _PREDICTION.unpack_from(payload, 0)


def _encode_result(result) -> bytes:
    """Map a PredictionEngine callback argument — a Prediction, or the
    typed failure the engine passed instead — onto a wire PREDICTION
    payload.  Shared by the socket reply path and the shm serve loop so
    the two transports cannot drift on status semantics."""
    from kafka_ps_tpu.serving.policy import OverloadedError, StalenessError
    if isinstance(result, OverloadedError):
        return encode_prediction(PREDICT_OVERLOADED)
    if isinstance(result, StalenessError):
        return encode_prediction(PREDICT_STALE)
    if isinstance(result, BaseException):
        return encode_prediction(PREDICT_FAILED)
    return encode_prediction(PREDICT_OK, result.label, result.confidence,
                             result.vector_clock, result.wall_time)


def send_frame(sock: socket.socket, topic: int, key: int,
               payload: bytes = b"") -> None:
    """One frame, immediately (the non-queued fallback path).  Header
    and payload go out as a two-element scatter-gather send — a
    multi-KB weights payload is never copied just to prepend 13
    bytes."""
    header = _FRAME.pack(_FRAME.size - 4 + len(payload), topic, key)
    if len(payload):
        sendmsg_all(sock, (header, payload))
    else:
        sock.sendall(header)


def locked_send(sock: socket.socket, lock, topic: int, key: int,
                payload: bytes = b"") -> None:
    """Serialize one frame write onto `sock` under its dedicated write
    lock.  Interleaved frame bodies from concurrent senders would
    corrupt the stream, so the write lock's entire critical section IS
    the write — every bridge sends through here."""
    with lock:
        # pscheck: disable=PS105 (dedicated write lock: this send IS the critical section)
        send_frame(sock, topic, key, payload)


def recv_frame(sock: socket.socket) -> tuple[int, int, memoryview] | None:
    """(topic, key, payload) or None on a clean EOF.  The payload is a
    zero-copy memoryview into the received frame body — every decode
    site (np.frombuffer, struct.unpack_from, zlib, serde) reads
    bytes-likes, so slicing the 9-byte topic/key prefix no longer
    copies the multi-KB message payload."""
    head = _recv_exact(sock, 4)
    if head is None:
        return None
    (length,) = struct.unpack("<I", head)
    body = _recv_exact(sock, length)
    if body is None:
        raise ConnectionError("mid-frame EOF")
    topic, key = struct.unpack_from("<Bq", body, 0)
    return topic, key, memoryview(body)[9:]


def _read_codec_trailer(payload, offset: int) -> CodecSpec:
    """The optional <u8 codec_id> <f32 param> trailer of a HELLO or
    CONFIG payload; NONE when absent (old peer) or unintelligible
    (newer peer with codec ids we don't know)."""
    if len(payload) < offset + _CODEC_TRAILER.size:
        return CODEC_SPEC_NONE
    cid, param = _CODEC_TRAILER.unpack_from(payload, offset)
    try:
        return CodecSpec(cid, param)
    except ValueError:
        return CODEC_SPEC_NONE


def _read_trace_flag(payload, offset: int) -> bool:
    """The optional <u8> trace offer/answer after the codec trailer;
    False when absent (old peer)."""
    if len(payload) < offset + _TRACE_TRAILER.size:
        return False
    (flag,) = _TRACE_TRAILER.unpack_from(payload, offset)
    return bool(flag)


def _read_shm_flag(payload, offset: int) -> bool:
    """The optional <u8> shared-memory request after the trace trailer
    on HELLO; False when absent (old peer, or a client on sockets)."""
    if len(payload) < offset + _SHM_TRAILER.size:
        return False
    (flag,) = _SHM_TRAILER.unpack_from(payload, offset)
    return bool(flag)


def _read_agg_flag(payload, offset: int) -> bool:
    """The optional <u8> aggregator-role byte after the shm trailer on
    HELLO; False when absent (a plain worker, or any older peer)."""
    if len(payload) < offset + _AGG_TRAILER.size:
        return False
    (flag,) = _AGG_TRAILER.unpack_from(payload, offset)
    return bool(flag)


def _read_shm_offer(payload, offset: int) -> tuple[str, bytes] | None:
    """The optional shm offer after the trace trailer on CONFIG:
    (segment name, nonce), or None when absent (legacy server) or the
    server declined (granted byte 0 — shm off, or segment creation
    failed on its side)."""
    if len(payload) < offset + _SHM_OFFER.size:
        return None
    granted, nonce, name = _SHM_OFFER.unpack_from(payload, offset)
    if not granted:
        return None
    return name.rstrip(b"\0").decode("ascii", "replace"), nonce


def _frame_counters(telemetry):
    """Pre-resolved per-topic (sent, received) counter children plus the
    matching wire-byte counters, so the frame hot paths never hit the
    registry's family lock.  All-null children when telemetry is off."""
    sent = {t: (telemetry.counter("frames_sent", topic=name),
                telemetry.counter("wire_bytes_total", topic=name,
                                  direction="out"))
            for t, name in TOPIC_NAMES.items()}
    recv = {t: (telemetry.counter("frames_received", topic=name),
                telemetry.counter("wire_bytes_total", topic=name,
                                  direction="in"))
            for t, name in TOPIC_NAMES.items()}
    return sent, recv


def _recv_exact(sock: socket.socket, n: int) -> bytearray | bytes | None:
    """Exactly n bytes, or None on a clean EOF before the first byte.
    EOF after a partial read is a torn frame — a crashed peer, never an
    orderly shutdown — and raises so the caller treats it as a failure
    (the reference gets this for free from Kafka's record framing).
    Preallocated bytearray filled via recv_into — no quadratic
    `bytes += chunk` re-copy for payloads the kernel delivers in
    pieces.  Stays as the fallback read path for the handshake and the
    PredictClient (bridge readers use wire.RecvBuffer)."""
    if n == 0:
        return b""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if r == 0:
            if got:
                raise ConnectionError(
                    f"mid-frame EOF ({got}/{n} bytes)")
            return None
        got += r
    return buf


class ServerBridge:
    """Server-process side: listens for worker processes, forwards
    WEIGHTS / INPUT_DATA to the connection owning each worker key, and
    delivers incoming GRADIENTS into the local fabric's gather queue.

    Install via `bridge.wrap(fabric)`: the returned fabric routes sends
    addressed to remote workers over their socket and leaves local
    behavior untouched (the Kafka-broker role, minus the broker).

    Failure detection (the consumer-group-rebalance analogue, SURVEY §5):
    a reader hitting EOF/reset purges the connection's worker ids and
    fires `on_disconnect(ids)`; a later HELLO re-registers them and
    fires `on_hello(ids)`; READY fires `on_ready(worker)` — the caller
    (cli/socket_mode.run_server) turns these into evictions and
    readmissions on the ServerNode.  With `heartbeat_interval` set the
    bridge PINGs every connection on that cadence and, when
    `heartbeat_timeout` is also set, force-closes connections silent for
    longer than it — half-open TCP (a worker host vanishing without a
    FIN) then surfaces as a normal disconnect instead of hanging the
    consistency gate forever.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 heartbeat_interval: float | None = None,
                 heartbeat_timeout: float | None = None,
                 run_id: int = 0, codec: CodecSpec | None = None,
                 tracer=None, telemetry=None, shm: bool = False,
                 coalesce: bool = True):
        # `run_id` identifies the logical RUN (fresh server start, or
        # the run a checkpoint resume continues — utils/checkpoint.py
        # persists it).  Advertised in T_CONFIG so worker processes can
        # tell whether their local state file belongs to THIS run or is
        # a stale leftover from an earlier one (cli/socket_mode.py).
        self.run_id = run_id
        # `codec`: this server's `--compress` choice; per-connection
        # negotiation (docstring above) lands in `_codec_of`, and sends
        # to a none-negotiated peer strip the encoded payload in _send
        self.codec = codec if codec is not None else CODEC_SPEC_NONE
        # guarded-by: _lock (HELLO writes hold the state lock; send-path reads are GIL-atomic dict gets)
        self._codec_of: dict[socket.socket, CodecSpec] = {}
        self._tracer = tracer or NULL_TRACER
        self._telemetry = telemetry or NULL_TELEMETRY
        # per-connection trace negotiation (module docstring): True iff
        # the peer offered AND this side's tracer is on
        # guarded-by: _lock (HELLO writes hold the state lock; send-path reads are GIL-atomic)
        self._trace_of: dict[socket.socket, bool] = {}
        # pre-resolved metric children: one dict lookup + one leaf-lock
        # inc per frame on the hot path (null metrics when telemetry off)
        self._m_sent, self._m_recv = _frame_counters(self._telemetry)
        # bytes on the wire per frame topic, both directions, including
        # the 13-byte frame header (the compression_ab bench reads this)
        self.wire_bytes: dict[int, int] = {}
        self._wire_lock = OrderedLock("ServerBridge.wire")
        self._listener = socket.create_server((host, port))
        self.port = self._listener.getsockname()[1]
        # guarded-by: _lock (registration holds the cv; routing reads are GIL-atomic dict gets)
        self._conn_of: dict[int, socket.socket] = {}   # worker -> conn
        self._ready: set[int] = set()
        self._lock = OrderedLock("ServerBridge.state", reentrant=True)
        self._cv = threading.Condition(self._lock)
        # pscheck: disable=PS201 (wrap publishes the fabric before any traffic can reference it - attach-before-serve)
        self._fabric: fabric_mod.Fabric | None = None
        self._stop = threading.Event()
        self._send_lock: dict[socket.socket, OrderedLock] = {}
        # `--wire-coalesce` (docs/WIRE.md): queue frames per connection
        # and ship them in scatter-gather batches from a dedicated
        # writer thread; off = the classic one-sendall-per-frame path
        self._coalesce = bool(coalesce)
        # guarded-by: _lock (accept-loop writes hold the state lock; send-path reads are GIL-atomic)
        self._writer_of: dict[socket.socket, FrameWriter] = {}
        # guarded-by: _lock (registered under the lock; the reader's per-frame store is GIL-atomic and the heartbeat tolerates an interval of staleness)
        self._last_recv: dict[socket.socket, float] = {}
        self.on_disconnect = None   # Callable[[list[int]], None]
        self.on_hello = None        # Callable[[list[int]], None]
        self.on_ready = None        # Callable[[int], None]
        # pscheck: disable=PS201 (attach_serving publishes the engine before predict frames can arrive)
        self._serving = None        # PredictionEngine (attach_serving)
        # same-host shared-memory fast path (serving/shm.py): offered
        # per connection on a HELLO that requests it, only when enabled
        # here AND a serving engine is attached
        self._shm_enabled = bool(shm)
        # connections whose HELLO carried the aggregator-role byte
        # (kafka_ps_tpu/agg/): weights to their member ids may group
        # into T_WEIGHTS_AGG frames, and their disconnects are relay
        # restarts, not member failures — on_disconnect is suppressed
        self._agg_conns: set[socket.socket] = set()
        # guarded-by: _lock (offer/teardown hold the state lock; reads are GIL-atomic)
        self._shm_of: dict[socket.socket, object] = {}
        self._shm_threads: list[threading.Thread] = []
        self._m_shm = self._telemetry.counter("serving_dispatch_mode",
                                              mode="shm")
        # pscheck: disable=PS201 (failure-path counter; a racing increment can only undercount telemetry)
        self.dropped_sends = 0      # frames lost to dead connections
        self._hb_interval = heartbeat_interval
        self._hb_timeout = heartbeat_timeout
        # guarded-by: _lock (accept loop swaps the list under the state lock; close() joins after the listener is down)
        self._reader_threads: list[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="kps-net-accept")
        self._accept_thread.start()
        self._hb_thread = None
        if heartbeat_interval:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True,
                name="kps-net-heartbeat")
            self._hb_thread.start()

    # -- fabric integration ------------------------------------------------

    def wrap(self, fabric: fabric_mod.Fabric) -> fabric_mod.Fabric:
        bridge = self

        # subclass the wrapped fabric's OWN class, not the base Fabric:
        # wrapping a log.durable_fabric.DurableFabric must keep its
        # append-before-enqueue send and its recover/commit surface —
        # the sharded split deployment (--shards N --durable-log, one
        # log partition per shard process) relies on exactly that
        class BridgedFabric(type(fabric)):
            def send(self, topic, key, message):
                conn = bridge._conn_of.get(key) \
                    if topic == fabric_mod.WEIGHTS_TOPIC else None
                if conn is not None:
                    bridge._send(conn, T_WEIGHTS, key, message)
                else:
                    super().send(topic, key, message)

        out = object.__new__(BridgedFabric)
        # share ALL state with the original (queues, cond, tracer — and
        # any subclass state such as the durable log writer) so
        # pre-wrap queues and already-appended partitions stay visible
        out.__dict__ = fabric.__dict__
        self._fabric = out
        return out

    def attach_serving(self, engine) -> None:
        """Answer T_PREDICT frames from any connection through a
        serving.engine.PredictionEngine.  Requests are submitted async —
        the reader thread never blocks on a batch deadline — and the
        reply goes out from the engine's batcher thread.  A client need
        not HELLO: predict-only connections register no worker ids, so
        the weights/data routing never sees them."""
        self._serving = engine

    def send_data(self, worker: int, features: dict[int, float],
                  label: int) -> bool:
        """Forward one stream row to the process hosting `worker`.
        False if that worker is not (yet) connected or its connection
        just died — the caller reroutes or counts the row."""
        from kafka_ps_tpu.runtime.messages import LabeledData
        conn = self._conn_of.get(worker)
        if conn is None:
            return False
        return self._send(conn, T_DATA, worker, LabeledData(features, label))

    def send_data_batch(self, worker: int, rows) -> bool:
        """Forward N stream rows to the process hosting `worker` in ONE
        columnar frame: <i64 -nrows> discriminator + packed
        feature-index/value/label ndarray columns
        (serde.encode_labeled_rows) decoded straight into
        SlidingBuffer.add_many — no per-row serde header, length
        prefix, or dict rebuild on the encode side.  Receivers accept
        the legacy per-row <i32 len><serde blob> layout too (nrows >=
        0), so a mixed-version fleet interoperates.  `rows` is a
        sequence of (features, label); False exactly like send_data
        (the caller reroutes the rows)."""
        conn = self._conn_of.get(worker)
        if conn is None:
            return False
        return self._send_raw(conn, T_DATA_BATCH, worker,
                              serde.encode_labeled_rows(rows))

    def send_weights_group(self, release, builder) -> set:
        """Grouped weights fan-out for aggregator relays (the
        ServerNode.weights_group_send hook, docs/AGGREGATION.md): ship
        ONE T_WEIGHTS_AGG frame per relay covering every released
        member behind it — member (worker, clock) list + one weights
        body the relay re-stamps and re-broadcasts.  `builder(clock)`
        produces the WeightsMessage (called once per relay; repeated
        calls hit the server compressor's identity cache).  Returns the
        worker ids actually shipped — members on plain connections (or
        none at all) are left for the caller's per-worker path."""
        groups: dict[socket.socket, list] = {}
        for worker, clock in release:
            conn = self._conn_of.get(worker)
            if conn is not None and conn in self._agg_conns:
                groups.setdefault(conn, []).append((worker, clock))
        handled: set = set()
        for conn, members in groups.items():
            msg = builder(members[0][1])
            if (getattr(msg, "encoded", None) is not None
                    and self._codec_of.get(conn,
                                           CODEC_SPEC_NONE).codec_id
                    == CODEC_NONE):
                # same downgrade rule as _send: a none-negotiated relay
                # gets the decoded f32 body its members will train on
                msg = dataclasses.replace(msg, encoded=None)
            payload = b"".join(
                [struct.pack("<q", len(members))]
                + [_AGG_MEMBER.pack(w, c) for w, c in members]
                + [serde.to_bytes(msg)])
            if self._send_raw(conn, T_WEIGHTS_AGG, 0, payload):
                handled.update(w for w, _ in members)
        return handled

    def send_goodbye(self) -> None:
        """Announce end-of-run to every live connection (T_CONFIG with
        GOODBYE_RUN_ID) — the relay's last act before closing its
        downstream listener, so members stop instead of waiting out the
        crash-reconnect grace window.  Best-effort: a connection that
        dies mid-goodbye just pays the grace timeout."""
        payload = struct.pack("<dq", self._hb_interval or 0.0,
                              GOODBYE_RUN_ID)
        for conn in list(self._send_lock):
            self._send_raw(conn, T_CONFIG, 0, payload)

    def forward_frame(self, topic: int, worker: int,
                      payload: bytes) -> bool:
        """Raw pre-serialized frame send to the connection owning
        `worker` — the aggregator relay's downstream re-broadcast path
        (weights with a re-stamped clock, pass-through data rows): the
        bytes cross without a decode/encode cycle, so what the worker
        receives is bit-identical to what the server sent.  A weights
        frame to a trace-negotiated member gets a FRESH flow suffix —
        the member's reader strips 16 bytes unconditionally, and the
        upstream hop's suffix never crossed the relay."""
        conn = self._conn_of.get(worker)
        if conn is None:
            return False
        if topic == T_WEIGHTS and self._trace_of.get(conn):
            fid = self._tracer.new_flow_id()
            with self._tracer.span("net.send", topic="weights",
                                   worker=worker):
                self._tracer.flow_start("weights.wire", fid,
                                        worker=worker)
            payload += _TRACE_CTX.pack(fid, 0)
        return self._send_raw(conn, topic, worker, payload)

    def wait_for_connected(self, workers, timeout: float = 60.0) -> None:
        """Block until every worker id has a connection (HELLO seen) —
        before this the producer has nowhere to send their rows."""
        with self._cv:
            ok = self._cv.wait_for(
                lambda: all(w in self._conn_of for w in workers),
                timeout=timeout)
        if not ok:
            missing = [w for w in workers if w not in self._conn_of]
            raise TimeoutError(f"workers {missing} not connected in time")

    def wait_for_workers(self, workers, timeout: float = 60.0) -> None:
        """Block until every worker id has reported READY (its buffer
        holds data) — the actual invariant behind the reference's fixed
        20 s bootstrap sleep (ServerAppRunner.java:95)."""
        with self._cv:
            ok = self._cv.wait_for(
                lambda: all(w in self._ready for w in workers),
                timeout=timeout)
        if not ok:
            missing = [w for w in workers if w not in self._ready]
            raise TimeoutError(f"workers {missing} not ready in time")

    def close(self) -> None:
        self._stop.set()
        # shutdown BEFORE close: closing the fd does not wake a thread
        # blocked in accept() — the in-flight syscall pins the kernel
        # socket, leaving the port in LISTEN with no owner (a restart
        # on the same port then fails EADDRINUSE until process exit)
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        # join the accept loop FIRST: it may have accepted a connection
        # just before the listener closed, and no reader must be
        # spawned after the sweep below (a missed one would survive its
        # join and die inside native recv at interpreter exit)
        if self._accept_thread is not threading.current_thread():
            self._accept_thread.join(timeout=10.0)
        # flush-before-close: writers drain their queues first, so a
        # goodbye/CONFIG enqueued before close() reaches the wire in
        # order; only then are the sockets torn down
        for writer in list(self._writer_of.values()):
            writer.close(flush=True)
        # every live connection, including ones that never sent HELLO
        for conn in list(self._send_lock):
            force_close(conn)        # wakes the blocked reader thread
        # shm channels whose reader cleanup has not run yet: close (and
        # unlink — this side owns the segments) so no serve thread spins
        # on an unlinked mapping and /dev/shm is left clean
        for chan in list(self._shm_of.values()):
            chan.close()
        for t in list(self._shm_threads):
            if t is not threading.current_thread():
                t.join(timeout=10.0)
        # join everything before returning: readers hand GRADIENTS into
        # the fabric (device arrays) and the heartbeat waits at most one
        # interval — a thread left alive at interpreter exit can die
        # inside native code and abort the process
        for t in (*self._reader_threads, self._hb_thread):
            if t is not None and t is not threading.current_thread():
                t.join(timeout=10.0)

    # -- internals ---------------------------------------------------------

    def _send(self, conn, topic, key, message=None) -> bool:
        """False (never raises) when the connection is gone: the message
        is dropped, like a Kafka send to a dead consumer — the reader's
        disconnect cleanup drives the actual eviction, so a send from
        inside the consistency gate can't crash the server."""
        if (message is not None
                and getattr(message, "encoded", None) is not None
                and self._codec_of.get(conn,
                                       CODEC_SPEC_NONE).codec_id
                == CODEC_NONE):
            # this peer negotiated no compression (old version, or
            # `--compress none`): ship the decoded values as a plain f32
            # frame — they ARE the values every compressed peer decodes
            # to, so a mixed fleet stays consistent
            message = dataclasses.replace(message, encoded=None)
        payload = serde.to_bytes(message) if message is not None else b""
        if topic == T_WEIGHTS and self._trace_of.get(conn):
            # open the weights flow: arrow from this send slice to the
            # worker's matching net.recv (run_reader strips the suffix)
            fid = self._tracer.new_flow_id()
            with self._tracer.span("net.send", topic="weights", worker=key):
                self._tracer.flow_start("weights.wire", fid, worker=key)
            payload += _TRACE_CTX.pack(fid, 0)
        return self._send_raw(conn, topic, key, payload)

    def _send_raw(self, conn, topic, key, payload: bytes) -> bool:
        # `dropped_sends` is a data-loss diagnostic: a control frame
        # (PING/CONFIG) hitting a dying connection is not lost training
        # data, and neither is a prediction reply to a vanished client
        count = topic not in (T_PING, T_CONFIG, T_PREDICTION)
        writer = self._writer_of.get(conn)
        if writer is not None:
            # coalesced path: enqueue and return — the writer thread
            # ships batches in scatter-gather syscalls.  Wire-byte /
            # telemetry accounting happens HERE at enqueue time, so an
            # arm with coalescing on is number-for-number comparable to
            # one with it off (bench wire_ab).  PINGs are advisory:
            # regenerated next interval, so a full queue drops them
            # (typed counter) instead of blocking the heartbeat thread.
            if not writer.send(topic, key, payload,
                               advisory=topic == T_PING):
                self.dropped_sends += count
                if writer.dead:
                    force_close(conn)   # reader wakes -> cleanup/eviction
                return False
        else:
            lock = self._send_lock.get(conn)
            if lock is None:
                self.dropped_sends += count
                return False
            try:
                locked_send(conn, lock, topic, key, payload)
            except (ConnectionError, OSError):
                self.dropped_sends += count
                force_close(conn)   # wake the reader -> cleanup/eviction
                return False
        with self._wire_lock:
            self.wire_bytes[topic] = (self.wire_bytes.get(topic, 0)
                                      + _FRAME.size + len(payload))
        if self._telemetry.enabled:
            frames, nbytes = self._m_sent[topic]
            frames.inc()
            nbytes.inc(_FRAME.size + len(payload))
        if FLIGHT.enabled and topic in (T_WEIGHTS, T_GRADIENTS):
            # only the data-plane topics: a PING every few seconds
            # would evict the interesting events from a quiet ring
            FLIGHT.record("net.send", topic=TOPIC_NAMES[topic],
                          peer=key, bytes=len(payload))
        return True

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return
            if self._stop.is_set():
                # raced close(): the listener accepted this connection
                # before it was torn down — it must not outlive close()
                force_close(conn)
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._cv:
                # per-connection registries are written under the state
                # lock; the heartbeat/send threads iterate them
                self._send_lock[conn] = OrderedLock("ServerBridge.send")
                if self._coalesce:
                    self._writer_of[conn] = FrameWriter(
                        conn, telemetry=self._telemetry)
                self._last_recv[conn] = time.monotonic()
            t = threading.Thread(target=self._reader, args=(conn,),
                                 daemon=True, name="kps-net-reader")
            t.start()
            # prune finished readers so worker churn over a long
            # rebalance run doesn't accumulate dead Thread objects
            with self._cv:
                self._reader_threads = [r for r in self._reader_threads
                                        if r.is_alive()] + [t]

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self._hb_interval):
            now = time.monotonic()
            for conn in list(self._send_lock):
                silent = now - self._last_recv.get(conn, now)
                if (self._hb_timeout is not None
                        and silent > self._hb_timeout):
                    # half-open: no FIN will ever come; force the
                    # reader's recv to fail so cleanup runs
                    force_close(conn)
                    continue
                self._send(conn, T_PING, 0)

    def _reader(self, conn: socket.socket) -> None:
        # buffered receive (wire.RecvBuffer): one recv_into brings in
        # every frame the kernel has ready; payloads stay zero-copy
        # views into the buffer
        rbuf = RecvBuffer(conn)
        try:
            while not self._stop.is_set():
                frame = rbuf.recv_frame()
                if frame is None:
                    break
                self._last_recv[conn] = time.monotonic()
                topic, key, payload = frame
                with self._wire_lock:
                    self.wire_bytes[topic] = (
                        self.wire_bytes.get(topic, 0)
                        + _FRAME.size + len(payload))
                if self._telemetry.enabled:
                    frames, nbytes = self._m_recv[topic]
                    frames.inc()
                    nbytes.inc(_FRAME.size + len(payload))
                if topic == T_HELLO:
                    (n,) = struct.unpack_from("<q", payload, 0)
                    ids = struct.unpack_from(f"<{n}q", payload, 8)
                    # negotiation: use our codec iff the peer asked for
                    # the SAME one (old peers send no trailer -> NONE)
                    peer = _read_codec_trailer(payload, 8 + 8 * n)
                    negotiated = (self.codec if peer == self.codec
                                  else CODEC_SPEC_NONE)
                    # trace negotiation: ON iff the peer offered AND our
                    # tracer is on (old peers send no flag -> off)
                    trace_on = (_read_trace_flag(
                        payload, 8 + 8 * n + _CODEC_TRAILER.size)
                        and self._tracer.enabled)
                    with self._cv:
                        # negotiation results land under the state lock
                        # BEFORE T_CONFIG goes out: once the peer sees
                        # CONFIG it may talk coded frames, and the send
                        # paths read these dicts from other threads
                        self._codec_of[conn] = negotiated
                        self._trace_of[conn] = trace_on
                    # shm negotiation: the offer rides CONFIG only when
                    # the peer asked — worker handshakes stay
                    # byte-identical to every earlier version
                    if _read_agg_flag(payload, 8 + 8 * n
                                      + _CODEC_TRAILER.size
                                      + _TRACE_TRAILER.size
                                      + _SHM_TRAILER.size):
                        self._agg_conns.add(conn)
                    shm_tail = b""
                    if _read_shm_flag(payload, 8 + 8 * n
                                      + _CODEC_TRAILER.size
                                      + _TRACE_TRAILER.size):
                        chan = self._offer_shm(conn)
                        shm_tail = (_SHM_OFFER.pack(0, b"", b"")
                                    if chan is None else
                                    _SHM_OFFER.pack(
                                        1, chan.nonce,
                                        # pscheck: disable=PS103 (segment name is a fresh control string, not message parts)
                                        chan.name.encode("ascii")))
                    # T_CONFIG goes out BEFORE the ids are registered:
                    # once registered, the producer thread may race data
                    # rows onto this connection, and the worker-side
                    # handshake relies on T_CONFIG being the first
                    # non-PING frame (per-connection FIFO).  Payload:
                    # PING cadence (0.0 = no heartbeats; the worker must
                    # not time out at all) + the run id + the negotiated
                    # codec + the trace answer (old workers unpack_from
                    # past both trailers).
                    self._send_raw(conn, T_CONFIG, 0,
                                   struct.pack("<dq",
                                               self._hb_interval or 0.0,
                                               self.run_id)
                                   + _CODEC_TRAILER.pack(
                                       negotiated.codec_id,
                                       negotiated.param)
                                   + _TRACE_TRAILER.pack(int(trace_on))
                                   + shm_tail)
                    with self._cv:
                        for w in ids:
                            self._conn_of[w] = conn
                        self._cv.notify_all()
                    if FLIGHT.enabled:
                        FLIGHT.record("net.hello", workers=list(ids))
                    if self.on_hello is not None:
                        self.on_hello(list(ids))
                elif topic == T_READY:
                    with self._cv:
                        self._ready.add(key)
                        self._cv.notify_all()
                    if self.on_ready is not None:
                        self.on_ready(key)
                elif topic == T_PONG:
                    pass            # liveness already stamped above
                elif topic == T_GRADIENTS and self._fabric is not None:
                    fid = None
                    if self._trace_of.get(conn):
                        # strip the trace suffix BEFORE decoding —
                        # compressed frames hand their whole tail to
                        # unpack_parts, which must not see it
                        (fid, _parent) = _TRACE_CTX.unpack_from(
                            payload, len(payload) - _TRACE_CTX.size)
                        payload = payload[:len(payload) - _TRACE_CTX.size]
                    msg = serde.from_bytes(payload)
                    if FLIGHT.enabled:
                        FLIGHT.record(
                            "net.recv", topic="gradients",
                            worker=getattr(msg, "worker_id", key),
                            clock=getattr(msg, "vector_clock", -1))
                    if fid is not None:
                        with self._tracer.span("net.recv",
                                               topic="gradients"):
                            self._tracer.flow_step("delta.wire", fid)
                        # frozen dataclass: tests construct messages
                        # positionally, so the context rides as a
                        # dynamic attribute, not a schema field
                        object.__setattr__(msg, "trace", fid)
                    self._fabric.send(fabric_mod.GRADIENTS_TOPIC, 0, msg)
                elif topic == T_PREDICT:
                    self._handle_predict(conn, key, payload)
        except (ConnectionError, OSError):
            pass
        finally:
            self._cleanup_conn(conn)

    def _handle_predict(self, conn, key: int, payload: bytes) -> None:
        engine = self._serving
        if engine is None:
            # a predict frame on a training-only bridge: explicit
            # failure beats a silent hang on the client side
            self._send_raw(conn, T_PREDICTION, key,
                           encode_prediction(PREDICT_FAILED))
            return
        from kafka_ps_tpu.serving.policy import OverloadedError, ReadBound
        try:
            x, min_clock, max_age_s, model_id = \
                decode_predict_request(payload)
            bound = ReadBound(min_clock=min_clock, max_age_s=max_age_s)
        except Exception:  # noqa: BLE001 — malformed frame, not our crash
            self._send_raw(conn, T_PREDICTION, key,
                           encode_prediction(PREDICT_FAILED))
            return

        def reply(result, conn=conn, key=key):
            self._send_raw(conn, T_PREDICTION, key, _encode_result(result))

        try:
            engine.submit(x, bound, reply, model_id=model_id)
        except OverloadedError:
            # admission shed happens synchronously in submit — the fast
            # rejection the bounded queue exists for: the reader thread
            # answers immediately instead of parking work it cannot serve
            self._send_raw(conn, T_PREDICTION, key,
                           encode_prediction(PREDICT_OVERLOADED))
        except (ValueError, RuntimeError):
            # unknown model id, or engine already closed (shutdown race)
            self._send_raw(conn, T_PREDICTION, key,
                           encode_prediction(PREDICT_FAILED))

    def _offer_shm(self, conn):
        """Create a per-connection shm channel plus its serve thread;
        None (a declined offer, the client stays on sockets) when shm is
        disabled here, no serving engine is attached, or the segment
        cannot be created (e.g. /dev/shm exhausted)."""
        if not self._shm_enabled or self._serving is None:
            return None
        try:
            from kafka_ps_tpu.serving.shm import ShmChannel
            chan = ShmChannel.create()
        except Exception:  # noqa: BLE001 — degrade, never fail the HELLO
            return None
        t = threading.Thread(target=self._shm_serve, args=(chan,),
                             daemon=True, name="kps-shm-serve")
        with self._cv:
            self._shm_of[conn] = chan
            self._shm_threads.append(t)
        t.start()
        return chan

    def _shm_serve(self, chan) -> None:
        """Per-channel poll loop: pop the pending request, submit it to
        the engine async (same as the socket path — this thread never
        blocks on a batch window), publish the reply from the engine's
        callback.  Depth-1 protocol, so an unanswered seq backpressures
        exactly one client."""
        from kafka_ps_tpu.serving.policy import OverloadedError, ReadBound
        engine = self._serving
        while not self._stop.is_set() and not chan.closed:
            got = chan.serve_once()
            if got is None:
                time.sleep(0.0002)
                continue
            seq, raw = got
            try:
                x, min_clock, max_age_s, model_id = \
                    decode_predict_request(raw)
                bound = ReadBound(min_clock=min_clock, max_age_s=max_age_s)
            except Exception:  # noqa: BLE001 — malformed payload
                chan.respond(seq, encode_prediction(PREDICT_FAILED))
                continue

            def reply(result, seq=seq):
                chan.respond(seq, _encode_result(result))
                self._m_shm.inc()
                if FLIGHT.enabled:
                    FLIGHT.record("serving.batch", n=1, mode="shm")

            try:
                engine.submit(x, bound, reply, model_id=model_id)
            except OverloadedError:
                reply(OverloadedError("shed"))
            except (ValueError, RuntimeError) as err:
                reply(err)

    def _cleanup_conn(self, conn: socket.socket) -> None:
        """Purge a dead connection's registrations and surface the
        disconnect — without this the consistency gate waits forever for
        a dead worker's gradients (ADVICE r2 medium)."""
        try:
            conn.close()
        except OSError:
            pass
        writer = self._writer_of.pop(conn, None)
        if writer is not None:
            # the connection is dead — discard the queue, don't flush
            # (a writer mid-sendmsg fails on the closed fd and exits)
            writer.close(flush=False, timeout=2.0)
        with self._cv:
            ids = [w for w, c in self._conn_of.items() if c is conn]
            for w in ids:
                del self._conn_of[w]
                self._ready.discard(w)
            was_agg = conn in self._agg_conns
            self._agg_conns.discard(conn)
            self._send_lock.pop(conn, None)
            self._last_recv.pop(conn, None)
            self._codec_of.pop(conn, None)
            self._trace_of.pop(conn, None)
            chan = self._shm_of.pop(conn, None)
            self._cv.notify_all()
        if chan is not None:
            chan.close()    # wakes + ends the kps-shm-serve thread
        if FLIGHT.enabled and ids:
            FLIGHT.record("net.disconnect", workers=ids, agg=was_agg)
        if was_agg:
            # an aggregator relay died, not its member workers: the
            # members are alive behind it, buffering resends for the
            # restarted relay — evicting them would shrink the gate on
            # a transient.  Their registrations are purged above; a
            # re-HELLO from the restarted relay re-registers them.
            return
        if ids and not self._stop.is_set() and self.on_disconnect is not None:
            self.on_disconnect(ids)


class WorkerBridge:
    """Worker-process side: connects to the server, registers its
    logical worker ids, feeds received INPUT_DATA rows into the local
    buffers, delivers received WEIGHTS into the local fabric, and routes
    the workers' GRADIENTS sends back over the socket."""

    def __init__(self, host: str, port: int, worker_ids: list[int],
                 connect_timeout: float = 30.0,
                 heartbeat_timeout: float | None = None,
                 codec: CodecSpec | None = None,
                 tracer=None, telemetry=None,
                 aggregator: bool = False,
                 coalesce: bool = True):
        """`heartbeat_timeout`: seconds of total server silence before
        the connection is declared dead (only sensible when the server
        PINGs, i.e. it was built with a heartbeat_interval — otherwise a
        quiet-but-alive server would be misread as gone).
        `codec`: this worker process's `--compress` choice, offered on
        HELLO; `self.negotiated` holds what the server agreed to (NONE
        against an old or differently-configured server) — the caller
        builds its gradient compressors from THAT, not the flag.
        `tracer`: offering tracer — when it is on AND the server answers
        the offer, `self.trace_negotiated` goes True and WEIGHTS /
        GRADIENTS frames carry the 16-byte trace context.
        `aggregator`: HELLO as a per-host aggregation relay for
        `worker_ids` (the MEMBER workers behind it, docs/AGGREGATION
        .md): the server routes their weights/data through this
        connection, may group releases into T_WEIGHTS_AGG frames, and
        treats a disconnect as a relay restart instead of a member
        failure.
        `coalesce`: queue outgoing frames behind a wire.FrameWriter
        (scatter-gather batches from a dedicated writer thread,
        docs/WIRE.md); False is the classic locked-sendall-per-frame
        path (`--no-wire-coalesce`)."""
        self.worker_ids = list(worker_ids)
        self.aggregator = bool(aggregator)
        # relay hook (agg/relay.py): when set, run_reader hands raw
        # pass-through frames (data rows, per-worker weights, grouped
        # weights) to it BEFORE any decode; a True return consumes the
        # frame.  None keeps the classic worker-process dispatch.
        self.raw_forward = None
        self._heartbeat_timeout = heartbeat_timeout
        self.codec = codec if codec is not None else CODEC_SPEC_NONE
        self.negotiated = CODEC_SPEC_NONE
        self._tracer = tracer or NULL_TRACER
        self._telemetry = telemetry or NULL_TELEMETRY
        self.trace_negotiated = False
        self._m_sent, self._m_recv = _frame_counters(self._telemetry)
        self.wire_bytes: dict[int, int] = {}
        self._wire_lock = OrderedLock("WorkerBridge.wire")
        # retry: the server process may still be importing/binding when
        # this process is already up (both launched together, run.sh-style)
        deadline = time.monotonic() + connect_timeout
        while True:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=5.0)
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._send_lock = OrderedLock("WorkerBridge.send")
        self._stop = threading.Event()
        self.disconnected = threading.Event()
        # set by a mid-stream GOODBYE config: the run ended cleanly,
        # the EOF that follows is not a crash (read before
        # `disconnected` by the aggregated worker supervisor)
        # pscheck: disable=PS201 (monotonic bool set by the reader thread; pollers tolerate one stale read)
        self.run_over = False
        self.server_run_id: int | None = None
        payload = (struct.pack(f"<q{len(self.worker_ids)}q",
                               len(self.worker_ids), *self.worker_ids)
                   + _CODEC_TRAILER.pack(self.codec.codec_id,
                                         self.codec.param)
                   + _TRACE_TRAILER.pack(int(self._tracer.enabled)))
        if self.aggregator:
            # trailers are positional: the agg byte sits after the shm
            # slot, so an explicit not-requesting-shm byte fills it
            payload += _SHM_TRAILER.pack(0) + _AGG_TRAILER.pack(1)
        locked_send(self._sock, self._send_lock, T_HELLO, 0, payload)
        # synchronous handshake: the server replies T_CONFIG before it
        # registers our ids (net.ServerBridge._reader), so it is the
        # first non-PING frame on the wire — read it HERE, before any
        # reader thread exists, so callers know the server's run id and
        # ping cadence before deciding what local state to restore
        self._sock.settimeout(10.0)
        try:
            while True:
                frame = recv_frame(self._sock)
                if frame is None:
                    raise ConnectionError("server closed during handshake")
                topic, _key, pl = frame
                if topic == T_PING:
                    locked_send(self._sock, self._send_lock, T_PONG, 0)
                    continue
                if topic == T_CONFIG:
                    interval, run_id = struct.unpack_from("<dq", pl, 0)
                    self.server_run_id = int(run_id)
                    # a 16-byte CONFIG is an old server: no negotiation,
                    # stay uncompressed (the server can't decode tid 4/5)
                    self.negotiated = _read_codec_trailer(pl, 16)
                    # trace answer sits after the codec trailer; an old
                    # server never sends it -> tracing stays off-wire
                    self.trace_negotiated = _read_trace_flag(
                        pl, 16 + _CODEC_TRAILER.size)
                    break
                raise ConnectionError(
                    f"expected T_CONFIG during handshake, got topic {topic}")
        except socket.timeout as e:
            raise ConnectionError("no T_CONFIG from server") from e
        # steady state: the configured read timeout (a half-open server
        # link then surfaces as socket.timeout in the read loop —
        # TimeoutError is an OSError, same exit path as a reset), or
        # blocking forever when no timeout was requested; the advertised
        # cadence may floor or disable it
        self._sock.settimeout(heartbeat_timeout)
        self._apply_server_ping_interval(interval)
        # the coalescing writer starts AFTER the synchronous handshake:
        # HELLO went out on the locked path above, and nothing else can
        # have been enqueued yet, so per-connection frame order is
        # preserved across the switch
        self._writer = (FrameWriter(self._sock,
                                    telemetry=self._telemetry)
                        if coalesce else None)

    def _enqueue(self, topic: int, key: int, payload: bytes = b"",
                 advisory: bool = False) -> None:
        """Send one frame via the coalescing writer when enabled, the
        locked direct path otherwise.  A failed protocol enqueue (dead
        writer, or the backpressure deadline expired) raises
        ConnectionError — the exact failure surface locked_send has —
        so caller semantics are identical on both paths."""
        if self._writer is not None:
            if not self._writer.send(topic, key, payload,
                                     advisory=advisory) and not advisory:
                raise ConnectionError("wire writer closed")
            return
        locked_send(self._sock, self._send_lock, topic, key, payload)

    def send_gradients(self, key: int, message) -> None:
        """Serialize one gradient message (full-range, or a per-shard
        dense/sparse slice — serde handles both) and send it on THIS
        bridge's socket.  The make_fabric() path calls it for the
        single-connection deployment; a sharded worker process calls it
        directly as the ShardRouter's per-shard send hook, one bridge
        per shard (runtime/sharding.py, docs/SHARDING.md)."""
        payload = serde.to_bytes(message)
        if self.trace_negotiated:
            # open the delta flow: this send slice is the wire
            # segment's source; the server's net.recv is the first
            # step of the arrow chain.  Each shard slice gets its OWN
            # flow id — one Perfetto arrow chain per routed slice.
            fid = self._tracer.new_flow_id()
            with self._tracer.span(
                    "net.send", topic="gradients",
                    worker=getattr(message, "worker_id", key)):
                self._tracer.flow_start("delta.wire", fid)
            payload += _TRACE_CTX.pack(fid, 0)
        self._enqueue(T_GRADIENTS, key, payload)
        with self._wire_lock:
            self.wire_bytes[T_GRADIENTS] = (
                self.wire_bytes.get(T_GRADIENTS, 0)
                + _FRAME.size + len(payload))
        if self._telemetry.enabled:
            frames, nbytes = self._m_sent[T_GRADIENTS]
            frames.inc()
            nbytes.inc(_FRAME.size + len(payload))
        if FLIGHT.enabled:
            FLIGHT.record("net.send", topic="gradients",
                          worker=getattr(message, "worker_id", key),
                          clock=getattr(message, "vector_clock", -1),
                          bytes=len(payload))

    def send_payload(self, key: int, payload: bytes) -> None:
        """Ship one PRE-serialized gradient-topic frame — the relay's
        composite send (agg/relay.py), which serializes the composite
        exactly once for both the wire-bytes accounting and the send.
        The trace suffix is mandatory when negotiated: the server's
        reader strips 16 bytes from every T_GRADIENTS frame on a
        trace-negotiated connection, composite or not."""
        if self.trace_negotiated:
            fid = self._tracer.new_flow_id()
            with self._tracer.span("net.send", topic="gradients",
                                   worker=key):
                self._tracer.flow_start("delta.wire", fid)
            payload += _TRACE_CTX.pack(fid, 0)
        self._enqueue(T_GRADIENTS, key, payload)
        with self._wire_lock:
            self.wire_bytes[T_GRADIENTS] = (
                self.wire_bytes.get(T_GRADIENTS, 0)
                + _FRAME.size + len(payload))
        if self._telemetry.enabled:
            frames, nbytes = self._m_sent[T_GRADIENTS]
            frames.inc()
            nbytes.inc(_FRAME.size + len(payload))
        if FLIGHT.enabled:
            FLIGHT.record("net.send", topic="gradients", worker=key,
                          bytes=len(payload))

    def make_fabric(self) -> fabric_mod.Fabric:
        """Local fabric whose GRADIENTS sends cross the socket (the
        worker's view of the broker)."""
        bridge = self

        class BridgedFabric(fabric_mod.Fabric):
            def send(self, topic, key, message):
                if topic == fabric_mod.GRADIENTS_TOPIC:
                    bridge.send_gradients(key, message)
                else:
                    super().send(topic, key, message)

        # pscheck: disable=PS201 (make_fabric publishes before run_reader starts - the handshake orders it)
        self.fabric = BridgedFabric()
        return self.fabric

    def set_weights_sink(self, sink) -> None:
        """Deliver received WEIGHTS frames into `sink.send(topic, key,
        msg)` instead of a make_fabric() fabric.  A sharded worker
        process plugs a per-shard collector here so each bridge's
        weights SLICES feed runtime/sharding.WeightsAssembler.offer
        and only the reassembled full-range message reaches the
        workers' local fabric (docs/SHARDING.md)."""
        self.fabric = sink

    def _apply_server_ping_interval(self, interval: float) -> None:
        """React to the server's advertised PING cadence (T_CONFIG,
        consumed in the constructor handshake right after HELLO).  The
        worker's `heartbeat_timeout` and the
        server's ping interval are independent flags in different
        processes; a timeout below a few pings false-declares a healthy
        server dead and kills the whole worker process (ADVICE r3) — so
        the effective read timeout is floored at 3 pings, and disabled
        entirely when the server does not ping at all."""
        if self._heartbeat_timeout is None:
            return
        if interval <= 0.0:
            print(f"warning: server sends no heartbeats; ignoring "
                  f"heartbeat_timeout={self._heartbeat_timeout}s",
                  file=sys.stderr, flush=True)
            self._sock.settimeout(None)
            return
        floor = 3.0 * interval
        effective = self._heartbeat_timeout
        if effective < floor:
            print(f"warning: heartbeat_timeout={effective}s is under 3x "
                  f"the server ping interval ({interval}s); using "
                  f"{floor}s", file=sys.stderr, flush=True)
            effective = floor
        self._sock.settimeout(effective)

    def mark_ready(self, worker: int) -> None:
        self._enqueue(T_READY, worker)

    def run_reader(self, buffers: dict[int, object]) -> None:
        """Blocking read loop (call on a dedicated thread or the main
        thread): dispatches INPUT_DATA to `buffers[worker].add` (batched
        frames to `.add_many`) and WEIGHTS into the local fabric.
        Returns on EOF (server done)."""
        rbuf = RecvBuffer(self._sock)
        try:
            while not self._stop.is_set():
                frame = rbuf.recv_frame()
                if frame is None:
                    break
                topic, key, payload = frame
                with self._wire_lock:
                    self.wire_bytes[topic] = (
                        self.wire_bytes.get(topic, 0)
                        + _FRAME.size + len(payload))
                if self._telemetry.enabled:
                    frames, nbytes = self._m_recv[topic]
                    frames.inc()
                    nbytes.inc(_FRAME.size + len(payload))
                if topic == T_PING:
                    # a PONG is liveness, regenerated on the next PING:
                    # advisory — never blocks the reader on backpressure
                    self._enqueue(T_PONG, 0, advisory=True)
                    continue
                if topic == T_CONFIG:
                    # normally consumed by the constructor handshake;
                    # tolerate a re-sent config mid-stream (same <dq>
                    # decode — run id changes are not acted on, except
                    # the GOODBYE sentinel announcing a clean end-of-run
                    (interval, rid) = struct.unpack_from("<dq", payload, 0)
                    if rid == GOODBYE_RUN_ID:
                        self.run_over = True
                        continue
                    self._apply_server_ping_interval(interval)
                    continue
                fid = None
                if topic == T_WEIGHTS and self.trace_negotiated:
                    (fid, _parent) = _TRACE_CTX.unpack_from(
                        payload, len(payload) - _TRACE_CTX.size)
                    payload = payload[:len(payload) - _TRACE_CTX.size]
                if (self.raw_forward is not None
                        and topic in (T_DATA, T_DATA_BATCH,
                                      T_WEIGHTS, T_WEIGHTS_AGG)):
                    # aggregator relay: pass-through frames forward as
                    # raw bytes (no decode — a relay needs no jax, and
                    # the members receive bit-identical payloads).  The
                    # trace suffix stripped above belongs to the
                    # server→relay hop; forward_frame opens a fresh
                    # flow per member on the downstream re-broadcast.
                    if self.raw_forward(topic, key, bytes(payload)):
                        if fid is not None:
                            with self._tracer.span("net.recv",
                                                   topic="weights",
                                                   worker=key):
                                self._tracer.flow_end("weights.wire",
                                                      fid)
                        continue
                if topic == T_DATA_BATCH:
                    (nrows,) = struct.unpack_from("<q", payload, 0)
                    if nrows < 0:
                        # columnar layout (serde.encode_labeled_rows):
                        # packed ndarray columns, one decode per BATCH
                        buffers[key].add_many(
                            serde.decode_labeled_rows(payload))
                        continue
                    # legacy per-row layout from an older server
                    off = 8
                    rows = []
                    for _ in range(nrows):
                        # pscheck: disable=PS204 (legacy framing: old servers length-prefixed each row with an i32; the current encoder is columnar and never packs this)
                        (blen,) = struct.unpack_from("<i", payload, off)
                        off += 4
                        row = serde.from_bytes(payload[off:off + blen])
                        off += blen
                        rows.append((row.features, row.label))
                    buffers[key].add_many(rows)
                    continue
                msg = serde.from_bytes(payload)
                if topic == T_DATA:
                    buffers[key].add(msg.features, msg.label)
                elif topic == T_WEIGHTS:
                    if FLIGHT.enabled:
                        FLIGHT.record(
                            "net.weights_recv", worker=key,
                            clock=getattr(msg, "vector_clock", -1))
                    if fid is not None:
                        # close the weights flow on the receiving slice
                        with self._tracer.span("net.recv",
                                               topic="weights",
                                               worker=key):
                            self._tracer.flow_end("weights.wire", fid)
                        object.__setattr__(msg, "trace", fid)
                    self.fabric.send(fabric_mod.WEIGHTS_TOPIC, key, msg)
        except (ConnectionError, OSError):
            pass
        finally:
            self.disconnected.set()

    def close(self) -> None:
        self._stop.set()
        if self._writer is not None:
            # flush-before-close: queued frames (a final gradient, a
            # READY) reach the wire before the socket goes down
            self._writer.close(flush=True)
        try:
            self._sock.close()
        except OSError:
            pass


class PredictClient:
    """Remote prediction client for the serving plane (docs/SERVING.md).

    NOT a worker: it sends no HELLO, registers no worker ids, and so
    never receives weights or data frames — the connection carries only
    PREDICT/PREDICTION (plus the server's PINGs, answered here to stay
    alive under heartbeat-timeout enforcement).  Synchronous: one
    outstanding request per client; run several clients for concurrency.

    `reconnect=True` survives a dropped server connection the way the
    split deployment's worker processes do (cli/socket_mode supervise):
    on ConnectionError the client re-dials with exponential backoff up
    to `reconnect_timeout` seconds and replays the in-flight request on
    the fresh connection.  An OVERLOADED/STALE reply is a healthy
    connection — those never trigger a re-dial.
    """

    def __init__(self, host: str, port: int, timeout: float = 30.0, *,
                 reconnect: bool = False, reconnect_timeout: float = 10.0,
                 model_id: int = 0, shm: bool = False):
        self._host, self._port = host, port
        self._timeout = timeout
        self._reconnect = reconnect
        self._reconnect_timeout = reconnect_timeout
        self._model_id = int(model_id)
        self._send_lock = OrderedLock("PredictClient.send")
        self._req = 0
        self._closed = False
        self.reconnects = 0          # successful re-dials (ops/test surface)
        self._shm = bool(shm)
        self._chan = None            # ShmChannel once negotiated
        self._sock = self._dial()
        if self._shm:
            self._chan = self._negotiate_shm()

    def _dial(self) -> socket.socket:
        sock = socket.create_connection((self._host, self._port),
                                        timeout=5.0)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(self._timeout)
        return sock

    def _negotiate_shm(self):
        """Ask the server for a shared-memory channel: an empty-ids
        HELLO carrying the shm request trailer, answered by a CONFIG
        whose offer names the segment (docs/SERVING.md, "Dispatch
        economics").  ANY failure — legacy server (no offer bytes),
        declined offer, remote peer (the segment name does not exist on
        this host), nonce mismatch — returns None and the client stays
        on the socket it already holds.  Registering zero worker ids
        keeps this connection invisible to the weights/data routing,
        exactly like a plain predict-only connection."""
        try:
            locked_send(self._sock, self._send_lock, T_HELLO, 0,
                        struct.pack("<q", 0)
                        + _CODEC_TRAILER.pack(CODEC_SPEC_NONE.codec_id,
                                              CODEC_SPEC_NONE.param)
                        + _TRACE_TRAILER.pack(0)
                        + _SHM_TRAILER.pack(1))
            while True:
                frame = recv_frame(self._sock)
                if frame is None:
                    return None
                topic, _key, payload = frame
                if topic == T_PING:
                    locked_send(self._sock, self._send_lock, T_PONG, 0)
                    continue
                if topic != T_CONFIG:
                    continue
                offer = _read_shm_offer(
                    payload,
                    16 + _CODEC_TRAILER.size + _TRACE_TRAILER.size)
                if offer is None:
                    return None
                name, nonce = offer
                from kafka_ps_tpu.serving.shm import ShmChannel
                return ShmChannel.attach(name, nonce)
        except Exception:  # noqa: BLE001 — every failure means sockets
            return None

    def _drop_chan(self) -> None:
        chan, self._chan = self._chan, None
        if chan is not None:
            try:
                chan.close()
            except Exception:  # noqa: BLE001 — already torn down
                pass

    def _redial(self) -> None:
        """Replace the dead socket, backing off exponentially (0.05 s
        doubling to 1 s) until `reconnect_timeout` is spent."""
        try:
            force_close(self._sock)
        except OSError:
            pass
        deadline = time.monotonic() + self._reconnect_timeout
        backoff = 0.05
        while not self._closed:
            try:
                self._sock = self._dial()
                self.reconnects += 1
                if self._shm:
                    # the old segment died with the old server process;
                    # negotiate a fresh channel (or fall back) before
                    # the replayed request goes out
                    self._drop_chan()
                    self._chan = self._negotiate_shm()
                return
            except OSError as err:
                if time.monotonic() + backoff > deadline:
                    raise ConnectionError(
                        f"serving endpoint {self._host}:{self._port} did "
                        f"not come back within {self._reconnect_timeout}s"
                    ) from err
                time.sleep(backoff)
                backoff = min(backoff * 2, 1.0)
        raise ConnectionError("client closed during reconnect")

    def predict(self, x, min_clock: int | None = None,
                max_age_s: float | None = None,
                model_id: int | None = None):
        """(label, confidence, vector_clock, wall_time) namedtuple;
        raises serving.policy.StalenessError when the bound rejects and
        serving.policy.OverloadedError when the server shed the request
        (admission queue full — back off and retry)."""
        self._req += 1
        payload = encode_predict_request(
            x, min_clock, max_age_s,
            self._model_id if model_id is None else model_id)
        chan = self._chan
        if chan is not None:
            try:
                raw = chan.rpc(bytes(payload), timeout=self._timeout)
            except Exception:  # noqa: BLE001 — transport died mid-flight:
                # drop the channel and fall through to the socket below
                # (transparent degradation; OVERLOADED/STALE are healthy
                # REPLIES and raise from _decode_reply, not here)
                self._drop_chan()
            else:
                return self._decode_reply(raw, min_clock, max_age_s)
        while True:
            try:
                locked_send(self._sock, self._send_lock, T_PREDICT,
                            self._req, payload)
                return self._await_reply(min_clock, max_age_s)
            except (ConnectionError, OSError):
                if not self._reconnect or self._closed:
                    raise
                # fresh socket, no stale frames: replaying the same
                # request id is unambiguous (prediction is idempotent)
                self._redial()

    def _await_reply(self, min_clock, max_age_s):
        while True:
            frame = recv_frame(self._sock)
            if frame is None:
                raise ConnectionError(
                    "server closed before the prediction arrived")
            topic, key, payload = frame
            if topic == T_PING:
                locked_send(self._sock, self._send_lock, T_PONG, 0)
                continue
            if topic != T_PREDICTION or key != self._req:
                continue            # stray control frame (e.g. CONFIG)
            return self._decode_reply(payload, min_clock, max_age_s)

    def _decode_reply(self, payload, min_clock, max_age_s):
        """One PREDICTION payload (socket frame or shm response buffer)
        to the caller's result: Prediction, or the typed error."""
        status, label, conf, clock, wall = decode_prediction(payload)
        if status == PREDICT_STALE:
            from kafka_ps_tpu.serving.policy import StalenessError
            raise StalenessError(
                f"server rejected the read bound (min_clock="
                f"{min_clock}, max_age_s={max_age_s})",
                min_clock=min_clock, max_age_s=max_age_s)
        if status == PREDICT_OVERLOADED:
            from kafka_ps_tpu.serving.policy import OverloadedError
            raise OverloadedError(
                "server shed the request (admission queue full)")
        if status != PREDICT_OK:
            raise RuntimeError("prediction failed on the server")
        from kafka_ps_tpu.serving.engine import Prediction
        return Prediction(label, conf, clock, wall)

    @property
    def shm_active(self) -> bool:
        """True while predict() rides the shared-memory channel
        (ops/test surface — flips False on fallback)."""
        return self._chan is not None

    def close(self) -> None:
        self._closed = True
        self._drop_chan()
        force_close(self._sock)
