"""Message serialization — the reference's serialization/ package
(JSONSerde.java:12-59 + the polymorphic `_t` type registry,
JSONSerdeCompatible.java:12-23) rebuilt for this runtime.

Two codecs over the same type registry:

  * JSON — wire-compatible in spirit with the reference (every payload
    carries a `_t` discriminator; parameter values keyed by position),
    for debugging and cross-language interop.
  * Binary — length-prefixed struct header + raw little-endian float32
    buffers, zero parsing on the hot path.  This is the DCN transport
    format: a 6150-float WeightsMessage is ~24 KB of contiguous bytes
    instead of ~120 KB of JSON.  The reference ships full-model JSON
    both directions every iteration and lists compression as future
    work (README.md:333) — implemented here as the compressed wire
    types below (tids 4/5) backed by kafka_ps_tpu/compress/
    (bf16 / int8 / topk codecs, docs/COMPRESSION.md): ~6 KB for the
    same message under int8, ~1.2 KB under topk:0.1.

Compressed frames carry the sender's device-encoded parts verbatim
(messages.EncodedValues): header = codec id + flags + param + aux shape
word, body = compress/wire.pack_parts output.  Decoding happens on
device via a lazy compress.codecs import so this module stays
importable without jax for plain frames.

The in-process fabric (runtime/fabric.py) passes objects by reference
and needs neither; serde sits on the process boundary — multi-host
transport, spill-to-disk, cross-language bridges.
"""

from __future__ import annotations

import json
import struct

import numpy as np

from kafka_ps_tpu.compress import wire as cwire
from kafka_ps_tpu.runtime.messages import (CompositeDelta, GradientMessage,
                                           KeyRange, LabeledData,
                                           SparseDeltaMessage,
                                           WeightsMessage)

MAGIC = b"KPS1"

# the `_t` registry (JSONSerdeCompatible.java:12-23); 4/5 are the
# codec-compressed variants of 1/2 (binary only — JSON keeps the
# reference-compatible three); 6 is the range-sharded sparse delta
# slice (docs/SHARDING.md — topk slices routed to the owning shard)
_TYPE_IDS = {
    "WeightsMessage": 1,
    "GradientMessage": 2,
    "LabeledData": 3,
    "CompressedWeights": 4,
    "CompressedGradient": 5,
    "SparseDelta": 6,
    "CompositeDelta": 7,
}
_ID_TYPES = {v: k for k, v in _TYPE_IDS.items()}


# -- JSON codec ------------------------------------------------------------

def to_json(msg) -> str:
    if isinstance(msg, GradientMessage):      # subclass first
        body = {"_t": "GradientMessage", "vectorClock": msg.vector_clock,
                "keyRange": [msg.key_range.start, msg.key_range.end],
                "values": [float(v) for v in msg.values],
                "partitionKey": msg.worker_id}
    elif isinstance(msg, WeightsMessage):
        body = {"_t": "WeightsMessage", "vectorClock": msg.vector_clock,
                "keyRange": [msg.key_range.start, msg.key_range.end],
                "values": [float(v) for v in msg.values]}
    elif isinstance(msg, LabeledData):
        body = {"_t": "LabeledData",
                "inputData": {str(k): float(v)
                              for k, v in msg.features.items()},
                "label": msg.label}
    else:
        raise TypeError(f"unregistered message type {type(msg).__name__}")
    return json.dumps(body)


def from_json(payload: str):
    body = json.loads(payload)
    t = body.get("_t")
    if t == "WeightsMessage":
        return WeightsMessage(
            vector_clock=int(body["vectorClock"]),
            key_range=KeyRange(*body["keyRange"]),
            values=np.asarray(body["values"], dtype=np.float32))
    if t == "GradientMessage":
        return GradientMessage(
            vector_clock=int(body["vectorClock"]),
            key_range=KeyRange(*body["keyRange"]),
            values=np.asarray(body["values"], dtype=np.float32),
            worker_id=int(body["partitionKey"]))
    if t == "LabeledData":
        return LabeledData(
            features={int(k): float(v)
                      for k, v in body["inputData"].items()},
            label=int(body["label"]))
    raise ValueError(f"unknown message type tag {t!r}")


# -- binary codec (the DCN hot path) ---------------------------------------

_HEADER = struct.Struct("<4sBq")          # magic, type id, vector_clock
_RANGE = struct.Struct("<qqq")            # start, end, worker_id
_CODEC_HEADER = struct.Struct("<BBfq")    # codec id, flags, param, aux
# composite delta (tid 7): <B flags><I k members> then k x _MEMBER
# ((worker, clock) vector-clock map), k x _TRACE (two u64 flow-ctx
# words, 0/0 = absent), <I d deltas>, then d x (<I len> + a nested
# to_bytes()-encoded GradientMessage — compressed members reuse the
# tid-5 body verbatim, so the PS103 no-re-encode contract holds)
_COMPOSITE_HEAD = struct.Struct("<BI")    # flags (bit0 = summed), k
_MEMBER = struct.Struct("<qq")            # worker_id, vector_clock
_TRACE = struct.Struct("<QQ")             # flow ctx (matches net trailer)
_CHUNK = struct.Struct("<I")              # nested body length


def to_bytes(msg) -> bytes:
    if isinstance(msg, (GradientMessage, WeightsMessage)):
        grad = isinstance(msg, GradientMessage)
        worker = msg.worker_id if grad else 0
        head = _RANGE.pack(msg.key_range.start, msg.key_range.end, worker)
        enc = getattr(msg, "encoded", None)
        if enc is not None:
            tid = _TYPE_IDS["CompressedGradient" if grad
                            else "CompressedWeights"]
            parts = [np.asarray(p) for p in enc.parts]    # D2H, small
            flags, aux, blob = cwire.pack_parts(
                enc.codec_id, parts, len(msg.key_range))
            return (_HEADER.pack(MAGIC, tid, msg.vector_clock) + head
                    + _CODEC_HEADER.pack(enc.codec_id, flags, enc.param,
                                         aux)
                    + blob)
        tid = _TYPE_IDS["GradientMessage" if grad else "WeightsMessage"]
        values = np.ascontiguousarray(msg.values, dtype="<f4")
        return (_HEADER.pack(MAGIC, tid, msg.vector_clock) + head
                + values.tobytes())
    if isinstance(msg, SparseDeltaMessage):
        head = _RANGE.pack(msg.key_range.start, msg.key_range.end,
                           msg.worker_id)
        idx = np.ascontiguousarray(msg.indices, dtype="<i4")
        vals = np.ascontiguousarray(msg.values, dtype="<f4")
        return (_HEADER.pack(MAGIC, _TYPE_IDS["SparseDelta"],
                             msg.vector_clock) + head
                + struct.pack("<q", len(idx))
                + idx.tobytes() + vals.tobytes())
    if isinstance(msg, CompositeDelta):
        out = [_HEADER.pack(MAGIC, _TYPE_IDS["CompositeDelta"],
                            msg.agg_id),
               _COMPOSITE_HEAD.pack(int(msg.summed), len(msg.members))]
        for w, c in msg.members:
            out.append(_MEMBER.pack(w, c))
        for i in range(len(msg.members)):
            fid = 0
            if not msg.summed:
                fid = int(getattr(msg.deltas[i], "trace", None) or 0)
            out.append(_TRACE.pack(fid, 0))
        out.append(_CHUNK.pack(len(msg.deltas)))
        for d in msg.deltas:
            body = to_bytes(d)
            out.append(_CHUNK.pack(len(body)))
            out.append(body)
        return b"".join(out)
    if isinstance(msg, LabeledData):
        keys = np.fromiter(msg.features.keys(), dtype="<i4",
                           count=len(msg.features))
        vals = np.fromiter(msg.features.values(), dtype="<f4",
                           count=len(msg.features))
        return (_HEADER.pack(MAGIC, _TYPE_IDS["LabeledData"], msg.label)
                + struct.pack("<q", len(keys))
                + keys.tobytes() + vals.tobytes())
    raise TypeError(f"unregistered message type {type(msg).__name__}")


# -- columnar ingest rows (T_DATA_BATCH, runtime/net.py) -------------------
# The batched stream-row frame body.  Legacy layout: <i64 nrows> then
# per row <i32 len> + a nested LabeledData to_bytes() blob — one magic
# header, one dtype dispatch, and one dict build per ROW.  Columnar
# layout (this encoder): one NEGATIVE <i64 -nrows> discriminator (the
# legacy row count is always >= 0, so old receivers can never confuse
# the two), then packed ndarray columns:
#     <i64 -nrows> <i64 total_nnz>
#     <i4 nnz[nrows]>       per-row feature counts
#     <i64 labels[nrows]>   per-row labels (the serde header's i64 slot)
#     <i4 keys[total_nnz]>  concatenated feature indices, row-major
#     <f4 vals[total_nnz]>  concatenated feature values, row-major
# Both sides of net.py accept BOTH layouts; only the sender changed.

_BATCH_HEAD = struct.Struct("<qq")        # -nrows, total_nnz


def encode_labeled_rows(rows) -> bytes:
    """Columnar T_DATA_BATCH body for a sequence of (features: dict,
    label: int) stream rows.  An empty sequence encodes as the legacy
    <i64 0> frame (the -0 discriminator would be ambiguous)."""
    n = len(rows)
    if n == 0:
        return struct.pack("<q", 0)
    nnz = np.empty(n, dtype="<i4")
    labels = np.empty(n, dtype="<q")
    keys_cols = []
    vals_cols = []
    for i, (features, label) in enumerate(rows):
        c = len(features)
        nnz[i] = c
        labels[i] = label
        keys_cols.append(np.fromiter(features.keys(), dtype="<i4",
                                     count=c))
        vals_cols.append(np.fromiter(features.values(), dtype="<f4",
                                     count=c))
    keys = np.concatenate(keys_cols) if keys_cols else \
        np.empty(0, dtype="<i4")
    vals = np.concatenate(vals_cols) if vals_cols else \
        np.empty(0, dtype="<f4")
    return b"".join((_BATCH_HEAD.pack(-n, keys.size),
                     nnz.tobytes(), labels.tobytes(),
                     keys.tobytes(), vals.tobytes()))


def decode_labeled_rows(payload) -> list:
    """Decode a columnar T_DATA_BATCH body (negative-nrows layout)
    back into [(features, label), ...] — the exact rows add_many
    inserts, with Python int keys / float values like the legacy
    per-row LabeledData decode."""
    neg, total = _BATCH_HEAD.unpack_from(payload, 0)
    n = -neg
    off = _BATCH_HEAD.size
    nnz = np.frombuffer(payload, dtype="<i4", offset=off, count=n)
    off += 4 * n
    labels = np.frombuffer(payload, dtype="<q", offset=off, count=n)
    off += 8 * n
    keys = np.frombuffer(payload, dtype="<i4", offset=off, count=total)
    off += 4 * total
    vals = np.frombuffer(payload, dtype="<f4", offset=off, count=total)
    ks, vs = keys.tolist(), vals.tolist()
    rows = []
    pos = 0
    for i in range(n):
        c = int(nnz[i])
        rows.append((dict(zip(ks[pos:pos + c], vs[pos:pos + c])),
                     int(labels[i])))
        pos += c
    return rows


def from_bytes(payload: bytes):
    magic, tid, clock_or_label = _HEADER.unpack_from(payload, 0)
    if magic != MAGIC:
        raise ValueError("bad magic — not a KPS1 message")
    off = _HEADER.size
    name = _ID_TYPES.get(tid)
    if name in ("WeightsMessage", "GradientMessage"):
        start, end, worker = _RANGE.unpack_from(payload, off)
        off += _RANGE.size
        values = np.frombuffer(payload, dtype="<f4", offset=off,
                               count=end - start).copy()
        if name == "WeightsMessage":
            return WeightsMessage(vector_clock=clock_or_label,
                                  key_range=KeyRange(start, end),
                                  values=values)
        return GradientMessage(vector_clock=clock_or_label,
                               key_range=KeyRange(start, end),
                               values=values, worker_id=worker)
    if name in ("CompressedWeights", "CompressedGradient"):
        start, end, worker = _RANGE.unpack_from(payload, off)
        off += _RANGE.size
        codec_id, flags, param, aux = _CODEC_HEADER.unpack_from(payload,
                                                                off)
        off += _CODEC_HEADER.size
        n = end - start
        parts = cwire.unpack_parts(codec_id, flags, aux, payload[off:], n)
        # device decode — deferred import keeps plain frames jax-free
        from kafka_ps_tpu.compress import codecs as _codecs
        values, enc = _codecs.decode_message_parts(codec_id, param,
                                                   parts, n)
        if name == "CompressedWeights":
            return WeightsMessage(vector_clock=clock_or_label,
                                  key_range=KeyRange(start, end),
                                  values=values, encoded=enc)
        return GradientMessage(vector_clock=clock_or_label,
                               key_range=KeyRange(start, end),
                               values=values, encoded=enc,
                               worker_id=worker)
    if name == "SparseDelta":
        start, end, worker = _RANGE.unpack_from(payload, off)
        off += _RANGE.size
        (n,) = struct.unpack_from("<q", payload, off)
        off += 8
        idx = np.frombuffer(payload, dtype="<i4", offset=off,
                            count=n).copy()
        off += 4 * n
        vals = np.frombuffer(payload, dtype="<f4", offset=off,
                             count=n).copy()
        return SparseDeltaMessage(vector_clock=clock_or_label,
                                  key_range=KeyRange(start, end),
                                  indices=idx, values=vals,
                                  worker_id=worker)
    if name == "CompositeDelta":
        flags, k = _COMPOSITE_HEAD.unpack_from(payload, off)
        off += _COMPOSITE_HEAD.size
        members = []
        for _ in range(k):
            members.append(_MEMBER.unpack_from(payload, off))
            off += _MEMBER.size
        fids = []
        for _ in range(k):
            fid, _reserved = _TRACE.unpack_from(payload, off)
            off += _TRACE.size
            fids.append(fid)
        (d,) = _CHUNK.unpack_from(payload, off)
        off += _CHUNK.size
        deltas = []
        for _ in range(d):
            (length,) = _CHUNK.unpack_from(payload, off)
            off += _CHUNK.size
            deltas.append(from_bytes(bytes(payload[off:off + length])))
            off += length
        summed = bool(flags & 1)
        if not summed:
            for m, fid in zip(deltas, fids):
                if fid:
                    object.__setattr__(m, "trace", fid)
        return CompositeDelta(agg_id=clock_or_label,
                              members=tuple(members),
                              deltas=tuple(deltas), summed=summed)
    if name == "LabeledData":
        (n,) = struct.unpack_from("<q", payload, off)
        off += 8
        keys = np.frombuffer(payload, dtype="<i4", offset=off, count=n)
        off += 4 * n
        vals = np.frombuffer(payload, dtype="<f4", offset=off, count=n)
        return LabeledData(
            features={int(k): float(v) for k, v in zip(keys, vals)},
            label=clock_or_label)
    raise ValueError(f"unknown binary type id {tid}")
