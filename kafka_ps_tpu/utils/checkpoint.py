"""Checkpoint / resume — an intentional improvement over the reference.

The reference cold-starts every run: `streams.cleanUp()` wipes local
state (BaseKafkaApp.java:57) and weights live only in processor memory
(ServerProcessor.java:35,57), so a server crash loses the model
(SURVEY §5).  Here the server's full recoverable state — parameter
vector, per-worker vector clocks, iteration count — snapshots to one
.npz atomically (write-temp-then-rename), restoring mid-stream resume.

Durability of the TRAINING WINDOW (VERDICT r2 missing #2): the
reference's workers restore their sliding buffers from the
changelog-backed Kafka Streams state store on partition reassignment
(WorkerApp.java:40-42, retention -1 in dev/env/kafka.env); here the
same property comes from persisting each worker's buffer slab +
insertion IDs + arrival-rate window alongside the weights:

  * in-process runs: `save(path, server, buffers=...)` folds every
    worker's buffer into the one server checkpoint;
  * split deployment: each worker PROCESS owns a local state file
    (`save_worker` / `maybe_restore_worker`, cli/socket_mode.run_worker)
    — the per-host analogue of the per-task changelog restore.
"""

from __future__ import annotations

import json
import os

import numpy as np


def _buffer_items(buffers):
    """Accept list (app.buffers, index = worker id) or dict {id: buf}."""
    if buffers is None:
        return []
    if isinstance(buffers, dict):
        return sorted(buffers.items())
    return list(enumerate(buffers))


def _pack_buffers(arrays: dict, buffers) -> None:
    for w, buf in _buffer_items(buffers):
        st = buf.state()
        for k, v in st.items():
            arrays[f"buf{w}_{k}"] = v


def _unpack_buffers(z, buffers) -> bool:
    """Restore any buffers present in the archive; True if any were."""
    found = False
    for w, buf in _buffer_items(buffers):
        if f"buf{w}_ids" not in z.files:
            continue        # pre-durability checkpoint, or remote worker
        buf.restore_state({k: z[f"buf{w}_{k}"]
                           for k in ("x", "y", "ids", "arrivals")})
        found = True
    return found


def _residual_items(residuals):
    """Accept dict {worker: ErrorFeedback-like} (app.compressors /
    socket_mode's per-process map); None means compression is off."""
    if residuals is None:
        return []
    return sorted(residuals.items())


def _pack_residuals(arrays: dict, residuals) -> None:
    # error-feedback residuals (compress/feedback.py): worker state the
    # same way the buffers are — a resume must carry the exact residual
    # the crash interrupted, or the compressed stream replays biased
    for w, ef in _residual_items(residuals):
        arrays[f"ef{w}_residual"] = ef.state()


def _unpack_residuals(z, residuals) -> None:
    for w, ef in _residual_items(residuals):
        if f"ef{w}_residual" in z.files:
            ef.restore(z[f"ef{w}_residual"])


def _atomic_savez(path: str, arrays: dict) -> None:
    tmp = path + ".tmp.npz"
    np.savez(tmp, **arrays)
    os.replace(tmp, path)


def save(path: str, server, buffers=None, log_offsets=None,
         residuals=None) -> None:
    arrays = {}
    store = getattr(server, "param_store", None)
    if store is not None:
        # tiered residency (kafka_ps_tpu/store/): record which pages
        # were hot/warm/cold plus their heat so recovery resumes with
        # the same residency it crashed with.  Captured BEFORE theta —
        # assembling the full slice below faults every cold page warm,
        # so the other order would record "everything resident" and
        # restores would never re-demote.  Values are tier-invariant,
        # so these arrays can never affect the restored theta — they
        # only skip the policy's warm-up
        reads, writes = store.heat_vectors()
        arrays["tier_residency"] = store.residency_vector()
        arrays["tier_reads"] = reads
        arrays["tier_writes"] = writes
        arrays["tier_page_params"] = np.asarray(store.page_params,
                                                dtype=np.int64)
    arrays.update(
        theta=server.theta,
        clocks=np.asarray(server.tracker.clocks, dtype=np.int64),
        sent=np.asarray([s.weights_message_sent for s in server.tracker.tracker],
                        dtype=bool),
        active=np.asarray([s.active for s in server.tracker.tracker],
                          dtype=bool),
        iterations=np.asarray(server.iterations, dtype=np.int64),
        run_id=np.asarray(server.run_id, dtype=np.int64))
    if log_offsets is not None:
        # durable-log runs: the consumer offsets this snapshot covers
        # ("topic/key" -> next offset) — recovery replays the tail past
        # exactly these (log/durable_fabric.recover)
        arrays["log_offsets"] = np.asarray(json.dumps(log_offsets))
    _pack_buffers(arrays, buffers)
    _pack_residuals(arrays, residuals)
    _atomic_savez(path, arrays)


def restore(path: str, server, buffers=None, residuals=None) -> None:
    with np.load(path) as z:
        if z["theta"].shape != server.theta.shape:
            raise ValueError(
                f"checkpoint theta shape {z['theta'].shape} != model "
                f"{server.theta.shape}")
        if len(z["clocks"]) != len(server.tracker.tracker):
            raise ValueError("checkpoint worker count mismatch")
        server.theta = z["theta"].copy()
        # checkpoints from before worker eviction existed have no
        # `active` field: treat every worker as active
        active = (z["active"] if "active" in z.files
                  else np.ones(len(z["clocks"]), dtype=bool))
        for status, clock, sent, act in zip(server.tracker.tracker,
                                            z["clocks"], z["sent"], active):
            status.vector_clock = int(clock)
            status.weights_message_sent = bool(sent)
            status.active = bool(act)
        server.iterations = int(z["iterations"])
        if "run_id" in z.files:      # pre-run-id checkpoints: keep ours
            server.run_id = int(z["run_id"])
        if "log_offsets" in z.files:
            server.restored_log_offsets = {
                k: int(v) for k, v
                in json.loads(str(z["log_offsets"])).items()}
        store = getattr(server, "param_store", None)
        if store is not None and "tier_residency" in z.files:
            if int(z["tier_page_params"]) != store.page_params:
                raise ValueError(
                    f"checkpoint page size {int(z['tier_page_params'])} "
                    f"!= store page size {store.page_params}")
            # re-apply recorded residency AFTER the theta assignment
            # above scattered the restored values in (every page landed
            # hot/warm); recorded-cold pages are RE-demoted with fresh
            # log appends, so the checkpoint never references cold
            # records a crash may have torn off the log tail
            store.set_residency(z["tier_residency"], z["tier_reads"],
                                z["tier_writes"])
        _unpack_buffers(z, buffers)
        _unpack_residuals(z, residuals)
    # the crash killed every in-flight message; start_training_loop
    # re-SENDS each worker's current clock (at-least-once redelivery,
    # like Kafka's uncommitted-offset replay on rebalance), and a crash
    # resume restarts from the LAST PERIODIC SAVE, so workers may
    # re-log clocks at or below what the surviving log already holds.
    # Record the boundary so the staleness auditor
    # (evaluation/validate.py) exempts exactly that one redelivery per
    # worker instead of flagging it.
    server.record_membership_event("resume", -1)


def maybe_restore(path: str, server, buffers=None, residuals=None) -> bool:
    if os.path.exists(path):
        restore(path, server, buffers=buffers, residuals=residuals)
        return True
    return False


# -- split-mode worker-local state store -------------------------------------

def peek_run_id(path: str) -> int | None:
    """The run id stored in a checkpoint or worker state file, if any.
    A RUN is a fresh server start plus every checkpoint resume of it
    (utils/checkpoint.py persists the id; net.T_CONFIG advertises it):
    worker-local state is only valid within the run that wrote it."""
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        return int(z["run_id"]) if "run_id" in z.files else None


def shard_state_path(checkpoint: str, shard_id: int,
                     num_shards: int) -> str:
    """One checkpoint file per server shard (range sharding,
    docs/SHARDING.md), derived from the job's --checkpoint path.  The
    degenerate N=1 case keeps the plain path — an unsharded run and a
    --shards 1 run read and write the SAME checkpoint."""
    if num_shards == 1:
        return checkpoint
    return f"{checkpoint}.shard{shard_id}of{num_shards}.npz"


def worker_state_path(checkpoint: str, worker_ids) -> str:
    """One state file per worker PROCESS (the ids it hosts), derived
    from the job's --checkpoint path so operators pass a single flag."""
    tag = "-".join(str(w) for w in sorted(worker_ids))
    return f"{checkpoint}.workers-{tag}.npz"


def save_worker(path: str, buffers, run_id: int = 0,
                residuals=None) -> None:
    arrays: dict = {"_worker_state": np.asarray(1, dtype=np.int64),
                    "run_id": np.asarray(run_id, dtype=np.int64)}
    _pack_buffers(arrays, buffers)
    _pack_residuals(arrays, residuals)
    _atomic_savez(path, arrays)


def maybe_restore_worker(path: str, buffers, run_id: int | None = None,
                         residuals=None) -> bool:
    """Restore the buffers (and, when compression is on, the
    error-feedback residuals) — unless `run_id` is given and the file
    was written under a DIFFERENT run (a stale leftover: restoring it
    would seed a fresh run with another run's training window)."""
    if not os.path.exists(path):
        return False
    with np.load(path) as z:
        if run_id is not None:
            stored = int(z["run_id"]) if "run_id" in z.files else None
            if stored != run_id:
                return False
        found = _unpack_buffers(z, buffers)
        _unpack_residuals(z, residuals)
        return found
