"""Checkpoint / resume — an intentional improvement over the reference.

The reference cold-starts every run: `streams.cleanUp()` wipes local
state (BaseKafkaApp.java:57) and weights live only in processor memory
(ServerProcessor.java:35,57), so a server crash loses the model
(SURVEY §5).  Here the server's full recoverable state — parameter
vector, per-worker vector clocks, iteration count — snapshots to one
.npz atomically (write-temp-then-rename), restoring mid-stream resume.
"""

from __future__ import annotations

import os

import numpy as np


def save(path: str, server) -> None:
    tmp = path + ".tmp.npz"
    np.savez(
        tmp,
        theta=server.theta,
        clocks=np.asarray(server.tracker.clocks, dtype=np.int64),
        sent=np.asarray([s.weights_message_sent for s in server.tracker.tracker],
                        dtype=bool),
        active=np.asarray([s.active for s in server.tracker.tracker],
                          dtype=bool),
        iterations=np.asarray(server.iterations, dtype=np.int64))
    os.replace(tmp, path)


def restore(path: str, server) -> None:
    with np.load(path) as z:
        if z["theta"].shape != server.theta.shape:
            raise ValueError(
                f"checkpoint theta shape {z['theta'].shape} != model "
                f"{server.theta.shape}")
        if len(z["clocks"]) != len(server.tracker.tracker):
            raise ValueError("checkpoint worker count mismatch")
        server.theta = z["theta"].copy()
        # checkpoints from before worker eviction existed have no
        # `active` field: treat every worker as active
        active = (z["active"] if "active" in z.files
                  else np.ones(len(z["clocks"]), dtype=bool))
        for status, clock, sent, act in zip(server.tracker.tracker,
                                            z["clocks"], z["sent"], active):
            status.vector_clock = int(clock)
            status.weights_message_sent = bool(sent)
            status.active = bool(act)
        server.iterations = int(z["iterations"])


def maybe_restore(path: str, server) -> bool:
    if os.path.exists(path):
        restore(path, server)
        return True
    return False
