"""Deferred log formatting — the per-node hot path's answer to
eval-bound wall-clock (VERDICT r2 weak #6).

The reference evaluates the full test set inside every iteration and
blocks on the result before logging (LogisticRegressionTaskSpark
.java:186, ServerProcessor.java:158-164).  On TPU the evaluation is an
async jit dispatch — the old loop blocked only because `float(metric)`
sat inside the f-string, and over a tunneled transport EVERY scalar
fetch is a full host round-trip (~100 ms measured).  A DeferredSink
keeps the LINE order of a plain sink while the numeric fields stay
device-resident futures:

  * the training thread only appends — it never fetches;
  * a background drain thread periodically pops the longest ready
    prefix and moves ALL its scalars in ONE stacked device->host
    transfer (N lines cost one round-trip, not 3N), overlapping the
    fetch with further training;
  * flush() forces everything out in one batched fetch (drive loops
    call it on exit so callers always see complete logs).

FIFO is preserved per sink by a ticket turnstile: a batch takes its
ticket atomically with popping its entries (under the pending lock),
formats and fetches OUTSIDE any lock, and emits when the turnstile
reaches its ticket — so a CSV shared by several workers keeps the
arrival order the staleness auditor's tie-breaking relies on
(evaluation/validate.py sorts stably by timestamp, file order breaking
ms collisions), while a slow batch (e.g. the poisoned-batch per-value
fallback, N tunnel round-trips) no longer serializes other batches'
device fetches behind a held emit lock — they fetch concurrently and
only the cheap ordered sink writes queue up.
"""

from __future__ import annotations

import functools
import sys
import threading
from collections import deque

from kafka_ps_tpu.analysis.lockgraph import OrderedCondition, OrderedLock


@functools.lru_cache(maxsize=None)
def _stacker(n: int):
    """Jit'd scalar packer for a fixed batch size.  Eager `jnp.stack`
    would trigger a fresh trace/compile for every distinct batch length
    (and a ~10 ms eager dispatch per op over a tunneled transport);
    bucketing lengths to powers of two keeps it to a handful of cached
    programs."""
    import jax
    import jax.numpy as jnp
    return jax.jit(
        lambda vs: jnp.stack([jnp.asarray(v, jnp.float32) for v in vs]))


# Stacker programs take one argument PER scalar, and XLA compile time
# is superlinear in argument count (measured on the 1-core reference
# box: 256 -> 0.7 s, 1024 -> 8 s, 4096 -> minutes — a max_pending
# backlog flush used to wedge the training thread inside that compile).
# Chunking bounds the largest program at 256 inputs; a backlog fetch
# costs ceil(N/256) transfers instead of one, but every program is
# compiled once and cached.
_MAX_STACK = 256


def _fetch_batched(jax_vals: list) -> list[float]:
    """Chunked stacked device->host transfer for any number of
    scalars."""
    import numpy as np
    out: list[float] = []
    for start in range(0, len(jax_vals), _MAX_STACK):
        chunk = jax_vals[start:start + _MAX_STACK]
        n = 1
        while n < len(chunk):
            n *= 2
        padded = tuple(chunk) + (0.0,) * (n - len(chunk))
        flat = np.asarray(_stacker(n)(padded))
        out.extend(float(flat[i]) for i in range(len(chunk)))
    return out


def _is_jax(value) -> bool:
    return hasattr(value, "is_ready")


def _is_ready(value) -> bool:
    if not _is_jax(value):
        return True                  # plain python number
    try:
        return bool(value.is_ready())
    except Exception:                # deleted/donated buffer etc.
        return True


class DeferredSink:
    """Wraps a line sink; lines may carry unresolved device scalars.

    submit(template, *values): enqueue `template.format(*values)` where
    each value may be a jax scalar — fetched (batched, off-thread) when
    it resolves.  __call__(line): emit an already-formatted line (kept
    in FIFO with deferred entries).  flush(): force-emit everything.
    """

    def __init__(self, sink, max_pending: int = 4096,
                 drain_interval: float = 0.25,
                 idle_exit: float = 10.0):
        self._sink = sink
        self._pending: deque = deque()
        self._max_pending = max_pending
        self._interval = drain_interval
        self._idle_exit = idle_exit
        self._lock = OrderedLock("DeferredSink.pending")  # guards _pending + tickets
        # emission turnstile: tickets are taken under _lock, atomically
        # with popping the entries they cover, so ticket order == entry
        # order; emission happens strictly in ticket order but the
        # formatting (device fetches) between take and emit runs
        # unlocked and concurrent
        self._turn_cv = OrderedCondition("DeferredSink.turn")
        self._next_ticket = 0
        self._turn = 0
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- producer side -----------------------------------------------------

    def submit(self, template: str, *values) -> None:
        with self._lock:
            self._pending.append((template, values))
            n = len(self._pending)
        self._ensure_thread()
        if n > self._max_pending:
            self.flush()             # backlogged: pay one batched fetch

    def __call__(self, line: str) -> None:
        with self._lock:
            if self._pending or self._thread is not None:
                self._pending.append((line, ()))
                return
            # pure-string sink right now: take a ticket so the write
            # lands AFTER any batch a drain/flush already popped (their
            # tickets are earlier) — the FIFO the auditor's tie-breaking
            # relies on, without re-checking under a second lock
            ticket = self._take_ticket_locked()
        self._emit_in_turn(ticket, (line,))

    # -- drain side --------------------------------------------------------

    def _ensure_thread(self) -> None:
        with self._lock:
            t = self._thread
            if t is None or not t.is_alive():
                self._thread = threading.Thread(
                    target=self._drain_loop, daemon=True,
                    name="kps-log-drain")
                self._thread.start()

    def _drain_loop(self) -> None:
        # Exits after _idle_exit seconds with nothing pending (restarted
        # by the next submit): a long-lived process (or a test suite
        # creating many sinks) must not accumulate forever-waking
        # threads — and a daemon thread that keeps dispatching device
        # fetches at interpreter exit dies inside XLA's C++ and aborts
        # the process (the round-4 SIGABRT, docs/TESTING.md).
        idle = 0.0
        while not self._stop.is_set():
            self._wake.wait(timeout=self._interval)
            self._wake.clear()
            try:
                self._drain_ready()
            except Exception as e:   # pragma: no cover - diagnostics
                print(f"log drain error: {e!r}", file=sys.stderr)
            with self._lock:
                if self._pending:
                    idle = 0.0
                    continue
                idle += self._interval
                if idle >= self._idle_exit:
                    if self._thread is threading.current_thread():
                        self._thread = None
                    return

    def _take_ticket_locked(self) -> int:
        """Issue the next turnstile ticket; caller must hold _lock (the
        ticket must be atomic with the pop it covers).  EVERY ticket
        taken must reach _emit_in_turn, even on error — callers wrap the
        formatting in try/finally."""
        ticket = self._next_ticket
        self._next_ticket += 1
        return ticket

    def _emit_in_turn(self, ticket: int, lines) -> None:
        """Write `lines` to the sink when the turnstile reaches
        `ticket`; always advances the turn, so a failed batch cannot
        wedge every later emitter."""
        with self._turn_cv:
            self._turn_cv.wait_for(lambda: self._turn == ticket)
            try:
                for line in lines:
                    self._sink(line)
            finally:
                self._turn += 1
                self._turn_cv.notify_all()

    def _drain_ready(self) -> None:
        with self._lock:
            ready = []
            while self._pending:
                _, values = self._pending[0]
                if not all(_is_ready(v) for v in values):
                    break
                ready.append(self._pending.popleft())
            if not ready:
                return
            ticket = self._take_ticket_locked()
        lines: list[str] = []
        try:
            lines = self._format_entries(ready)
        finally:
            self._emit_in_turn(ticket, lines)

    def _format_entries(self, entries) -> list[str]:
        """Format entries in order, fetching every device scalar they
        reference in ONE stacked transfer (a per-scalar fetch is a full
        tunnel round-trip; N at once cost the same as one).  Runs with
        NO lock held: the poisoned-batch fallback below degrades to N
        per-value round-trips, and those must overlap other batches'
        fetches, not serialize them."""
        jax_vals = [v for _, values in entries for v in values
                    if _is_jax(v)]
        fetched: dict[int, float] = {}
        if jax_vals:
            try:
                flat = _fetch_batched(jax_vals)
                fetched = {id(v): flat[i] for i, v in enumerate(jax_vals)}
            except Exception as e:   # deleted/donated buffer poisoned
                # the batch: fall back to per-value fetch below so the
                # OTHER lines still come out (a nan marks the bad value
                # instead of silently dropping audit-relevant CSV rows)
                print(f"batched log fetch failed ({e!r}); falling back "
                      "to per-value fetch", file=sys.stderr)

        def resolve(v) -> float:
            if not _is_jax(v):
                return float(v)
            if id(v) in fetched:
                return fetched[id(v)]
            try:
                return float(v)
            except Exception:
                return float("nan")

        lines = []
        for template, values in entries:
            if values:
                template = template.format(*(resolve(v) for v in values))
            lines.append(template)
        return lines

    def flush_ready(self) -> None:
        self._drain_ready()

    def flush(self) -> None:
        with self._lock:
            entries = list(self._pending)
            self._pending.clear()
            # a ticket even when empty: flush doubles as an emission
            # barrier — by the time our turn has come and gone, every
            # batch popped before this point has been written
            ticket = self._take_ticket_locked()
        lines: list[str] = []
        try:
            if entries:
                lines = self._format_entries(entries)
        finally:
            self._emit_in_turn(ticket, lines)

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        with self._lock:
            t = self._thread
        if t is not None and t is not threading.current_thread():
            # the drain thread may be mid device-fetch; a process must
            # never finalize while it is inside XLA (SIGABRT) — wait it
            # out (its work is bounded: one batched fetch)
            t.join(timeout=60.0)
        self.flush()
        close = getattr(self._sink, "close", None)
        if close is not None:
            close()


def submit_or_write(log, template: str, *values) -> None:
    """Route a log line through a DeferredSink when the sink supports
    it, else format eagerly (plain sinks, test list-appenders)."""
    if hasattr(log, "submit"):
        log.submit(template, *values)
    else:
        log(template.format(*(float(v) for v in values)))
