"""Configuration — the reference's three config tiers collapsed into dataclasses.

Mirrors the hardcoded constants and CLI defaults of the reference
(BaseKafkaApp.java:25-40, LogisticRegressionTaskSpark.java:32-35,
ServerAppRunner.java:19-26,59-63, WorkerAppRunner.java:17-24,55-58,
WorkerSamplingProcessor.java:21-23, ServerProcessor.java:36,44-49),
but everything the reference hardcodes is configurable here.
"""

from __future__ import annotations

import dataclasses

# Consistency-model constants (ServerProcessor.java:44-49):
#   sequential/BSP == 0, bounded-delay/SSP == k > 0, eventual/ASP == -1
#   (the reference's MAX_DELAY_INFINITY sentinel == the eventual model).
SEQUENTIAL = 0
EVENTUAL = -1


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """LR task shape (LogisticRegressionTaskSpark.java:32-35).

    The parameter vector is flat with (num_classes + 1) * num_features
    coefficient keys followed by (num_classes + 1) intercept keys —
    6*1024 + 6 = 6150 by default.  One extra row because reference labels
    are 1..num_classes and Spark sizes the model 0..max_label
    (LogisticRegressionTaskSpark.java:98-104,122-140).
    """

    num_features: int = 1024
    num_classes: int = 5
    num_max_iter: int = 2       # k local solver steps per iteration
    local_learning_rate: float = 0.5  # step size of the local k-step solver
    hidden_dim: int = 128       # used by the mlp task family only

    @property
    def num_rows(self) -> int:
        return self.num_classes + 1

    @property
    def num_params(self) -> int:
        return self.num_rows * self.num_features + self.num_rows


@dataclasses.dataclass(frozen=True)
class BufferConfig:
    """Dynamic sliding-buffer policy (WorkerAppRunner.java:55-58,
    WorkerSamplingProcessor.java:21-23,115-122)."""

    min_size: int = 128
    max_size: int = 1024
    coefficient: float = 0.3      # -bc: target = clamp(bc * events_per_min, min, max)
    arrival_window: int = 500     # inter-arrival-time window length


@dataclasses.dataclass(frozen=True)
class StreamConfig:
    """Producer pacing (CsvProducer.java:73-83, ServerAppRunner.java:60)."""

    time_per_event_ms: float = 200.0   # -p: steady-state ms per event
    prefill_per_worker: int = 128      # first num_workers*128 rows unthrottled


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Online serving plane (kafka_ps_tpu/serving/, docs/SERVING.md):
    snapshot ring + micro-batching prediction engine.  `--serve` flag
    group in cli/run.py."""

    enabled: bool = False
    port: int | None = None       # socket endpoint; None = in-process only
    max_batch: int = 16           # micro-batch size cap (one jit shape)
    deadline_ms: float = 2.0      # max wait to fill a micro-batch
    ring_capacity: int = 8        # retained snapshots (at_clock reads)
    queue_limit: int = 0          # per-tenant admission budget; 0 = none
    shed_deadline_ms: float = 0.0  # predictive shed threshold; 0 = off
    auto: bool = True             # adaptive dispatch (costmodel.py)
    shm: bool = False             # offer same-host shared-memory path


@dataclasses.dataclass(frozen=True)
class TierConfig:
    """Tiered parameter residency (kafka_ps_tpu/store/,
    docs/TIERING.md): byte caps for the hot (device) and warm (host
    RAM) tiers; everything over the caps lives as commit-log records
    (cold).  `--tier-hot-bytes` / `--tier-warm-bytes` in cli/run.py.

    0 = unbounded — the fully-resident default, byte for byte today's
    behavior (no store is even constructed).  A warm cap needs a cold
    log to overflow into, so warm_bytes > 0 requires --durable-log (or
    a standalone cold directory).  Caps are PER PROCESS: a process
    hosting several in-process shards splits them evenly."""

    hot_bytes: int = 0
    warm_bytes: int = 0
    page_params: int = 1024        # keys per page (the residency unit)
    rebalance_interval_s: float = 0.05   # policy-thread cadence

    @property
    def enabled(self) -> bool:
        return self.hot_bytes > 0 or self.warm_bytes > 0


@dataclasses.dataclass(frozen=True)
class PSConfig:
    """Top-level parameter-server configuration (BaseKafkaApp.java:25,
    ServerProcessor.java:36,45-49)."""

    num_workers: int = 4
    consistency_model: int = SEQUENTIAL   # -c: 0 BSP, k>0 SSP, -1 ASP
    # model family (models/task.py registry): "logreg" (the reference's
    # task) or "mlp"
    task: str = "logreg"
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    buffer: BufferConfig = dataclasses.field(default_factory=BufferConfig)
    stream: StreamConfig = dataclasses.field(default_factory=StreamConfig)
    # Server aggregation rate: 1/num_workers makes the BSP update the
    # average of worker deltas (ServerProcessor.java:36).
    learning_rate: float | None = None
    eval_every: int = 1   # server evaluates test metrics every iteration
    # Async coalescing eval engine (evaluation/engine.py,
    # docs/EVALUATION.md): take test-set evaluation off the server's
    # apply critical path — a dedicated thread coalesces pending
    # (theta, clock) snapshots into batched eval dispatches, emitting
    # results in strict clock order.  Default ON; `--no-eval-async` is
    # the A/B lever (eval CSV bitwise-identical either way).  The
    # fused-BSP drive loop ignores it (its eval is already
    # chunk-amortized, runtime/app._run_fused_loop).
    eval_async: bool = True
    seed: int = 0
    # Use the Pallas fused local-update kernel (ops/fused_update.py) for
    # worker iterations; falls back to the XLA path off-TPU or when the
    # buffer exceeds the VMEM budget.
    use_pallas: bool = False
    # Gang-scheduled dispatch (runtime/gang.py, docs/GANG_DISPATCH.md):
    # coalesce workers released by the consistency gate at the same
    # moment into one batched device step.  On by default for the
    # serial/threaded drive loops; `--no-gang` restores the per-message
    # path.  In-process fabrics only — socket mode forces it off (the
    # wire protocol has no gang notice frame).
    use_gang: bool = True
    # Compressed delta transport (kafka_ps_tpu/compress/,
    # docs/COMPRESSION.md): "none" | "bf16" | "int8" | "topk:<ratio>".
    # Applied symmetrically — server->worker weights are quantize-
    # dequantized, worker->server deltas go through per-worker
    # error-feedback residuals.  "none" is bitwise-identical to a build
    # without the feature.  Incompatible with the fused BSP path (its
    # collectives never cross a serde boundary).
    compress: str = "none"
    # Device-resident training slab (compress/slab.py,
    # docs/PERFORMANCE.md).  slab_dtype: "f32" | "bf16" | "int8" — the
    # storage precision of each worker's on-device slab; decode is
    # fused into the training step.  "f32" is bitwise-identical to a
    # build without the feature.  slab_incremental: scatter only dirty
    # buffer rows into the device slab instead of re-uploading the
    # whole slab on every arrival (full upload remains the fallback
    # for bootstrap, restore, and mass-delete churn).
    slab_dtype: str = "f32"
    slab_incremental: bool = True
    # Online serving plane (kafka_ps_tpu/serving/): disabled by default —
    # attaching it never perturbs training (snapshots alias the
    # immutable device theta), but the engine thread only exists when
    # asked for.
    serving: ServingConfig = dataclasses.field(default_factory=ServingConfig)
    # Tiered parameter residency (kafka_ps_tpu/store/): disabled (both
    # caps 0) keeps theta fully device-resident — bitwise-identical to
    # a build without the feature; capped runs stay bitwise-identical
    # too (the tier replay contract, docs/TIERING.md), they just bound
    # resident bytes.
    tier: TierConfig = dataclasses.field(default_factory=TierConfig)

    @property
    def server_lr(self) -> float:
        if self.learning_rate is not None:
            return self.learning_rate
        return 1.0 / self.num_workers

    @property
    def max_vector_clock_delay(self) -> int:
        """ServerProcessor.java:45-49: delay == consistency model value."""
        return self.consistency_model
