"""CSV performance logging — the reference's stdout-redirect scheme
(ServerAppRunner.java:78-82, WorkerAppRunner.java:77-81) as proper sinks.

Schemas (unchanged, so the reference's evaluation notebooks parse our
logs):
  server: timestamp;partition;vectorClock;loss;fMeasure;accuracy
  worker: timestamp;partition;vectorClock;loss;fMeasure;accuracy;numTuplesSeen
"""

from __future__ import annotations

import sys

from kafka_ps_tpu.analysis.lockgraph import OrderedLock

SERVER_HEADER = "timestamp;partition;vectorClock;loss;fMeasure;accuracy"
WORKER_HEADER = SERVER_HEADER + ";numTuplesSeen"
# membership/audit events (evict / readmit / resume) — written
# INCREMENTALLY as they happen so a crash cannot lose the record the
# staleness auditor segments elastic runs by (evaluation/validate.py)
EVENTS_HEADER = "timestamp;event;partition"
# drift verdicts (telemetry/drift.py warn/trip edges): the monitor
# emits the clock-free remainder, the CLI sink prepends the wall-clock
# stamp (PS104: telemetry modules never read a clock)
DRIFT_HEADER = "timestamp;event;detector;statistic;signal"


class NullLogSink:
    """Discard-everything sink (e.g. the server log on non-coordinator
    processes of a multi-host job — one writer per file)."""

    def __call__(self, line: str) -> None:
        pass

    def close(self) -> None:
        pass


class CsvLogSink:
    """Thread-safe line sink to a file (with header) or stdout.

    `append=True` (checkpoint-resumed runs) continues an existing log
    instead of truncating it; the header is only written when the file
    is new or empty."""

    def __init__(self, path: str | None, header: str, append: bool = False):
        import os
        self._lock = OrderedLock("CsvLogSink.write")
        if path is None:
            self._fh = sys.stdout
            self._close = False
            write_header = True
        else:
            exists = os.path.exists(path) and os.path.getsize(path) > 0
            self._fh = open(path, "a" if append else "w")
            self._close = True
            write_header = not (append and exists)
        if write_header:
            self._fh.write(header + "\n")
            self._fh.flush()

    def __call__(self, line: str) -> None:
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        # idempotent: a CsvLogSink wrapped in a DeferredSink is closed
        # by the wrapper AND by the CLI's own cleanup
        if self._close:
            self._close = False
            self._fh.close()
