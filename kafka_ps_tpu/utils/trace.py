"""Tracing / profiling hooks — the reference's observability surface
(Confluent monitoring interceptors on every producer/consumer feeding
Control Center, BaseKafkaApp.java:73-78, dev/docker-compose.yaml:30-47)
rebuilt for the TPU runtime.

Three layers:
  * `Tracer` — host-side span + counter + flow-event recorder.  Spans
    export as Chrome trace-event JSON (load in chrome://tracing or
    Perfetto); counters are sampled over time as `ph: "C"` counter
    events, giving the per-topic message-flow timeline the Kafka
    interceptors provided; flow events (`ph: s/t/f`) connect a delta's
    lifecycle across threads AND processes (the wire trace context,
    runtime/net.py + docs/OBSERVABILITY.md).
  * `Tracer.span(...)` context manager — wrap any section; thread-safe,
    so the threaded runtime's per-worker threads can share one tracer.
  * `device_trace(...)` — jax.profiler wrapper capturing XLA/TPU traces
    (HLO timelines, per-op device time) to a TensorBoard logdir.

Zero overhead when disabled: the module-level NULL_TRACER no-ops every
call, and runtime code takes `tracer or NULL_TRACER`.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import defaultdict, deque


class Tracer:
    """Span + counter + flow recorder with Chrome trace-event export.

    `pid` labels every event (defaults to the real process id — the
    merge CLI in kafka_ps_tpu/telemetry keys track groups off it);
    `counter_sample_s` throttles how often a hot counter emits a
    timeline sample (0 = every increment, for deterministic tests)."""

    def __init__(self, clock=time.perf_counter, pid: int | None = None,
                 counter_sample_s: float = 0.01):
        self._clock = clock
        self._t0 = clock()
        # wall-clock anchor for cross-process merging: perf_counter
        # epochs are process-private, so dump() records where this
        # tracer's zero sits on the shared wall clock
        self._wall0 = time.time()
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._counters: dict[str, int] = defaultdict(int)
        # sampled (ts_us, name, total) points -> ph:"C" events at dump
        self._counter_samples: list[tuple[float, str, int]] = []
        self._sample_every = counter_sample_s
        self._last_sample: dict[str, float] = {}
        self._flow_seq = 0
        self.pid = os.getpid() if pid is None else pid
        self.enabled = True

    # -- spans -------------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, **args):
        if not self.enabled:
            yield
            return
        start = self._clock()
        try:
            yield
        finally:
            end = self._clock()
            with self._lock:
                self._events.append({
                    "name": name,
                    "ph": "X",                      # complete event
                    "ts": (start - self._t0) * 1e6,  # µs, trace convention
                    "dur": (end - start) * 1e6,
                    "pid": self.pid,
                    "tid": threading.get_ident() % 2 ** 31,
                    "args": args,
                })

    def span_at(self, name: str, start: float, end: float, **args) -> None:
        """Record a complete span from two clock values already taken
        (same clock as this tracer, time.perf_counter by default).
        For retroactive sections whose start predates the decision to
        record them — e.g. the consistency gate's hold time, known only
        at release (runtime/server.py:_observe_gate_release)."""
        if not self.enabled:
            return
        with self._lock:
            self._events.append({
                "name": name,
                "ph": "X",
                "ts": (start - self._t0) * 1e6,
                "dur": max(0.0, end - start) * 1e6,
                "pid": self.pid,
                "tid": threading.get_ident() % 2 ** 31,
                "args": args,
            })

    # -- counters (message-flow view) --------------------------------------
    def count(self, name: str, n: int = 1) -> None:
        if not self.enabled:
            return
        now = self._clock()
        with self._lock:
            self._counters[name] += n
            # throttled timeline sample: Perfetto renders these as a
            # counter track (the satellite fix — totals alone never
            # appeared on the timeline)
            if now - self._last_sample.get(name, -1e18) >= self._sample_every:
                self._last_sample[name] = now
                self._counter_samples.append(
                    ((now - self._t0) * 1e6, name, self._counters[name]))

    # -- flow events (cross-thread / cross-process causality) --------------
    def new_flow_id(self) -> int:
        """Globally-unique flow id: pid in the high bits so ids from
        different processes never collide in a merged trace."""
        with self._lock:
            self._flow_seq += 1
            return ((self.pid & 0xFFFF) << 40) | self._flow_seq

    def flow(self, ph: str, name: str, flow_id: int, **args) -> None:
        """One flow event: ph 's' (start), 't' (step), 'f' (end).
        Emit from inside a span — viewers bind the arrow endpoints to
        the enclosing slice on this (pid, tid)."""
        if not self.enabled:
            return
        now = self._clock()
        ev = {"name": name, "cat": "flow", "ph": ph, "id": flow_id,
              "ts": (now - self._t0) * 1e6, "pid": self.pid,
              "tid": threading.get_ident() % 2 ** 31, "args": args}
        if ph == "f":
            ev["bp"] = "e"      # bind the arrowhead to the enclosing slice
        with self._lock:
            self._events.append(ev)

    def flow_start(self, name: str, flow_id: int, **args) -> None:
        self.flow("s", name, flow_id, **args)

    def flow_step(self, name: str, flow_id: int, **args) -> None:
        self.flow("t", name, flow_id, **args)

    def flow_end(self, name: str, flow_id: int, **args) -> None:
        self.flow("f", name, flow_id, **args)

    def clear(self) -> None:
        """Drop every recorded event and counter sample (the
        warmup-then-measure pattern: run until jit compiles settle,
        clear, then trace steady state).  Flow ids keep advancing, so
        post-clear events never collide with discarded ones; a flow
        whose start was discarded is simply unmatched downstream."""
        with self._lock:
            self._events.clear()
            self._counter_samples.clear()

    # -- export ------------------------------------------------------------
    def counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def span_stats(self) -> dict[str, dict]:
        """Per-span-name count/total/mean milliseconds."""
        with self._lock:
            acc: dict[str, list[float]] = defaultdict(list)
            for e in self._events:
                acc[e["name"]].append(e["dur"] / 1e3)
        return {name: {"count": len(ds), "total_ms": round(sum(ds), 3),
                       "mean_ms": round(sum(ds) / len(ds), 3)}
                for name, ds in sorted(acc.items())}

    def dump(self, path: str) -> str:
        """Chrome trace-event JSON: {traceEvents: [...], counters: ...}.

        Counters land on the timeline as standard `ph: "C"` counter
        events (one per throttled sample plus a closing sample at dump
        time), so Perfetto draws them as counter tracks; the top-level
        "counters" totals stay for the programmatic consumers
        (span_stats callers, tests).  "wallClockT0" anchors this
        process's ts=0 on the shared wall clock for the merge CLI."""
        now_us = (self._clock() - self._t0) * 1e6
        with self._lock:
            events = list(self._events)
            tid = threading.get_ident() % 2 ** 31
            for ts_us, name, total in self._counter_samples:
                events.append({"name": name, "ph": "C", "ts": ts_us,
                               "pid": self.pid, "tid": tid,
                               "args": {"value": total}})
            for name, total in sorted(self._counters.items()):
                events.append({"name": name, "ph": "C", "ts": now_us,
                               "pid": self.pid, "tid": tid,
                               "args": {"value": total}})
            payload = {"traceEvents": events,
                       "counters": dict(self._counters),
                       "wallClockT0": self._wall0,
                       "pid": self.pid}
        with open(path, "w") as f:
            json.dump(payload, f)
        return path


class LatencyRecorder:
    """Sliding-window latency samples with percentile export — the
    serving plane's p50/p99 (seconds in, milliseconds out). Bounded so
    a long-lived server never grows; thread-safe so request callbacks
    and the status heartbeat can share one recorder."""

    def __init__(self, window: int = 4096):
        self._samples: "deque[float]" = deque(maxlen=max(1, window))
        self._lock = threading.Lock()
        self.count = 0

    def record(self, seconds: float) -> None:
        with self._lock:
            self._samples.append(seconds)
            self.count += 1

    def percentiles_ms(self, *ps: float) -> dict[str, float | None]:
        """{"p50_ms": ..., "p99_ms": ...}; None before any sample."""
        with self._lock:
            data = sorted(self._samples)
        out: dict[str, float | None] = {}
        for p in ps:
            key = f"p{p:g}_ms"
            if not data:
                out[key] = None
            else:
                idx = min(len(data) - 1, round(p / 100 * (len(data) - 1)))
                out[key] = round(data[idx] * 1e3, 3)
        return out


class _NullTracer(Tracer):
    """No-op tracer (observability off — the default, like running the
    reference without Control Center)."""

    def __init__(self):
        super().__init__()
        self.enabled = False


NULL_TRACER = _NullTracer()


@contextlib.contextmanager
def device_trace(logdir: str | None):
    """XLA/TPU device profiling via jax.profiler (per-op device time,
    HLO timeline — view with TensorBoard).  None → no-op."""
    if logdir is None:
        yield
        return
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
