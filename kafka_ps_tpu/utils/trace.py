"""Tracing / profiling hooks — the reference's observability surface
(Confluent monitoring interceptors on every producer/consumer feeding
Control Center, BaseKafkaApp.java:73-78, dev/docker-compose.yaml:30-47)
rebuilt for the TPU runtime.

Three layers:
  * `Tracer` — host-side span + counter recorder.  Spans export as
    Chrome trace-event JSON (load in chrome://tracing or Perfetto);
    counters give the message-flow view the Kafka interceptors provided
    (sends per topic, iterations per worker).
  * `Tracer.span(...)` context manager — wrap any section; thread-safe,
    so the threaded runtime's per-worker threads can share one tracer.
  * `device_trace(...)` — jax.profiler wrapper capturing XLA/TPU traces
    (HLO timelines, per-op device time) to a TensorBoard logdir.

Zero overhead when disabled: the module-level NULL_TRACER no-ops every
call, and runtime code takes `tracer or NULL_TRACER`.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import defaultdict, deque


class Tracer:
    """Span + counter recorder with Chrome trace-event export."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._counters: dict[str, int] = defaultdict(int)
        self.enabled = True

    # -- spans -------------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, **args):
        if not self.enabled:
            yield
            return
        start = self._clock()
        try:
            yield
        finally:
            end = self._clock()
            with self._lock:
                self._events.append({
                    "name": name,
                    "ph": "X",                      # complete event
                    "ts": (start - self._t0) * 1e6,  # µs, trace convention
                    "dur": (end - start) * 1e6,
                    "pid": 0,
                    "tid": threading.get_ident() % 2 ** 31,
                    "args": args,
                })

    # -- counters (message-flow view) --------------------------------------
    def count(self, name: str, n: int = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._counters[name] += n

    # -- export ------------------------------------------------------------
    def counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def span_stats(self) -> dict[str, dict]:
        """Per-span-name count/total/mean milliseconds."""
        with self._lock:
            acc: dict[str, list[float]] = defaultdict(list)
            for e in self._events:
                acc[e["name"]].append(e["dur"] / 1e3)
        return {name: {"count": len(ds), "total_ms": round(sum(ds), 3),
                       "mean_ms": round(sum(ds) / len(ds), 3)}
                for name, ds in sorted(acc.items())}

    def dump(self, path: str) -> str:
        """Chrome trace-event JSON: {traceEvents: [...], counters: ...}."""
        with self._lock:
            payload = {"traceEvents": list(self._events),
                       "counters": dict(self._counters)}
        with open(path, "w") as f:
            json.dump(payload, f)
        return path


class LatencyRecorder:
    """Sliding-window latency samples with percentile export — the
    serving plane's p50/p99 (seconds in, milliseconds out). Bounded so
    a long-lived server never grows; thread-safe so request callbacks
    and the status heartbeat can share one recorder."""

    def __init__(self, window: int = 4096):
        self._samples: "deque[float]" = deque(maxlen=max(1, window))
        self._lock = threading.Lock()
        self.count = 0

    def record(self, seconds: float) -> None:
        with self._lock:
            self._samples.append(seconds)
            self.count += 1

    def percentiles_ms(self, *ps: float) -> dict[str, float | None]:
        """{"p50_ms": ..., "p99_ms": ...}; None before any sample."""
        with self._lock:
            data = sorted(self._samples)
        out: dict[str, float | None] = {}
        for p in ps:
            key = f"p{p:g}_ms"
            if not data:
                out[key] = None
            else:
                idx = min(len(data) - 1, round(p / 100 * (len(data) - 1)))
                out[key] = round(data[idx] * 1e3, 3)
        return out


class _NullTracer(Tracer):
    """No-op tracer (observability off — the default, like running the
    reference without Control Center)."""

    def __init__(self):
        super().__init__()
        self.enabled = False


NULL_TRACER = _NullTracer()


@contextlib.contextmanager
def device_trace(logdir: str | None):
    """XLA/TPU device profiling via jax.profiler (per-op device time,
    HLO timeline — view with TensorBoard).  None → no-op."""
    if logdir is None:
        yield
        return
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
