"""Periodic runtime status line — the live-observability stand-in for
the reference's Confluent Control Center (dev/docker-compose.yaml:30-47
runs a full web UI streaming per-topic message flow).

A broker UI makes no sense without a broker; the deliberate divergence
(docs/EVALUATION.md) is a one-line status heartbeat on stderr, emitted
by the drive loops every `--status_every` seconds:

    [status] iters=412 (+38.0/s) clocks=0:103,1:103,2:102,3:103 \
        active=4/4 pending weights=2 gradients=1 buffers=256,256,256,256

Post-hoc deep inspection stays with the tracer (`--trace` Chrome trace,
utils/trace.py — the interceptor analogue); this is the live pulse: is
it making progress, how fast, who is lagging, is a queue backing up.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Callable


class StatusReporter:
    """Prints `source()` every `interval` seconds on its own thread.

    `source` returns a dict; an `iters` key gets a derived rate
    (+N/s since the previous line), and ANY key suffixed `_per_s`
    (top-level or nested one dict deep) is treated as a cumulative
    count and rendered as the rate since the previous line ("--" until
    a baseline exists) — how the serving plane's QPS rides the same
    heartbeat.  The thread only formats and prints host-side state —
    still joined on stop(), per the teardown discipline
    (docs/TESTING.md)."""

    def __init__(self, interval: float, source: Callable[[], dict],
                 out=None, clock=time.monotonic):
        self.interval = interval
        self.source = source
        self.out = out if out is not None else sys.stderr
        self._clock = clock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # per-key (last value, last timestamp) for every derived-rate
        # key — `iters` and the `*_per_s` family share the mechanism
        # pscheck: disable=PS201 (rate scratch for the status line; a torn read skews one printed rate)
        self._last_counts: dict[str, tuple[float, float]] = {}

    def start(self) -> "StatusReporter":
        if self.interval and self.interval > 0 and self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="kps-status")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.emit()

    def emit(self) -> None:
        """One status line now (also called directly by tests)."""
        try:
            fields = self.source()
        except Exception as e:       # a torn-down source must not kill
            fields = {"error": repr(e)}
        now = self._clock()
        parts = []
        for k, v in fields.items():
            if k == "iters" and isinstance(v, (int, float)):
                per_s = self._rate("iters", v, now)
                rate = "" if per_s is None else f" (+{per_s:.1f}/s)"
                parts.append(f"iters={v}{rate}")
            elif k.endswith("_per_s") and isinstance(v, (int, float)):
                parts.append(f"{k}={self._fmt_rate(k, v, now)}")
            elif isinstance(v, dict):
                inner = " ".join(
                    f"{ik}={self._fmt_rate(f'{k}.{ik}', iv, now)}"
                    if ik.endswith("_per_s") and isinstance(iv, (int, float))
                    else f"{ik}={iv}"
                    for ik, iv in v.items())
                parts.append(f"{k} {inner}")
            elif isinstance(v, (list, tuple)):
                parts.append(f"{k}=" + ",".join(str(i) for i in v))
            else:
                parts.append(f"{k}={v}")
        print("[status] " + " ".join(parts), file=self.out, flush=True)

    def _rate(self, key: str, value: float, now: float) -> float | None:
        """Derived rate for a cumulative count since its previous
        sample; None until a baseline exists (first line)."""
        prev = self._last_counts.get(key)
        self._last_counts[key] = (value, now)
        if prev is None or now <= prev[1]:
            return None
        return (value - prev[0]) / (now - prev[1])

    def _fmt_rate(self, key: str, value: float, now: float) -> str:
        per_s = self._rate(key, value, now)
        return "--" if per_s is None else f"{per_s:.1f}"

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=10.0)
