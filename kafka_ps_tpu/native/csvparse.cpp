// Native CSV → sparse-row parser: the data-loading hot path of the
// streaming producer (the role the reference's CsvProducer + Jackson
// JSON serde play on the JVM, producer/CsvProducer.java:36-99).
//
// Parses a whole training CSV into CSR-style arrays in one pass:
//   row_offsets[num_rows + 1], keys[nnz], vals[nnz], labels[num_rows]
// dropping zero features exactly like the reference's producer
// (CsvProducer.java:52-57).  The Python binding (binding.py) wraps the
// arrays as numpy views; the paced stream iterator then replays rows
// without re-parsing.
//
// Build: make -C kafka_ps_tpu/native   (g++ -O3 -shared -fPIC)

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {

struct ParsedCsv {
    long num_rows;
    long nnz;
    long num_features;      // columns per row minus the label
    long *row_offsets;      // [num_rows + 1]
    int *keys;              // [nnz]
    float *vals;            // [nnz]
    int *labels;            // [num_rows]
};

static char *read_file(const char *path, long *out_len) {
    FILE *f = fopen(path, "rb");
    if (!f) return nullptr;
    fseek(f, 0, SEEK_END);
    long len = ftell(f);
    fseek(f, 0, SEEK_SET);
    char *buf = (char *)malloc((size_t)len + 1);
    if (!buf) { fclose(f); return nullptr; }
    if (len > 0 && fread(buf, 1, (size_t)len, f) != (size_t)len) {
        free(buf); fclose(f); return nullptr;
    }
    fclose(f);
    buf[len] = '\0';
    *out_len = len;
    return buf;
}

// Parse one line of comma-separated floats into (keys, vals) of nonzeros
// plus the final column as the label.  Returns the column count, or -1
// on a malformed number.
static long parse_line(char *line, std::vector<int> &keys,
                       std::vector<float> &vals, int *label) {
    long col = 0;
    float last = 0.0f;
    char *p = line;
    while (*p) {
        char *end = nullptr;
        float v = strtof(p, &end);
        if (end == p) return -1;                 // not a number
        // a previous "last" value was a feature, not the label
        if (col > 0 && last != 0.0f) {
            keys.push_back((int)(col - 1));
            vals.push_back(last);
        }
        last = v;
        col++;
        p = end;
        if (*p == ',') p++;
        else if (*p == '\0') break;
        else return -1;                          // junk between fields
    }
    if (col == 0) return 0;                      // blank line
    *label = (int)last;
    return col;
}

ParsedCsv *kps_parse_csv(const char *path, int has_header) {
    long len = 0;
    char *buf = read_file(path, &len);
    if (!buf) return nullptr;

    std::vector<long> row_offsets;
    std::vector<int> keys;
    std::vector<float> vals;
    std::vector<int> labels;
    row_offsets.push_back(0);

    long num_features = -1;
    bool first_line = true;
    char *save = nullptr;
    for (char *line = strtok_r(buf, "\n", &save); line;
         line = strtok_r(nullptr, "\n", &save)) {
        size_t n = strlen(line);
        if (n > 0 && line[n - 1] == '\r') line[n - 1] = '\0';
        if (line[0] == '\0') continue;
        if (first_line) {
            first_line = false;
            if (has_header) continue;
        }
        int label = 0;
        long cols = parse_line(line, keys, vals, &label);
        if (cols == 0) continue;                 // blank
        if (cols < 2) { free(buf); return nullptr; }
        if (num_features < 0) num_features = cols - 1;
        else if (cols - 1 != num_features) { free(buf); return nullptr; }
        labels.push_back(label);
        row_offsets.push_back((long)keys.size());
    }
    free(buf);

    ParsedCsv *out = (ParsedCsv *)malloc(sizeof(ParsedCsv));
    if (!out) return nullptr;
    out->num_rows = (long)labels.size();
    out->nnz = (long)keys.size();
    out->num_features = num_features < 0 ? 0 : num_features;
    out->row_offsets = (long *)malloc(sizeof(long) * row_offsets.size());
    out->keys = (int *)malloc(sizeof(int) * (keys.size() ? keys.size() : 1));
    out->vals = (float *)malloc(sizeof(float) * (vals.size() ? vals.size() : 1));
    out->labels = (int *)malloc(sizeof(int) * (labels.size() ? labels.size() : 1));
    if (!out->row_offsets || !out->keys || !out->vals || !out->labels) {
        free(out->row_offsets); free(out->keys); free(out->vals);
        free(out->labels); free(out);
        return nullptr;
    }
    memcpy(out->row_offsets, row_offsets.data(),
           sizeof(long) * row_offsets.size());
    if (!keys.empty()) {
        memcpy(out->keys, keys.data(), sizeof(int) * keys.size());
        memcpy(out->vals, vals.data(), sizeof(float) * vals.size());
    }
    if (!labels.empty())
        memcpy(out->labels, labels.data(), sizeof(int) * labels.size());
    return out;
}

void kps_free(ParsedCsv *p) {
    if (!p) return;
    free(p->row_offsets);
    free(p->keys);
    free(p->vals);
    free(p->labels);
    free(p);
}

}  // extern "C"
