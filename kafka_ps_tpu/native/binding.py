"""ctypes binding for the native CSV parser (csvparse.cpp).

Loads `libkpscsv.so` from the package directory, building it with make
on first use if a toolchain is present.  `is_available()` gates callers;
data/stream.py falls back to the pure-Python parser when it is False,
so the framework has no hard native dependency.
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libkpscsv.so")
_lock = threading.Lock()
_lib = None
_build_failed = False


class _ParsedCsv(ctypes.Structure):
    _fields_ = [
        ("num_rows", ctypes.c_long),
        ("nnz", ctypes.c_long),
        ("num_features", ctypes.c_long),
        ("row_offsets", ctypes.POINTER(ctypes.c_long)),
        ("keys", ctypes.POINTER(ctypes.c_int)),
        ("vals", ctypes.POINTER(ctypes.c_float)),
        ("labels", ctypes.POINTER(ctypes.c_int)),
    ]


def _load():
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        if not os.path.exists(_SO):
            try:
                subprocess.run(["make", "-C", _DIR, "libkpscsv.so"],
                               check=True, capture_output=True, timeout=120)
            except (OSError, subprocess.SubprocessError):
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _build_failed = True
            return None
        lib.kps_parse_csv.restype = ctypes.POINTER(_ParsedCsv)
        lib.kps_parse_csv.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.kps_free.restype = None
        lib.kps_free.argtypes = [ctypes.POINTER(_ParsedCsv)]
        _lib = lib
        return _lib


def is_available() -> bool:
    return _load() is not None


@dataclasses.dataclass(frozen=True)
class NativeCsv:
    """CSR view of a parsed CSV: row i's nonzeros are
    keys[row_offsets[i]:row_offsets[i+1]] (same zero-dropping as
    CsvProducer.java:52-57); labels[i] is the last column."""

    row_offsets: np.ndarray   # [num_rows + 1] int64
    keys: np.ndarray          # [nnz] int32
    vals: np.ndarray          # [nnz] float32
    labels: np.ndarray        # [num_rows] int32
    num_features: int

    @property
    def num_rows(self) -> int:
        return len(self.labels)

    def row(self, i: int) -> tuple[dict[int, float], int]:
        lo, hi = self.row_offsets[i], self.row_offsets[i + 1]
        feats = {int(k): float(v)
                 for k, v in zip(self.keys[lo:hi], self.vals[lo:hi])}
        return feats, int(self.labels[i])

    def to_dense(self) -> tuple[np.ndarray, np.ndarray]:
        x = np.zeros((self.num_rows, self.num_features), np.float32)
        rows = np.repeat(np.arange(self.num_rows),
                         np.diff(self.row_offsets))
        x[rows, self.keys] = self.vals
        return x, self.labels.copy()


def parse_csv(path: str, has_header: bool = True) -> NativeCsv:
    """One-pass native parse; raises RuntimeError if the library is
    unavailable or the file is malformed (callers gate on
    is_available() and fall back to the Python parser)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native CSV parser unavailable (no toolchain?)")
    p = lib.kps_parse_csv(path.encode(), 1 if has_header else 0)
    if not p:
        raise RuntimeError(f"native parse failed for {path}")
    try:
        c = p.contents
        n, nnz = c.num_rows, c.nnz
        out = NativeCsv(
            row_offsets=np.ctypeslib.as_array(c.row_offsets,
                                              (n + 1,)).copy(),
            keys=np.ctypeslib.as_array(c.keys, (max(nnz, 1),))[:nnz].copy(),
            vals=np.ctypeslib.as_array(c.vals, (max(nnz, 1),))[:nnz].copy(),
            labels=np.ctypeslib.as_array(c.labels,
                                         (max(n, 1),))[:n].copy(),
            num_features=int(c.num_features),
        )
    finally:
        lib.kps_free(p)
    return out
