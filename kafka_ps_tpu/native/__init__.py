"""Native (C++) components of the runtime.

The compute path is JAX/XLA/Pallas; the IO-side hot paths are native:
csvparse.cpp replaces the JVM CsvProducer + Jackson parsing layer of the
reference (producer/CsvProducer.java, serialization/JSONSerde.java) with
a one-pass C++ CSV → CSR parser exposed through ctypes (binding.py).
Everything degrades gracefully to the pure-Python path when the shared
library is unavailable.
"""

from kafka_ps_tpu.native.binding import (  # noqa: F401
    NativeCsv,
    is_available,
    parse_csv,
)
