"""Local delta pre-reduction — the aggregator's combine/expand engine.

One `LocalAggregator` lives on each host, between that host's workers
and the server(s).  Workers send it plain per-worker GradientMessages;
it combines everything pending into one `CompositeDelta` per flush and
forwards that upstream, then fans the returning weights back out.  The
server gate advances every member worker's clock from the composite's
(worker, clock) vector-clock map exactly as if the deltas had arrived
individually (runtime/server.py `process_composite`).

Two combine shapes (messages.CompositeDelta):

  * stacked (default) — members travel as their own per-worker deltas
    inside one frame.  The server applies them per-member in member
    order, so the aggregated path is BITWISE-identical to the direct
    path for all three consistency models (float addition is not
    associative; preserving the apply sequence, not just the sum, is
    what keeps the pin).
  * summed (`summed=True`) — members sharing ONE clock are pre-reduced
    into a single delta (exact by linearity for BSP): one server apply
    per host per clock.  Pending deltas that span clocks fall back to
    stacked for that flush, so mixed-progress moments never block.

Compression (`--compress`): workers ship raw f32 to their aggregator;
the aggregator owns the per-member error-feedback residuals
(compress/feedback.ErrorFeedback) and encodes at the aggregator→server
edge.  Because EF state is per worker stream and the encode sequence
per member is exactly what the worker itself would have produced, the
compressed aggregated path stays bitwise-pinned to the compressed
direct path in stacked mode.

Determinism: combine order, member order, and merge results are pure
functions of the offered messages (no wall clock, no hash-order
iteration) — the PS104 replay contract extends to this package.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict

import numpy as np

from kafka_ps_tpu.analysis.lockgraph import OrderedLock
from kafka_ps_tpu.runtime.messages import (CompositeDelta, GradientMessage,
                                           KeyRange, WeightsMessage)
from kafka_ps_tpu.telemetry import FLIGHT, NULL_TELEMETRY
from kafka_ps_tpu.utils.trace import NULL_TRACER

# composite fan-in distribution buckets (workers per composite)
FAN_IN_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


def merge_composites(a: CompositeDelta, b: CompositeDelta) -> CompositeDelta:
    """Vector-clock merge of two STACKED composites: the union of their
    member maps, deduplicated by (worker, clock), sorted ascending.

    This is a semilattice join — associative, commutative, idempotent —
    because a redelivered (worker, clock) carries the identical delta
    (workers resend from their redelivery cache verbatim, never
    recompute), so "first writer wins" and "second writer wins" pick
    the same bytes.  tests/test_agg.py pins the algebra."""
    if a.summed or b.summed:
        raise ValueError("merge is defined on stacked composites only "
                         "(a summed composite has lost its members' "
                         "individual deltas)")
    by_member: dict[tuple[int, int], GradientMessage] = {}
    for comp in (a, b):
        for m, d in zip(comp.members, comp.deltas):
            by_member.setdefault(m, d)
    members = tuple(sorted(by_member))
    return CompositeDelta(agg_id=a.agg_id, members=members,
                          deltas=tuple(by_member[m] for m in members))


def split_composite(plan, composite: CompositeDelta) -> list[CompositeDelta]:
    """Range-sharding composition: run the shard split ONCE per
    composite instead of once per worker (docs/SHARDING.md).  Each
    member delta is sliced to every shard's key range; the result is
    one composite per shard carrying the full member map, so every
    shard's gate still sees one message per (host, clock)."""
    out = []
    for r in plan.ranges:
        deltas = []
        for d in composite.deltas:
            lo = r.start - d.key_range.start
            hi = r.end - d.key_range.start
            deltas.append(dataclasses.replace(
                d, key_range=KeyRange(r.start, r.end),
                values=d.values[lo:hi], encoded=None))
        out.append(CompositeDelta(agg_id=composite.agg_id,
                                  members=composite.members,
                                  deltas=tuple(deltas),
                                  summed=composite.summed))
    return out


class LocalAggregator:
    """Combine engine for one aggregator host.

    `offer()` is called from the per-worker reader threads; `combine()`
    from the forwarding loop.  Pending deltas are keyed (worker, clock)
    in arrival order with first-writer-wins dedup (a reconnecting
    worker's resend of an already-pending clock is dropped here; one
    that was already forwarded is deduplicated by the server gate)."""

    def __init__(self, agg_id: int, num_params: int, codec_spec=None,
                 summed: bool = False, telemetry=None, tracer=None):
        self.agg_id = agg_id
        self.num_params = num_params
        self.summed = summed
        self._spec = codec_spec          # compress/wire.CodecSpec or None
        self._ef = {}                    # worker id -> ErrorFeedback
        self._ef_clock = {}              # worker id -> last encoded clock
        self._ef_last = {}               # worker id -> last encoded msg
        self._pending: OrderedDict[tuple[int, int], GradientMessage] = \
            OrderedDict()
        self._lock = OrderedLock("agg.pending")
        self._telemetry = telemetry or NULL_TELEMETRY
        self._tracer = tracer or NULL_TRACER
        mode = "summed" if summed else "stacked"
        self._m_composites = self._telemetry.counter(
            "agg_composites_total", mode=mode)
        self._m_dropped_dups = self._telemetry.counter(
            "agg_duplicate_offers_total")
        self._m_fan_in = self._telemetry.histogram(
            "agg_fan_in", buckets=FAN_IN_BUCKETS)

    def _ef_for(self, worker: int):
        ef = self._ef.get(worker)
        if ef is None:
            from kafka_ps_tpu.compress.codecs import get_codec
            from kafka_ps_tpu.compress.feedback import ErrorFeedback
            ef = ErrorFeedback(get_codec(self._spec, self.num_params))
            self._ef[worker] = ef
        return ef

    # -- worker-facing side ------------------------------------------------

    def offer(self, msg: GradientMessage) -> bool:
        """Queue one worker delta for the next combine.  Returns False
        for a duplicate of a still-pending (worker, clock)."""
        key = (msg.worker_id, msg.vector_clock)
        with self._lock:
            if key in self._pending:
                self._m_dropped_dups.inc()
                return False
            self._pending[key] = msg
        return True

    @property
    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- server-facing side ------------------------------------------------

    def combine(self) -> CompositeDelta | None:
        """Drain everything pending into one composite (None when
        idle).  Summed mode pre-reduces only when all pending members
        share one clock; otherwise this flush degrades to stacked so a
        mixed-progress moment (reconnect backlog, eventual consistency)
        never stalls or misorders anyone."""
        with self._lock:
            if not self._pending:
                return None
            drained = list(self._pending.items())
            self._pending.clear()
        drained.sort(key=lambda kv: kv[0])
        members = tuple(k for k, _ in drained)
        deltas = [d for _, d in drained]
        clocks = {c for _, c in members}
        summed = self.summed and len(clocks) == 1 and len(deltas) > 1
        if summed:
            total = deltas[0].values
            for d in deltas[1:]:         # ascending worker id: documented
                total = total + d.values  # exact by linearity, not bitwise
            base = GradientMessage(
                vector_clock=next(iter(clocks)),
                key_range=deltas[0].key_range, values=total,
                worker_id=members[0][0])
            deltas = [self._encode(base) if self._spec is not None
                      else base]
        elif self._spec is not None:
            kept_members, kept = [], []
            for m, d in zip(members, deltas):
                out = self._encode(d)
                if out is None:
                    # resend below the EF horizon: its original encode
                    # already rode a forwarded composite (ef_state
                    # persists only after the upstream send), so the
                    # server has it — re-advancing the residual here
                    # would desync every later encode
                    self._m_dropped_dups.inc()
                    continue
                kept_members.append(m)
                kept.append(out)
            if not kept:
                return None
            members, deltas = tuple(kept_members), kept
        composite = CompositeDelta(agg_id=self.agg_id, members=members,
                                   deltas=tuple(deltas), summed=summed)
        self._m_composites.inc()
        self._m_fan_in.observe(len(members))
        if FLIGHT.enabled:
            FLIGHT.record("agg.combine", agg=self.agg_id,
                          fan_in=len(members), summed=summed,
                          clock=members[-1][1])
        if self._tracer.enabled:
            for m, d in zip(members, composite.deltas):
                fid = getattr(d, "trace", None)
                if fid:
                    # continue the worker's delta.wire flow through the
                    # aggregator hop so critpath still stitches
                    # end-to-end
                    self._tracer.flow_step("delta.wire", fid,
                                           agg=self.agg_id, worker=m[0])
        return composite

    def _encode(self, msg: GradientMessage) -> GradientMessage | None:
        """Aggregator-owned error feedback at the upstream edge: the
        same compensate→encode→decode sequence the worker would have
        run on the direct path, keyed by the member's worker id.

        EF is a running residual, so each clock may advance it exactly
        once even when workers resend (reconnect replays the whole
        redelivery cache).  The clock horizon makes resends safe:
        a clock AT the horizon returns the cached encode verbatim
        (bitwise, the server deduplicates it), one BELOW it returns
        None (already forwarded — combine drops the member)."""
        w, c = msg.worker_id, msg.vector_clock
        last = self._ef_clock.get(w, -1)
        if c < last:
            return None
        if c == last:
            return self._ef_last[w]
        decoded, enc = self._ef_for(w).step(msg.values)
        out = dataclasses.replace(msg, values=decoded, encoded=enc)
        fid = getattr(msg, "trace", None)
        if fid:
            object.__setattr__(out, "trace", fid)
        self._ef_clock[w] = c
        self._ef_last[w] = out
        return out

    # -- weights fan-out (reverse direction) -------------------------------

    def expand(self, msg: WeightsMessage, members) -> list:
        """One server→aggregator weights send re-broadcast to every
        member: (worker, WeightsMessage-with-that-worker's-clock)
        pairs.  theta bytes are shared; only the clock stamp differs
        (eventual consistency advances members independently)."""
        out = []
        for worker, clock in members:
            m = (msg if msg.vector_clock == clock
                 else dataclasses.replace(msg, vector_clock=clock))
            out.append((worker, m))
        if FLIGHT.enabled:
            FLIGHT.record("agg.forward", agg=self.agg_id,
                          fan_out=len(out), clock=msg.vector_clock)
        return out

    # -- crash/restart seam ------------------------------------------------

    def reset(self) -> None:
        """Drop all pending state (the SIGKILL simulation seam used by
        bench aggregation_ab): a real restart loses pending deltas AND
        EF residuals; workers re-send from their redelivery caches and
        the server gate deduplicates what had already been forwarded."""
        with self._lock:
            self._pending.clear()
        self._ef.clear()
        self._ef_clock.clear()
        self._ef_last.clear()

    def ef_state(self) -> dict[int, tuple[np.ndarray, int, bytes]]:
        """Snapshot the error-feedback plane for the relay checkpoint:
        worker -> (residual copy, last encoded clock, last encoded
        message as serde bytes).  Persisted AFTER each upstream send,
        so a restore's horizon only covers composites the server has:
        under `--compress` a SIGKILL'd aggregator would otherwise lose
        the residuals and break the bitwise pin on every later round."""
        from kafka_ps_tpu.runtime import serde
        out = {}
        for w, ef in self._ef.items():
            out[w] = (ef.state().copy(), self._ef_clock.get(w, -1),
                      serde.to_bytes(self._ef_last[w]))
        return out

    def ef_restore(self, state: dict) -> None:
        """Rehydrate `ef_state()` after a restart (agg/relay.py)."""
        from kafka_ps_tpu.runtime import serde
        for w, (residual, clock, last) in state.items():
            self._ef_for(int(w)).restore(np.asarray(residual))
            self._ef_clock[int(w)] = int(clock)
            self._ef_last[int(w)] = serde.from_bytes(last)


def direct_equivalent(composite: CompositeDelta) -> list[GradientMessage]:
    """The per-member message sequence this composite stands for, in
    member order — what the server's stacked expansion applies, and
    what tests compare against the direct path."""
    if composite.summed:
        raise ValueError("a summed composite has no per-member "
                         "equivalent (pre-reduced by linearity)")
    return list(composite.deltas)
