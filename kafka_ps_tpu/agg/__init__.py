"""Hierarchical aggregation tier (docs/AGGREGATION.md): a per-host
local aggregator that pre-reduces co-located workers' deltas into ONE
composite message per (host, clock), collapsing server fan-in from
O(workers) to O(hosts)."""

from kafka_ps_tpu.agg.core import (LocalAggregator, merge_composites,
                                   split_composite)

__all__ = ["LocalAggregator", "merge_composites", "split_composite"]
