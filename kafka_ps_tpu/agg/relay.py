"""The aggregator relay process body (docs/AGGREGATION.md): one per
host, between that host's worker processes and the server.

Topology (socket deployment, cli/socket_mode.run_aggregator):

    workers --TCP/shm--> AggregatorRelay --one conn--> server

Upstream it is a `net.WorkerBridge` that HELLOs with `aggregator=True`
and ALL member worker ids: the server routes the members' data rows
and weights through this single connection and may group a release set
into one T_WEIGHTS_AGG frame.  Downstream it is a `net.ServerBridge`
the member workers dial exactly as they would dial a server — same
HELLO, same CONFIG (the relay advertises the UPSTREAM run id, so
worker-side staleness checks keep working), same framing — which is
what lets `--aggregate HOST:PORT` reuse the sharded worker path
unchanged (cli/socket_mode._run_worker_sharded with one address).

The relay is deliberately thin and (without `--compress`) jax-free:

  * gradients: members' frames decode into the downstream fabric,
    queue in a `LocalAggregator`, and flush upstream as ONE composite
    per (host, flush) — serialized exactly once (`send_payload`).
  * weights: upstream frames re-broadcast raw (`forward_frame`, no
    decode/encode cycle); a grouped T_WEIGHTS_AGG frame is expanded by
    re-stamping the shared body's clock word per member.
  * data rows: raw pass-through, with a per-worker stash for rows that
    arrive before their worker has connected (the server starts
    producing as soon as the RELAY's HELLO registers the member ids).

Crash safety: the relay holds no durable protocol state — workers
resend their redelivery caches on reconnect and the server gate
deduplicates (docs/SHARDING.md redelivery rules).  The one exception
is `--compress`: error-feedback residuals live here, so an optional
checkpoint persists them AFTER each upstream send; restoring keeps the
compressed aggregated path bitwise-pinned across a SIGKILL.
"""

from __future__ import annotations

import os
import struct
import threading

import numpy as np

from kafka_ps_tpu.agg.core import LocalAggregator
from kafka_ps_tpu.analysis.lockgraph import OrderedLock
from kafka_ps_tpu.compress.wire import CODEC_NONE
from kafka_ps_tpu.runtime import fabric as fabric_mod
from kafka_ps_tpu.runtime import net, serde
from kafka_ps_tpu.runtime.net import (T_DATA, T_DATA_BATCH, T_WEIGHTS,
                                      T_WEIGHTS_AGG)
from kafka_ps_tpu.telemetry import FLIGHT, NULL_TELEMETRY
from kafka_ps_tpu.utils.trace import NULL_TRACER

# serde._HEADER is <4sBq>: the vector-clock word of every nested
# weights body sits at byte offset 5 (magic + type id), for plain
# tid-1 AND compressed tid-4 frames alike — the grouped-frame
# expansion re-stamps it in place, touching nothing else
_CLOCK_OFFSET = 5


class AggregatorRelay:
    """One host's aggregation relay: combine upstream, fan out down."""

    def __init__(self, agg_id: int, upstream_host: str, upstream_port: int,
                 worker_ids, num_params: int, *,
                 listen_host: str = "127.0.0.1", listen_port: int = 0,
                 codec_spec=None, summed: bool = False,
                 checkpoint_path: str | None = None,
                 checkpoint_every: int = 1,
                 flush_interval: float = 0.002,
                 heartbeat_interval: float | None = None,
                 heartbeat_timeout: float | None = None,
                 connect_timeout: float = 30.0,
                 tracer=None, telemetry=None, coalesce: bool = True):
        self.agg_id = agg_id
        self.worker_ids = list(worker_ids)
        self.flush_interval = flush_interval
        self._tracer = tracer or NULL_TRACER
        self._telemetry = telemetry or NULL_TELEMETRY
        self._stop = threading.Event()
        # upstream first: its CONFIG carries the run id the downstream
        # listener advertises, and the negotiated codec decides whether
        # this relay owns error-feedback state at all
        self.upstream = net.WorkerBridge(
            upstream_host, upstream_port, self.worker_ids,
            connect_timeout=connect_timeout,
            heartbeat_timeout=heartbeat_timeout,
            codec=codec_spec, tracer=tracer, telemetry=telemetry,
            aggregator=True, coalesce=coalesce)
        spec = (self.upstream.negotiated
                if self.upstream.negotiated.codec_id != CODEC_NONE
                else None)
        self.agg = LocalAggregator(agg_id, num_params, codec_spec=spec,
                                   summed=summed, telemetry=telemetry,
                                   tracer=tracer)
        self._ckpt = checkpoint_path if spec is not None else None
        self._ckpt_every = max(1, int(checkpoint_every))
        self._flushes = 0
        self.restored = self._restore_checkpoint()
        # downstream: the listener the member workers dial.  No codec —
        # members always ship raw f32 to their relay (the re-encode
        # happens once, at the aggregator→server edge, core.py).
        self.downstream = net.ServerBridge(
            host=listen_host, port=listen_port,
            run_id=self.upstream.server_run_id or 0,
            heartbeat_interval=heartbeat_interval,
            heartbeat_timeout=heartbeat_timeout,
            tracer=tracer, telemetry=telemetry, coalesce=coalesce)
        self.port = self.downstream.port
        self.fabric = self.downstream.wrap(fabric_mod.Fabric())
        # rows/weights that arrived before their worker connected: the
        # server produces as soon as the relay's HELLO registers the
        # member ids, which can beat the member processes to the door
        self._stash_lock = OrderedLock("agg.stash")
        self._stash_rows: dict[int, list] = {}
        self._stash_weights: dict[int, bytes] = {}
        self._m_bytes_saved = self._telemetry.counter(
            "agg_wire_bytes_saved")
        self.downstream.on_ready = self._on_member_ready
        self.downstream.on_hello = self._on_member_hello
        self.upstream.raw_forward = self._on_upstream_frame
        self._reader = threading.Thread(
            target=self.upstream.run_reader, args=({},), daemon=True,
            name=f"kps-agg{agg_id}-upstream")
        self._reader.start()

    # -- downstream (member) events ----------------------------------------

    def _on_member_ready(self, worker: int) -> None:
        # READY crosses the relay verbatim: the server's bootstrap gate
        # waits on MEMBER readiness, not relay liveness
        self.upstream.mark_ready(worker)

    def _on_member_hello(self, ids) -> None:
        for worker in ids:
            if worker not in self.worker_ids:
                print(f"warning: worker {worker} connected to "
                      f"aggregator {self.agg_id}, which does not "
                      f"relay for it", flush=True)
            with self._stash_lock:
                rows = self._stash_rows.pop(worker, [])
                weights = self._stash_weights.pop(worker, None)
            for topic, payload in rows:
                self.downstream.forward_frame(topic, worker, payload)
            if weights is not None:
                self.downstream.forward_frame(T_WEIGHTS, worker, weights)

    # -- upstream (server) frames ------------------------------------------

    def _on_upstream_frame(self, topic: int, key: int,
                           payload: bytes) -> bool:
        if topic in (T_DATA, T_DATA_BATCH):
            self._forward_rows(topic, key, payload)
            return True
        if topic == T_WEIGHTS:
            self._forward_weights(key, payload)
            return True
        if topic == T_WEIGHTS_AGG:
            self._expand_group(payload)
            return True
        return False

    def _forward_rows(self, topic: int, worker: int,
                      payload: bytes) -> None:
        if self.downstream.forward_frame(topic, worker, payload):
            return
        with self._stash_lock:
            if worker not in self.downstream._conn_of:
                # data rows are NOT recoverable (the producer believes
                # they were delivered): hold them for the late joiner
                self._stash_rows.setdefault(worker, []).append(
                    (topic, payload))
                return
        self.downstream.forward_frame(topic, worker, payload)

    def _forward_weights(self, worker: int, payload: bytes) -> None:
        if self.downstream.forward_frame(T_WEIGHTS, worker, payload):
            return
        with self._stash_lock:
            # weights ARE recoverable (the gate's duplicate-liveness
            # re-send), so only the latest undeliverable frame is kept —
            # a disconnected member's backlog must not grow unbounded
            self._stash_weights[worker] = payload

    def _expand_group(self, payload: bytes) -> None:
        """One T_WEIGHTS_AGG frame → one T_WEIGHTS per member: the
        shared body is re-broadcast with each member's clock stamped
        into the serde header in place (bit-identical otherwise)."""
        (n,) = struct.unpack_from("<q", payload, 0)
        off = 8
        members = []
        for _ in range(n):
            members.append(net._AGG_MEMBER.unpack_from(payload, off))
            off += net._AGG_MEMBER.size
        body = payload[off:]
        for worker, clock in members:
            buf = bytearray(body)
            struct.pack_into("<q", buf, _CLOCK_OFFSET, clock)
            self._forward_weights(worker, bytes(buf))
        if FLIGHT.enabled:
            FLIGHT.record("agg.forward", agg=self.agg_id,
                          fan_out=len(members), grouped=True)

    # -- the combine/flush loop --------------------------------------------

    def run(self) -> None:
        """Blocking forward loop: drain member gradients into the
        aggregator, flush one composite upstream per full round or per
        `flush_interval` of quiet — whichever comes first."""
        while not self._stop.is_set():
            if self.upstream.disconnected.is_set():
                # the RUN is over (the server closed) — tell the members
                # so they stop immediately; a SIGKILL'd relay never gets
                # here, and its members instead hold the run open for the
                # reconnect grace window (cli/socket_mode, GOODBYE_RUN_ID)
                self.downstream.send_goodbye()
                break
            g = self.fabric.poll_blocking(fabric_mod.GRADIENTS_TOPIC, 0,
                                          timeout=self.flush_interval)
            if g is not None:
                self.agg.offer(g)
                if self.agg.pending_count < len(self.worker_ids):
                    continue        # a full round may be one poll away
            self.flush()

    def flush(self) -> None:
        comp = self.agg.combine()
        if comp is None:
            return
        payload = serde.to_bytes(comp)
        saved = self._direct_cost(comp, len(payload)) \
            - (len(payload) + net._FRAME.size)
        self.upstream.send_payload(0, payload)
        if saved > 0:
            self._m_bytes_saved.inc(saved)
        self._flushes += 1
        if self._ckpt and self._flushes % self._ckpt_every == 0:
            self._save_checkpoint()

    @staticmethod
    def _direct_cost(comp, payload_len: int) -> int:
        """Wire bytes the direct path would have spent on these
        members: per-member serde bodies (recovered from the composite
        length — nested bodies ride verbatim) plus one frame header
        each.  The summed shape ships ONE body for k members, so the
        direct cost multiplies instead."""
        k = comp.fan_in
        overhead = (serde._HEADER.size + serde._COMPOSITE_HEAD.size
                    + k * (serde._MEMBER.size + serde._TRACE.size)
                    + (1 + len(comp.deltas)) * serde._CHUNK.size)
        bodies = payload_len - overhead
        if comp.summed:
            return k * (bodies + net._FRAME.size)
        return bodies + k * net._FRAME.size

    # -- EF residual checkpoint (--compress crash safety) -------------------

    def _save_checkpoint(self) -> None:
        """Persist the EF plane AFTER the upstream send, atomically:
        a restore's horizon then only ever covers composites the server
        has already received (core.LocalAggregator._encode)."""
        state = self.agg.ef_state()
        arrays = {
            "run_id": np.asarray([self.upstream.server_run_id or 0],
                                 dtype=np.int64),
            "workers": np.asarray(sorted(state), dtype=np.int64),
        }
        for w, (residual, clock, blob) in state.items():
            arrays[f"residual_{w}"] = residual
            arrays[f"clock_{w}"] = np.asarray([clock], dtype=np.int64)
            # pscheck: disable=PS204 (checkpoint stash of opaque message blobs via savez, not a wire-frame decode)
            arrays[f"msg_{w}"] = np.frombuffer(blob, dtype=np.uint8)
        tmp = self._ckpt + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, self._ckpt)

    def _restore_checkpoint(self) -> bool:
        if not self._ckpt or not os.path.exists(self._ckpt):
            return False
        with np.load(self._ckpt) as z:
            if int(z["run_id"][0]) != (self.upstream.server_run_id or 0):
                return False        # a different run's leftovers
            state = {}
            for w in z["workers"].tolist():
                state[int(w)] = (z[f"residual_{w}"],
                                 int(z[f"clock_{w}"][0]),
                                 z[f"msg_{w}"].tobytes())
        self.agg.ef_restore(state)
        return True

    # -- lifecycle ----------------------------------------------------------

    def stop(self) -> None:
        self._stop.set()

    def close(self) -> None:
        self._stop.set()
        self.downstream.close()
        self.upstream.close()
        if self._reader is not threading.current_thread():
            self._reader.join(timeout=10.0)
