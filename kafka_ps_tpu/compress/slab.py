"""Device-resident training-slab codec + store (docs/PERFORMANCE.md).

The worker's hot path streams its buffer slab ([cap, F] x + labels +
validity mask) through the solver every iteration.  Two memory walls,
two tools in this module:

* **Host->device bytes**: the slab used to be re-uploaded WHOLE
  whenever one row arrived (runtime/worker.py invalidated the device
  copy on any `num_tuples_seen` change — ~4-20 MB per arrival at
  reference shapes).  `SlabStore` keeps the slab device-resident and
  applies only the rows `SlidingBuffer` marked dirty, via a jit'd
  scatter whose changed-row count is padded to a power-of-two bucket —
  O(log cap) compiled shapes, O(changed rows) bytes moved.

* **HBM->VMEM bytes**: the solver re-reads the slab from HBM every
  step.  `--slab-dtype bf16|int8` stores the device slab reduced
  (encode fused into the scatter/upload program), and decode is fused
  into the training step (models/logreg.py, models/mlp.py,
  ops/fused_update.py call `decode_x`), halving or quartering the
  bytes every matmul streams.

This is the device-side refactor of the wire codec's quantizers
(compress/codecs.py): `quantize_rows`/`dequantize_rows` are the shared
int8 primitive — the wire codec applies them to the flat vector
reshaped to [nchunks, 256] chunks, the slab codec to [cap, F] with the
slab ROW as the chunk (a per-row scale broadcasts over lanes inside
the Pallas streaming kernel, where a mid-row chunk boundary would not).

Numerics contract: `--slab-dtype f32` is bitwise-identical to the
pre-slab-store behavior — encode/decode are identity (an f32->f32
astype leaves the jaxpr unchanged) and the scatter moves the same
float bits `SlidingBuffer.snapshot` would have uploaded.  bf16/int8
are lossy on x ONLY (labels and mask stay exact); eval-metric deltas
are bounded by the same tolerance as compressed transport
(tests/test_slab.py, docs/PERFORMANCE.md).

All programs are cached per slab dtype (`_slab_programs`, an lru_cache
factory like runtime/worker._solver_fns) and jit handles the
shape/bucket polymorphism — compile-once-per-(shape, dtype) is a
tested invariant (TRACE_COUNTS below, PS101-style regression test).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

SLAB_DTYPES = ("f32", "bf16", "int8")

# Trace counters, bumped INSIDE traced bodies (the pattern
# evaluation/ground_truth._fit_traces established): a counter that
# moves on a steady-state arrival means the hot path is re-tracing.
TRACE_COUNTS = {"full": 0, "apply": 0, "decode": 0}

# Changed-row counts are padded up to a power-of-two bucket (never
# below this) so N single-row arrivals reuse ONE compiled scatter.
MIN_BUCKET = 4


# -- shared int8 primitive (also used by compress/codecs._build_fns) ---------

def quantize_rows(r: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Max-abs int8 quantization over the last axis of a 2-D block:
    [n, c] f32 -> (q [n, c] int8, scale [n] f32).  The wire codec's
    chunks and the slab codec's rows are both just choices of `c`."""
    scale = jnp.max(jnp.abs(r), axis=-1) / 127.0
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(r / safe[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_rows(q: jax.Array, scale: jax.Array) -> jax.Array:
    """Inverse of quantize_rows (up to the quantization error)."""
    return q.astype(jnp.float32) * scale[..., None]


class QuantizedSlab(NamedTuple):
    """int8 slab storage: rows quantized with a per-row scale.  A
    NamedTuple is a jax pytree, so it flows through jit/vmap/tree-stack
    wherever a plain x array would (runtime/gang.py stacks members
    with a tree-map for exactly this reason)."""

    q: jax.Array       # [cap, F] int8
    scale: jax.Array   # [cap, 1] f32  (max|row| / 127)


def slab_batch_shape(x) -> tuple[int, int]:
    """(batch, num_features) of a slab in any storage dtype."""
    a = x.q if isinstance(x, QuantizedSlab) else x
    return a.shape[-2], a.shape[-1]


def decode_x(x) -> jax.Array:
    """Stored slab -> f32, fused into whatever program traces it
    (models/*.local_update, the Pallas fallbacks).  Identity for f32
    input — the astype leaves the traced jaxpr unchanged, which is the
    f32 bitwise contract."""
    TRACE_COUNTS["decode"] += 1
    if isinstance(x, QuantizedSlab):
        return x.q.astype(jnp.float32) * x.scale
    return x.astype(jnp.float32)


def encode_x(dtype: str, x: jax.Array):
    """f32 rows -> stored form (traceable; fused into upload/scatter)."""
    if dtype == "bf16":
        return x.astype(jnp.bfloat16)
    if dtype == "int8":
        q, scale = quantize_rows(x)
        return QuantizedSlab(q=q, scale=scale[..., None])
    return x


@functools.lru_cache(maxsize=None)
def _slab_programs(dtype: str):
    """(full_upload, scatter_apply) jit'd programs for one slab dtype.
    jit's own cache keys the shape/bucket polymorphism, so the compile
    count is O(1) full + O(log cap) apply buckets per (cap, F)."""

    def full(x, y, mask):
        TRACE_COUNTS["full"] += 1
        return encode_x(dtype, x), y, mask

    def apply(sx, sy, sm, slots, xr, yr, mr):
        # slots padded with an out-of-range sentinel: mode="drop" makes
        # the padding rows no-ops, so every bucket size is one program
        TRACE_COUNTS["apply"] += 1
        enc = encode_x(dtype, xr)
        if dtype == "int8":
            sx = QuantizedSlab(
                q=sx.q.at[slots].set(enc.q, mode="drop"),
                scale=sx.scale.at[slots].set(enc.scale, mode="drop"))
        else:
            sx = sx.at[slots].set(enc, mode="drop")
        return (sx, sy.at[slots].set(yr, mode="drop"),
                sm.at[slots].set(mr, mode="drop"))

    return jax.jit(full), jax.jit(apply)


class SlabStore:
    """One worker's device-resident training slab.

    `upload_full` replaces the whole slab (bootstrap, restore,
    mass-delete fallback); `apply_rows` scatters a drained dirty set
    (SlidingBuffer.drain_dirty) into it.  `bytes_uploaded` counts the
    HOST bytes each path shipped — the quantity the slab_ab bench block
    audits (bench.py) — so the ~cap/changed-rows upload reduction is a
    measured number, not an estimate."""

    def __init__(self, dtype: str, capacity: int, num_features: int,
                 telemetry=None):
        if dtype not in SLAB_DTYPES:
            raise ValueError(
                f"slab dtype {dtype!r} not in {SLAB_DTYPES}")
        self.dtype = dtype
        self.capacity = capacity
        self.num_features = num_features
        self._x = None
        self._y = None
        self._mask = None
        self.bytes_uploaded = 0
        self.full_uploads = 0
        self.incremental_applies = 0
        self.rows_applied = 0
        # optional metrics mirror of bytes_uploaded (.nbytes of host
        # arrays — no device sync), labeled by upload path
        if telemetry is None:
            from kafka_ps_tpu.telemetry import NULL_TELEMETRY
            telemetry = NULL_TELEMETRY
        self._telemetry = telemetry
        self._m_full = telemetry.counter("slab_upload_bytes_total",
                                         path="full")
        self._m_rows = telemetry.counter("slab_upload_bytes_total",
                                         path="incremental")

    @property
    def ready(self) -> bool:
        return self._x is not None

    def upload_full(self, x, y, mask) -> None:
        """Host slab copy -> device store (encode fused in one jit)."""
        x = np.ascontiguousarray(x, dtype=np.float32)
        y = np.ascontiguousarray(y, dtype=np.int32)
        mask = np.ascontiguousarray(mask, dtype=np.float32)
        self.bytes_uploaded += x.nbytes + y.nbytes + mask.nbytes
        self.full_uploads += 1
        if self._telemetry.enabled:
            self._m_full.inc(x.nbytes + y.nbytes + mask.nbytes)
        full, _ = _slab_programs(self.dtype)
        self._x, self._y, self._mask = full(x, y, mask)

    def apply_rows(self, slots, xr, yr, mr) -> None:
        """Scatter the changed rows into the device slab.  The row
        count is padded to a power-of-two bucket (sentinel slot ==
        capacity, dropped by the scatter) so arrival-count jitter
        never re-compiles."""
        n = int(len(slots))
        if n == 0:
            return
        if not self.ready:
            raise RuntimeError("apply_rows before the first upload_full")
        b = MIN_BUCKET
        while b < n:
            b *= 2
        pad = b - n
        slots_p = np.concatenate(
            [np.asarray(slots, np.int32),
             np.full((pad,), self.capacity, np.int32)])
        xr_p = np.concatenate(
            [np.asarray(xr, np.float32),
             np.zeros((pad, self.num_features), np.float32)])
        yr_p = np.concatenate(
            [np.asarray(yr, np.int32), np.zeros((pad,), np.int32)])
        mr_p = np.concatenate(
            [np.asarray(mr, np.float32), np.zeros((pad,), np.float32)])
        self.bytes_uploaded += (slots_p.nbytes + xr_p.nbytes
                                + yr_p.nbytes + mr_p.nbytes)
        self.incremental_applies += 1
        self.rows_applied += n
        if self._telemetry.enabled:
            self._m_rows.inc(slots_p.nbytes + xr_p.nbytes
                             + yr_p.nbytes + mr_p.nbytes)
        _, apply = _slab_programs(self.dtype)
        self._x, self._y, self._mask = apply(
            self._x, self._y, self._mask, slots_p, xr_p, yr_p, mr_p)

    def arrays(self):
        """(x, y, mask) device views — x in the storage dtype (plain
        f32/bf16 array or QuantizedSlab); decode happens inside the
        training step."""
        if not self.ready:
            raise RuntimeError("slab store read before first upload")
        return self._x, self._y, self._mask

    def device_bytes(self) -> int:
        """Bytes the solver streams from HBM per slab read — the
        quantity --slab-dtype shrinks (docs/PERFORMANCE.md)."""
        if not self.ready:
            return 0
        if isinstance(self._x, QuantizedSlab):
            xb = self._x.q.nbytes + self._x.scale.nbytes
        else:
            xb = self._x.nbytes
        return xb + self._y.nbytes + self._mask.nbytes


class ParamPageSlab:
    """Hot-tier device residency for the tiered parameter store
    (kafka_ps_tpu/store/, docs/TIERING.md): page index -> f32 device
    array, with the same measured-bytes discipline as SlabStore —
    `bytes_uploaded` counts actual host->device traffic and
    `device_bytes()` the resident HBM footprint, so the tiering_ab
    bench audits counters, not estimates.

    This is SlabStore's parameter-side sibling: per-PAGE residency of
    the server's theta slice instead of the worker's full training
    slab.  Values are immutable device arrays replaced wholesale (the
    theta replacement contract, runtime/server.py), so readers may
    hold a fetched reference without locking."""

    def __init__(self):
        self._pages: dict[int, jax.Array] = {}
        self.bytes_uploaded = 0
        self.uploads = 0

    def __contains__(self, page: int) -> bool:
        return page in self._pages

    def __len__(self) -> int:
        return len(self._pages)

    def put(self, page: int, values) -> jax.Array:
        """Install a page value; host arrays are uploaded (counted),
        device arrays (a jit apply's output) are stored as-is —
        the steady-state hot path moves zero host bytes."""
        if isinstance(values, np.ndarray):
            host = np.ascontiguousarray(values, dtype=np.float32)
            self.bytes_uploaded += host.nbytes
            self.uploads += 1
            values = jnp.asarray(host)
        self._pages[page] = values
        return values

    def get(self, page: int) -> jax.Array:
        return self._pages[page]

    def pop_host(self, page: int) -> np.ndarray:
        """Demotion fetch: device -> host, page leaves the slab."""
        return np.asarray(self._pages.pop(page), dtype=np.float32)

    def drop(self, page: int) -> None:
        self._pages.pop(page, None)

    def device_bytes(self) -> int:
        return sum(a.nbytes for a in self._pages.values())
