"""Device-side codecs: jit'd encode/decode over the flat parameter
vector (docs/COMPRESSION.md).

Encoding runs ON DEVICE — the D2H fetch at the socket boundary
(runtime/serde.py) then moves the small encoded parts (1-2 bytes per
value, or 8 bytes per kept value for top-k) instead of 4n bytes of
float32.  Decoding is also a device program: the receiver H2D-uploads
the encoded parts and expands them with one dispatch, so the values a
message carries stay jax arrays end to end (the per-node hot path's
no-host-sync property, runtime/worker.py).

Determinism contract: decode(unpack(pack(encode(v)))) on the receiver
is bitwise-identical to decode(encode(v)) on the sender — pack/unpack
are exact (compress/wire.py) and decode is one fixed program — which is
what keeps error feedback (compress/feedback.py) and durable-log replay
(log/durable_fabric.py) exact across process boundaries.

All programs are cached per (spec, n): N logical workers pay one
trace/compile, like runtime/worker._solver_fns.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from kafka_ps_tpu.compress import wire
from kafka_ps_tpu.compress.slab import dequantize_rows, quantize_rows
from kafka_ps_tpu.compress.wire import (CODEC_BF16, CODEC_INT8, CODEC_NONE,
                                        CODEC_TOPK, INT8_CHUNK, CodecSpec)
from kafka_ps_tpu.runtime.messages import EncodedValues


def _build_fns(spec: CodecSpec, n: int):
    """(encode, decode) as traceable functions over an n-vector."""
    if spec.codec_id == CODEC_BF16:
        def encode(v):
            return (jax.lax.bitcast_convert_type(
                v.astype(jnp.bfloat16), jnp.uint16),)

        def decode(bits):
            return jax.lax.bitcast_convert_type(
                bits, jnp.bfloat16).astype(jnp.float32)
        return encode, decode

    if spec.codec_id == CODEC_INT8:
        # per-chunk max-abs quantization via the shared device-side
        # primitive (compress/slab.quantize_rows — same ops, so this
        # refactor is bitwise-invisible to the EF/replay contract); the
        # wire codec's "row" is a 256-value chunk of the flat vector,
        # the slab codec's is a feature row
        nchunks = wire.int8_chunks(n)
        pad = nchunks * INT8_CHUNK - n

        def encode(v):
            r = jnp.pad(v, (0, pad)).reshape(nchunks, INT8_CHUNK)
            q, scale = quantize_rows(r)
            return q.reshape(-1), scale

        def decode(q, scale):
            r = dequantize_rows(q.reshape(nchunks, INT8_CHUNK), scale)
            return r.reshape(-1)[:n]
        return encode, decode

    if spec.codec_id == CODEC_TOPK:
        k = wire.topk_k(spec.param, n)

        def encode(v):
            # lax.top_k breaks ties toward the lower index — the
            # selection (and therefore the wire bytes) is deterministic
            _, idx = jax.lax.top_k(jnp.abs(v), k)
            return idx.astype(jnp.int32), v[idx]

        def decode(idx, vals):
            return jnp.zeros((n,), jnp.float32).at[idx].set(
                vals, unique_indices=True)
        return encode, decode

    raise ValueError(f"no device codec for {spec.spec_str()!r}")


class Codec:
    """Compiled encode/decode programs for one (spec, n)."""

    def __init__(self, spec: CodecSpec, n: int):
        self.spec = spec
        self.n = n
        encode, decode = _build_fns(spec, n)
        self._encode = jax.jit(encode)
        self._decode = jax.jit(decode)

        # Every decoded value the SENDER keeps (message values, EF
        # residual) must come from the SAME `_decode` program the
        # receiver/replay path runs — fusing decode into a larger
        # program lets XLA produce 1-ULP-different floats, which breaks
        # the bitwise EF/replay contract.  So the sender-side steps are
        # split: a fused front half up to the encoded parts, then the
        # shared `_decode`, then the residual subtraction.
        def ef_front(delta, residual):
            c = delta + residual
            return (c, *encode(c))
        self._ef_front = jax.jit(ef_front)
        self._sub = jax.jit(lambda c, d: c - d)

    def encode(self, v):
        """v (f32, length n) -> tuple of device-encoded parts."""
        return tuple(self._encode(jnp.asarray(v, jnp.float32)))

    def decode(self, *parts):
        """Encoded parts (device or host arrays) -> f32 device array."""
        return self._decode(*parts)

    def roundtrip(self, v):
        """(decoded, parts) — quantize-dequantize via the shared
        decode program (the weights side, ServerNode._weights_message)."""
        parts = self.encode(v)
        return self._decode(*parts), parts

    def ef_step(self, delta, residual):
        """(decoded, new_residual, parts): compensate + encode fused,
        then the shared decode, then the residual carry."""
        out = self._ef_front(jnp.asarray(delta, jnp.float32), residual)
        c, parts = out[0], tuple(out[1:])
        d = self._decode(*parts)
        return d, self._sub(c, d), parts

    def encoded(self, parts) -> EncodedValues:
        """Wrap device parts as the message-borne encoded payload
        (runtime/messages.EncodedValues) serde serializes verbatim."""
        return EncodedValues(codec_id=self.spec.codec_id,
                             param=self.spec.param, parts=tuple(parts))


@functools.lru_cache(maxsize=None)
def get_codec(spec: CodecSpec, n: int) -> Codec:
    return Codec(spec, n)


class WeightsCompressor:
    """Server->worker weights compression: plain quantize-dequantize,
    NO error feedback — weights are state, not an accumulated signal,
    so carrying a residual would smear old quantization error into
    unrelated rounds.  The master theta stays full-precision on the
    server; every worker (in-process or across the socket) trains on
    the identical decoded copy.

    A one-entry identity cache covers the dominant pattern: the
    consistency gate releases the SAME theta object to many workers at
    one moment (theta is updated by replacement, runtime/server.py), so
    a multi-worker release encodes once."""

    def __init__(self, codec: Codec):
        self.codec = codec
        self._cache = None          # (theta_ref, decoded, EncodedValues)

    def encode(self, theta):
        c = self._cache
        if c is not None and c[0] is theta:
            return c[1], c[2]
        decoded, parts = self.codec.roundtrip(theta)
        enc = self.codec.encoded(parts)
        self._cache = (theta, decoded, enc)
        return decoded, enc


def make_compressor(compress: str | CodecSpec, n: int):
    """`--compress` value -> WeightsCompressor, or None for "none"."""
    spec = (compress if isinstance(compress, CodecSpec)
            else wire.parse_codec(compress))
    if spec.codec_id == CODEC_NONE:
        return None
    return WeightsCompressor(get_codec(spec, n))


def decode_message_parts(codec_id: int, param: float, parts, n: int):
    """Receiver-side decode used by serde.from_bytes: H2D the unpacked
    parts and expand on device.  Returns (values, EncodedValues) so a
    decoded message re-serializes byte-identically (durable-log
    append of a replayed frame)."""
    codec = get_codec(CodecSpec(codec_id, param), n)
    parts = tuple(parts)
    return codec.decode(*parts), EncodedValues(
        codec_id=codec_id, param=codec.spec.param, parts=parts)
