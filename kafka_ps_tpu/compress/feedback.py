"""Per-worker error-feedback compression of gradient deltas.

Lossy codecs alone bias SGD: the dropped/rounded part of every delta is
gone forever.  Error feedback (Seide et al. 2014; Karimireddy et al.
2019) keeps the quantization error as a device-resident residual and
folds it into the next delta, so the compressed stream sums to the
uncompressed stream up to one in-flight residual — which is what makes
topk:0.01 trainable at all and keeps int8 accuracy within noise.

The whole step (compensate, encode, decode, new residual) is one fused
jit dispatch (compress/codecs.Codec._ef_step).  The residual is part of
worker state: it rides through utils/checkpoint.py (key
``ef{worker}_residual``) so a SIGKILL'd run resumes with the exact
residual it crashed with — replaying the durable log then reproduces
the same compressed bytes.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from kafka_ps_tpu.compress.codecs import Codec


class ErrorFeedback:
    """Gradient-side compressor for ONE logical worker (residuals are
    per-stream: mixing two workers' errors into one residual would
    re-introduce the bias error feedback exists to cancel)."""

    def __init__(self, codec: Codec):
        self.codec = codec
        self.residual = jnp.zeros((codec.n,), jnp.float32)

    def step(self, delta):
        """delta -> (decoded_delta, EncodedValues) to send; the
        residual (delta + residual − decoded) carries to the next call."""
        decoded, self.residual, parts = self.codec.ef_step(
            delta, self.residual)
        return decoded, self.codec.encoded(parts)

    # -- checkpoint plumbing (utils/checkpoint.py) -----------------------

    def state(self) -> np.ndarray:
        return np.asarray(self.residual, dtype=np.float32)

    def restore(self, arr) -> None:
        self.residual = jnp.asarray(arr, jnp.float32)
