"""Compressed delta transport: device-side codecs (bf16 / int8 /
topk:R), error-feedback residuals, and the host wire format.  See
docs/COMPRESSION.md.

``compress.wire`` is importable without jax (runtime/serde.py depends
only on it); importing this package root pulls in the device codecs.
"""

from kafka_ps_tpu.compress.codecs import (Codec, WeightsCompressor,
                                          decode_message_parts, get_codec,
                                          make_compressor)
from kafka_ps_tpu.compress.feedback import ErrorFeedback
from kafka_ps_tpu.compress.slab import (SLAB_DTYPES, QuantizedSlab,
                                        SlabStore, decode_x,
                                        dequantize_rows, quantize_rows)
from kafka_ps_tpu.compress.wire import (CODEC_BF16, CODEC_INT8, CODEC_NONE,
                                        CODEC_TOPK, INT8_CHUNK, NONE,
                                        CodecSpec, parse_codec)

__all__ = [
    "Codec", "CodecSpec", "ErrorFeedback", "QuantizedSlab", "SlabStore",
    "SLAB_DTYPES", "WeightsCompressor",
    "CODEC_NONE", "CODEC_BF16", "CODEC_INT8", "CODEC_TOPK", "INT8_CHUNK",
    "NONE", "decode_message_parts", "decode_x", "dequantize_rows",
    "get_codec", "make_compressor", "parse_codec", "quantize_rows",
]
