"""Host-side wire format of the compressed codecs (docs/COMPRESSION.md).

This module is deliberately jax-free: runtime/serde.py packs and
unpacks compressed frames through it without pulling a device runtime
into the serialization layer.  The device-side encode/decode lives in
compress/codecs.py; both share the codec ids and the CodecSpec
identity defined here.

Codec table (codec id, wire parts, asymptotic ratio vs raw f32):

  0 none   — never appears on the wire (legacy f32 frames)
  1 bf16   — <u16 bits[n]>                               2x
  2 int8   — <f4 scales[ceil(n/256)]> <i1 q[n]>, then a  ~4x + zlib
             lossless zlib stage over the whole blob
             (flag bit 0; raw fallback when zlib grows it)
  3 topk:R — <i4 idx[k]> <f4 vals[k]>, k = max(1, R*n)   ~1/(2R)

Pack/unpack are exact inverses: the receiver reconstructs the sender's
encoded parts bit-for-bit, so decoding on either side of the socket
yields the same float32 values — the invariant the error-feedback
residuals (compress/feedback.py) and the durable log's exactly-once
replay (log/durable_fabric.py) both rely on.
"""

from __future__ import annotations

import dataclasses
import zlib

import numpy as np

CODEC_NONE = 0
CODEC_BF16 = 1
CODEC_INT8 = 2
# topk parts are (indices, values) over the full key space — which is
# why a range-sharded router can split a topk delta by index range
# into per-shard SparseDeltaMessages without decoding it
# (runtime/sharding.ShardPlan.split_sparse, docs/SHARDING.md)
CODEC_TOPK = 3

_CODEC_NAMES = {CODEC_NONE: "none", CODEC_BF16: "bf16",
                CODEC_INT8: "int8", CODEC_TOPK: "topk"}

# int8 quantization granularity: one f32 scale per 256-value chunk
INT8_CHUNK = 256
# lossless stage over the int8 blob (the QSGD entropy-coding analogue,
# Alistarh et al. 2017 §3.3): quantized deltas cluster near zero, so a
# cheap deflate pass is what carries the codec past the 4x bound that
# raw int8+scales can never reach (4n / (n + scales) < 4)
_ZLIB_LEVEL = 6
FLAG_ZLIB = 1


@dataclasses.dataclass(frozen=True)
class CodecSpec:
    """Codec identity as negotiated on the HELLO exchange
    (runtime/net.py): id + one f32 parameter (the top-k ratio; 0 for
    the parameter-free codecs).  `param` is canonicalized through
    float32 so a spec parsed locally compares equal to one that crossed
    the wire as <f4>."""

    codec_id: int
    param: float = 0.0

    def __post_init__(self):
        if self.codec_id not in _CODEC_NAMES:
            raise ValueError(f"unknown codec id {self.codec_id}")
        object.__setattr__(self, "param", float(np.float32(self.param)))
        if self.codec_id == CODEC_TOPK and not 0.0 < self.param <= 1.0:
            raise ValueError(
                f"topk ratio must be in (0, 1], got {self.param}")

    @property
    def name(self) -> str:
        return _CODEC_NAMES[self.codec_id]

    def spec_str(self) -> str:
        """The `--compress` flag form this spec round-trips from."""
        if self.codec_id == CODEC_TOPK:
            return f"topk:{self.param:g}"
        return self.name


NONE = CodecSpec(CODEC_NONE)


def parse_codec(spec: str | None) -> CodecSpec:
    """Parse a `--compress` value: none | bf16 | int8 | topk:<ratio>."""
    if spec is None or spec == "" or spec == "none":
        return NONE
    if spec == "bf16":
        return CodecSpec(CODEC_BF16)
    if spec == "int8":
        return CodecSpec(CODEC_INT8)
    if spec.startswith("topk:"):
        try:
            ratio = float(spec[len("topk:"):])
        except ValueError:
            raise ValueError(f"bad topk ratio in {spec!r}") from None
        return CodecSpec(CODEC_TOPK, ratio)
    raise ValueError(
        f"unknown codec {spec!r} (expected none, bf16, int8 or topk:R)")


def topk_k(param: float, n: int) -> int:
    """The static k of a topk:R codec over an n-vector."""
    return max(1, min(n, int(round(param * n))))


def int8_chunks(n: int) -> int:
    return -(-n // INT8_CHUNK)


# -- pack / unpack -----------------------------------------------------------

def pack_parts(codec_id: int, parts, n: int) -> tuple[int, int, bytes]:
    """Encoded parts (host arrays) of an n-vector -> (flags, aux, blob).
    `aux` is the codec's shape word (k for topk, chunk count for int8,
    0 for bf16) so unpack needs nothing beyond the frame's KeyRange."""
    if codec_id == CODEC_BF16:
        (bits,) = parts
        return 0, 0, np.ascontiguousarray(bits, dtype="<u2").tobytes()
    if codec_id == CODEC_INT8:
        q, scales = parts
        scales = np.ascontiguousarray(scales, dtype="<f4")
        # the padded tail of q is exactly zero (zero input quantizes to
        # zero) — trim it to n bytes; unpack re-pads
        q = np.ascontiguousarray(q, dtype=np.int8)[:n]
        nchunks = len(scales)
        blob = scales.tobytes() + q.tobytes()
        comp = zlib.compress(blob, _ZLIB_LEVEL)
        if len(comp) < len(blob):
            return FLAG_ZLIB, nchunks, comp
        return 0, nchunks, blob
    if codec_id == CODEC_TOPK:
        idx, vals = parts
        idx = np.ascontiguousarray(idx, dtype="<i4")
        vals = np.ascontiguousarray(vals, dtype="<f4")
        return 0, len(idx), idx.tobytes() + vals.tobytes()
    raise ValueError(f"cannot pack codec id {codec_id}")


def unpack_parts(codec_id: int, flags: int, aux: int, blob, n: int):
    """(flags, aux, blob) -> the sender's encoded parts, bit-exact.
    `blob` may be any bytes-like (memoryview payloads included)."""
    if codec_id == CODEC_BF16:
        return (np.frombuffer(blob, dtype="<u2", count=n),)
    if codec_id == CODEC_INT8:
        if flags & FLAG_ZLIB:
            blob = zlib.decompress(blob)
        nchunks = aux
        scales = np.frombuffer(blob, dtype="<f4", count=nchunks)
        stored = len(blob) - 4 * nchunks
        q = np.zeros(nchunks * INT8_CHUNK, dtype=np.int8)
        q[:stored] = np.frombuffer(blob, dtype=np.int8, count=stored,
                                   offset=4 * nchunks)
        return q, scales
    if codec_id == CODEC_TOPK:
        idx = np.frombuffer(blob, dtype="<i4", count=aux)
        vals = np.frombuffer(blob, dtype="<f4", count=aux, offset=4 * aux)
        return idx, vals
    raise ValueError(f"cannot unpack codec id {codec_id}")
