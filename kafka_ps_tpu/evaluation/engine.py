"""Async coalescing eval engine — continuous test-set evaluation off
the apply critical path (docs/EVALUATION.md "Async evaluation").

The reference evaluates the full test set inside every server iteration
(ServerProcessor.java:153-165); our fused port kept that shape — eval
rides the apply dispatch (`ServerNode._apply_full_eval`) and costs ~2x
per-node throughput at `eval_every=1` (BENCH r5: 148 vs 295 iters/s),
because each eval re-reads the whole test set for a single theta — a
memory-bound pass (docs/ROOFLINE.md).

This engine is the serving plane's batching economics (Clipper-style,
serving/engine.py) applied to evaluation:

  * the server hands over `(theta, clock)` pairs with an O(1) append —
    thetas are immutable device aliases by the same contract that lets
    serving snapshots alias them (serving/snapshot.py module doc:
    ServerNode only ever REPLACES theta, never mutates it), so enqueue
    costs no copy and no host sync;
  * a dedicated `kps-eval` thread pops the whole backlog and evaluates
    k pending thetas as ONE batched dispatch — the vmap-of-kernel
    construction PR 2 proved bitwise for gang solvers (runtime/gang.py
    stacks thetas the same way): vmap runs the identical per-element
    program, so each row's metrics are bit-identical to a standalone
    eval of that theta;
  * results are emitted in strict clock order whatever the coalescing
    did, through the SAME emission point the fused path uses
    (`ServerNode._emit_eval`): CSV rows, `last_metrics`, and
    `DriftMonitor.observe_eval` see the exact fused-path sequence.

Coalescing widths bucket to powers of two (pad by REPEATING the last
theta and discard the extra rows — vmap rows are independent, so
padding is bitwise-neutral) and are capped by the fused-update tile
budget (`coalesce_width_cap`): chunking happens over pending thetas,
NEVER over the test set — splitting X_test would reorder the loss-mean
reduction and break the bitwise contract.

Crash story: the engine holds no durable state.  Pending-eval clocks
are exactly the eval-cadence clocks of gradients the durable log will
replay (log/durable_fabric.py) — a restarted server re-applies them
and re-submits the same (theta, clock) pairs, so no new checkpoint
state exists (tier1.sh --eval pins this under SIGKILL).

pscheck scope: PS102 (no host sync in submit/dispatch), PS104 (no wall
clock — timestamps belong to the emission callback, which lives in
runtime/server.py), PS106 (telemetry calls carry host ints only).
"""

from __future__ import annotations

import sys
import threading
from collections import deque

from kafka_ps_tpu.analysis.lockgraph import OrderedCondition
from kafka_ps_tpu.telemetry import NULL_TELEMETRY
from kafka_ps_tpu.telemetry.flight import FLIGHT
from kafka_ps_tpu.utils.trace import NULL_TRACER

# hard ceiling on a single batched dispatch, independent of the byte
# budget: beyond this the stacked matmul stops gaining and the jit
# program zoo grows for nothing
_MAX_COALESCE = 32

# coalesce-width histogram buckets (powers of two up to the ceiling)
WIDTH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0)

_FALLBACK_VMEM_BUDGET = 12 * 1024 * 1024


def _vmem_budget() -> int:
    """The fused-update tile budget (ops/fused_update.py) — guarded so
    an environment without the pallas toolchain still gets the same
    constant."""
    try:
        from kafka_ps_tpu.ops.fused_update import _VMEM_BYTE_BUDGET
        return int(_VMEM_BYTE_BUDGET)
    except Exception:                      # pragma: no cover - no pallas
        return _FALLBACK_VMEM_BUDGET


def coalesce_width_cap(num_params: int, n_test: int,
                       budget: int | None = None) -> int:
    """Widest power-of-two batch such that the stacked working set
    (k thetas + k per-example score rows against the resident test set)
    stays inside the fused-update tile budget.  The estimate charges
    one f32 per test row per lane — the score/prediction row the
    confusion-matrix build materializes (models/metrics.py) — plus the
    lane's theta; deliberately coarse, it only has to keep `n_test x k`
    from outgrowing the tile budget, not model VMEM exactly."""
    if budget is None:
        budget = _vmem_budget()
    lane_bytes = 4 * (int(num_params) + int(n_test))
    cap = max(1, int(budget) // max(lane_bytes, 1))
    width = 1
    while width * 2 <= min(cap, _MAX_COALESCE):
        width *= 2
    return width


class EvalEngine:
    """Dedicated eval thread over a bounded (theta, clock) queue.

    `emit(clock, metrics)` is called on the engine thread in strict
    clock order — the caller owns row formatting, timestamps and
    downstream fan-out (ServerNode._emit_eval / the sharded group's
    row writer), so this module stays free of wall-clock reads.

    The thread is lazy and self-reaping (the DeferredSink discipline,
    utils/asynclog.py): started on first submit, exits after
    `idle_exit` seconds with nothing pending, restarted by the next
    submit — a process must never finalize with a live thread inside
    XLA (docs/TESTING.md).
    """

    def __init__(self, task, test_x, test_y, emit, *,
                 max_pending: int = 64, max_width: int | None = None,
                 telemetry=None, tracer=None,
                 start_thread: bool = True,
                 idle_exit: float = 10.0):
        import jax.numpy as jnp
        self._task = task
        self._tx = jnp.asarray(test_x)
        self._ty = jnp.asarray(test_y)
        self._emit = emit
        self._max_pending = int(max_pending)
        self._max_width = int(max_width) if max_width else \
            coalesce_width_cap(task.num_params, self._tx.shape[0])
        self._start_thread = start_thread
        self._idle_exit = idle_exit
        self.tracer = tracer or NULL_TRACER
        self.telemetry = telemetry or NULL_TELEMETRY
        self._m_lag = self.telemetry.gauge(
            "eval_lag_clocks",
            help_text="newest submitted eval clock minus newest "
                      "evaluated eval clock (async eval backlog)")
        self._m_width = self.telemetry.histogram(
            "eval_coalesce_width", buckets=WIDTH_BUCKETS,
            help_text="pending thetas coalesced per batched eval "
                      "dispatch")
        # pending (theta, clock) pairs + all engine state, one lock
        self._pending: deque = deque()
        self._cv = OrderedCondition("EvalEngine.pending")
        self._inflight = 0           # popped but not yet emitted
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # host-side counters for /evalz (telemetry/health.py)
        # guarded-by: _cv (submit writes hold the cv; reads are int snapshots)
        self._submitted_clock = -1
        # pscheck: disable=PS201 (dispatch-side monotonic clock; lag/stats reads tolerate a one-batch-stale snapshot)
        self._evaluated_clock = -1
        # pscheck: disable=PS201 (telemetry counter; racing poll drivers at worst undercount a stat)
        self._dispatches = 0
        # pscheck: disable=PS201 (telemetry counter; racing poll drivers at worst undercount a stat)
        self._evals = 0
        # pscheck: disable=PS201 (telemetry histogram; racing poll drivers at worst undercount a stat)
        self._width_counts: dict[int, int] = {}
        # pscheck: disable=PS201 (jit cache; a racing rebuild traces the same function - idempotent)
        self._programs: dict[int, object] = {}

    # -- producer side (the server's apply path) ---------------------------

    def submit(self, theta, clock: int) -> None:
        """O(1) hand-off of an immutable theta alias at an eval-cadence
        clock.  Never syncs the device and never formats — the whole
        point is that the apply path sheds eval entirely.  A backlog
        past `max_pending` makes the SUBMITTER wait for the engine to
        catch up (each queued theta pins a device array; the bound is
        the memory cap, and dropping is not an option — every clock
        owes a CSV row under the bitwise contract)."""
        clock = int(clock)
        with self._cv:
            self._pending.append((theta, clock))
            self._submitted_clock = clock
            backlog = len(self._pending)
            self._cv.notify_all()
        if self.telemetry.enabled:
            self._m_lag.set(self._submitted_clock - self._evaluated_clock)
        if self._start_thread:
            self._ensure_thread()
        if backlog > self._max_pending:
            self.drain()

    @property
    def lag_clocks(self) -> int:
        """Newest submitted eval clock minus newest evaluated one —
        0 when every released eval clock has been evaluated."""
        if self._submitted_clock < 0:
            return 0
        return self._submitted_clock - self._evaluated_clock

    # -- the kps-eval thread -----------------------------------------------

    def _ensure_thread(self) -> None:
        with self._cv:
            t = self._thread
            if t is None or not t.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="kps-eval")
                self._thread.start()

    def _loop(self) -> None:
        idle = 0.0
        tick = 0.25
        while not self._stop.is_set():
            with self._cv:
                if not self._pending:
                    self._cv.wait(timeout=tick)
            if not self.poll():
                idle += tick
                if idle >= self._idle_exit:
                    with self._cv:
                        if self._thread is threading.current_thread():
                            self._thread = None
                    return
            else:
                idle = 0.0

    def poll(self) -> bool:
        """Pop up to one coalesced batch and dispatch it.  Returns
        whether anything was evaluated.  Runs on the engine thread in
        steady state; tests and close() call it directly for
        deterministic, caller-thread dispatch."""
        with self._cv:
            if not self._pending:
                return False
            batch = []
            while self._pending and len(batch) < self._max_width:
                batch.append(self._pending.popleft())
            self._inflight = len(batch)
        try:
            self._dispatch(batch)
        except Exception as e:       # pragma: no cover - diagnostics
            print(f"eval engine dispatch error: {e!r}", file=sys.stderr)
        finally:
            with self._cv:
                self._inflight = 0
                self._cv.notify_all()
        return True

    def _dispatch(self, batch) -> None:
        """ONE batched eval for the popped backlog, then emission in
        strict clock order.  Width buckets to the next power of two by
        repeating the last theta; the padded rows' outputs are
        discarded (vmap rows are independent — padding is
        bitwise-neutral for the kept rows)."""
        import jax.numpy as jnp
        k = len(batch)
        width = 1
        while width < k:
            width *= 2
        clock_lo, clock_hi = batch[0][1], batch[-1][1]
        with self.tracer.span("server.eval", clock=clock_hi,
                              coalesced=k):
            thetas = [jnp.asarray(t) for t, _ in batch]
            thetas.extend([thetas[-1]] * (width - k))
            mets = self._program(width)(self._tx, self._ty, *thetas)
            self.tracer.count("eval.dispatch_async")
        self._dispatches += 1
        self._evals += k
        self._width_counts[k] = self._width_counts.get(k, 0) + 1
        if self.telemetry.enabled:
            self._m_width.observe(k)
        if FLIGHT.enabled:
            FLIGHT.record("eval.dispatch", width=k,
                          clock_lo=clock_lo, clock_hi=clock_hi)
        for i, (_, clock) in enumerate(batch):
            self._emit(clock, mets[i])
            self._evaluated_clock = clock
        if self.telemetry.enabled:
            self._m_lag.set(max(
                0, self._submitted_clock - self._evaluated_clock))

    def _program(self, width: int):
        """Cached jit per coalesce width.  Width 1 is the standalone
        eval program; width k vmaps the SAME per-element program over
        stacked thetas (models/task.evaluate_batch) and unstacks the
        per-row metrics INSIDE the jit — fan-out costs no extra
        dispatches (the runtime/gang.py idiom).  The test set rides as
        arguments, exactly as the fused `_apply_full_eval` passes it."""
        fn = self._programs.get(width)
        if fn is None:
            import jax
            import jax.numpy as jnp
            task = self._task
            if width == 1:
                def single(tx, ty, theta):
                    return (task.evaluate(theta, tx, ty),)
                fn = jax.jit(single)
            else:
                def batched(tx, ty, *thetas):
                    met = task.evaluate_batch(jnp.stack(thetas), tx, ty)
                    return tuple(
                        type(met)(f1=met.f1[i], accuracy=met.accuracy[i],
                                  loss=met.loss[i])
                        for i in range(len(thetas)))
                fn = jax.jit(batched)
            self._programs[width] = fn
        return fn

    # -- lifecycle / introspection -----------------------------------------

    def drain(self, timeout: float = 120.0) -> None:
        """Block until every submitted clock has been dispatched AND
        emitted (rows handed to the log sink; device fetches may still
        be in flight — DeferredSink.flush owns those).  Drive loops
        call this at exit so `eval_lag_clocks` returns to 0 and the
        CSV is complete before sinks flush."""
        if self._start_thread:
            self._ensure_thread()
            with self._cv:
                ok = self._cv.wait_for(
                    lambda: (not self._pending and self._inflight == 0)
                    or self._stop.is_set(),
                    timeout=timeout)
            if not ok:               # pragma: no cover - watchdog
                raise TimeoutError("eval engine drain timed out")
        else:
            while self.poll():
                pass

    def close(self) -> None:
        """Drain, stop and join the kps-eval thread (it dispatches jit
        programs — must be joined before interpreter exit,
        docs/TESTING.md), then evaluate anything still pending inline."""
        if self._start_thread and not self._stop.is_set():
            try:
                self.drain()
            except TimeoutError:     # pragma: no cover - watchdog
                pass
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
            t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=60.0)
        while self.poll():           # leftovers after a timed-out drain
            pass

    def stats(self) -> dict:
        """Host-side pulse for the /evalz health endpoint."""
        with self._cv:
            pending = len(self._pending) + self._inflight
        return {
            "pending": pending,
            "submitted_clock": self._submitted_clock,
            "evaluated_clock": self._evaluated_clock,
            "lag_clocks": self.lag_clocks,
            "dispatches": self._dispatches,
            "evals": self._evals,
            "max_width": self._max_width,
            "widths": {str(w): n for w, n in
                       sorted(self._width_counts.items())},
        }
