"""CSV log parsing + derived run statistics.

The reference's notebooks load `logs-server.csv` / `logs-worker.csv`
(semicolon-separated, schema ServerAppRunner.java:81 /
WorkerAppRunner.java:80) and derive loss/F1/accuracy curves over time and
tuples-seen.  This module reproduces those derivations — plus the summary
columns SURVEY §6 computes from the committed logs (duration, iters/s,
best F1, wall-clock-to-F1-target) — so runs of this framework and the
reference's own committed logs are comparable with the same code.
"""

from __future__ import annotations

import dataclasses

import pandas as pd

SERVER_COLUMNS = ["timestamp", "partition", "vectorClock", "loss",
                  "fMeasure", "accuracy"]
WORKER_COLUMNS = SERVER_COLUMNS + ["numTuplesSeen"]
# drift verdict log (utils/csvlog.DRIFT_HEADER, written by the CLI's
# wall-clock-stamping sink around telemetry/drift.py): one row per
# warn/trip edge
DRIFT_COLUMNS = ["timestamp", "event", "detector", "statistic", "signal"]


def _load(path: str, columns: list[str]) -> pd.DataFrame:
    df = pd.read_csv(path, sep=";")
    missing = [c for c in columns if c not in df.columns]
    if missing:
        raise ValueError(f"{path}: missing log columns {missing} "
                         f"(have {list(df.columns)})")
    df = df[columns].apply(pd.to_numeric, errors="coerce")
    df = df.dropna(subset=["timestamp", "vectorClock"])
    # relative seconds since run start (notebooks plot against this)
    if len(df):
        df["seconds"] = (df["timestamp"] - df["timestamp"].iloc[0]) / 1000.0
    else:
        df["seconds"] = pd.Series(dtype=float)
    return df.reset_index(drop=True)


def load_server_log(path: str) -> pd.DataFrame:
    return _load(path, SERVER_COLUMNS)


def load_worker_log(path: str) -> pd.DataFrame:
    return _load(path, WORKER_COLUMNS)


def load_drift_log(path: str) -> pd.DataFrame:
    """Load a `logs-drift.csv` (--model-health -l): warn/trip verdict
    rows with numeric timestamp/statistic and derived relative seconds.
    `event`/`detector`/`signal` stay categorical strings."""
    df = pd.read_csv(path, sep=";")
    missing = [c for c in DRIFT_COLUMNS if c not in df.columns]
    if missing:
        raise ValueError(f"{path}: missing drift columns {missing} "
                         f"(have {list(df.columns)})")
    df = df[DRIFT_COLUMNS].copy()
    for c in ("timestamp", "statistic"):
        df[c] = pd.to_numeric(df[c], errors="coerce")
    df = df.dropna(subset=["timestamp"])
    if len(df):
        df["seconds"] = (df["timestamp"] - df["timestamp"].iloc[0]) / 1000.0
    else:
        df["seconds"] = pd.Series(dtype=float)
    return df.reset_index(drop=True)


def with_drift_events(server_df: pd.DataFrame,
                      drift_df: pd.DataFrame) -> pd.DataFrame:
    """Join the drift verdicts onto the server eval curve: adds a
    `drift_events` column — the cumulative count of drift TRIPS at or
    before each eval row's timestamp — so a loss/F1 plot can mark
    where the detectors fired.  An empty drift log yields all zeros."""
    out = server_df.copy()
    trips = drift_df.loc[drift_df["event"] == "trip", "timestamp"]
    trip_ts = trips.sort_values().to_numpy()
    if len(trip_ts) == 0:
        out["drift_events"] = 0
        return out
    import numpy as np
    out["drift_events"] = np.searchsorted(
        trip_ts, out["timestamp"].to_numpy(), side="right")
    return out


@dataclasses.dataclass(frozen=True)
class RunSummary:
    """The derived columns of SURVEY §6 / BASELINE.md for one run."""

    duration_s: float          # last − first server timestamp
    iterations: int            # max vector clock seen by the server
    iters_per_sec: float | None   # None on zero-duration (degenerate) logs
    best_f1: float
    best_accuracy: float
    final_loss: float
    secs_to_f1: dict[float, float | None]   # target -> wall-clock seconds
    worker_updates_per_sec: float | None = None   # aggregate, worker log

    def row(self) -> dict:
        out = dataclasses.asdict(self)
        out.update({f"secs_to_f1_{t:g}": v
                    for t, v in out.pop("secs_to_f1").items()})
        return out


def summarize_run(server_df: pd.DataFrame,
                  worker_df: pd.DataFrame | None = None,
                  f1_targets: tuple[float, ...] = (0.40, 0.44)) -> RunSummary:
    if not len(server_df):
        raise ValueError("empty server log — run produced no iterations")
    duration = float(server_df["seconds"].iloc[-1])
    iterations = int(server_df["vectorClock"].max())
    secs_to = {}
    for t in f1_targets:
        hit = server_df.loc[server_df["fMeasure"] >= t, "seconds"]
        secs_to[t] = float(hit.iloc[0]) if len(hit) else None
    wups = None
    if worker_df is not None and len(worker_df) > 1:
        span = float(worker_df["seconds"].iloc[-1])
        wups = (len(worker_df) / span) if span > 0 else None
    return RunSummary(
        duration_s=duration,
        iterations=iterations,
        iters_per_sec=iterations / duration if duration > 0 else None,
        best_f1=float(server_df["fMeasure"].max()),
        best_accuracy=float(server_df["accuracy"].max()),
        final_loss=float(server_df["loss"].iloc[-1]),
        secs_to_f1=secs_to,
        worker_updates_per_sec=wups,
    )


def compare_runs(named_server_logs: dict[str, str]) -> pd.DataFrame:
    """Cross-run table (evaluation-multipleDatasetsAtOnce.ipynb): one row
    per run config with the §6 derived columns."""
    rows = []
    for name, path in named_server_logs.items():
        s = summarize_run(load_server_log(path))
        rows.append({"run": name, **s.row()})
    return pd.DataFrame(rows)


def tuples_seen_curve(worker_df: pd.DataFrame) -> pd.DataFrame:
    """F1/accuracy against cumulative tuples seen (the x-axis the
    reference's per-run plots use for the streaming-progress view)."""
    g = worker_df.groupby("vectorClock").agg(
        numTuplesSeen=("numTuplesSeen", "max"),
        fMeasure=("fMeasure", "mean"),
        accuracy=("accuracy", "mean"),
        loss=("loss", "mean"),
        seconds=("seconds", "max"),
    )
    return g.reset_index().sort_values("vectorClock")


def worker_clock_spread(worker_df: pd.DataFrame) -> pd.DataFrame:
    """Fastest-vs-slowest worker iteration gap over time — the metric the
    reference uses to characterize eventual consistency (README.md:316-323:
    ~20-iteration gap under `-c -1`).

    Per second bucket: each worker's latest vector clock, then max − min
    across workers (not across raw rows — a single fast worker logging
    several clocks within one second is progression, not staleness)."""
    df = worker_df.copy()
    df["second_bucket"] = df["seconds"].astype(int)
    latest = df.groupby(["second_bucket", "partition"])["vectorClock"].max()
    g = latest.groupby("second_bucket").agg(["min", "max"])
    g["spread"] = g["max"] - g["min"]
    return g.reset_index()
