"""CLI for the offline evaluation subsystem.

  python -m kafka_ps_tpu.evaluation summarize --server logs-server.csv
      [--worker logs-worker.csv]
  python -m kafka_ps_tpu.evaluation plot      --server logs-server.csv [--worker ...] --out run.png
  python -m kafka_ps_tpu.evaluation compare   --runs name=path [name=path ...] --out cmp.png
  python -m kafka_ps_tpu.evaluation validate  --worker logs-worker.csv
      [--server ...] -c K [--elastic]
  python -m kafka_ps_tpu.evaluation ground-truth --train train.csv --test test.csv

Replaces the reference's three Jupyter notebooks (SURVEY §3.4) with
scriptable equivalents over the same CSV log schema.
"""

from __future__ import annotations

import argparse
import json
import sys


def _parse_runs(pairs: list[str]) -> dict[str, str]:
    out = {}
    for p in pairs:
        name, _, path = p.partition("=")
        if not path:
            raise SystemExit(f"--runs entries must be name=path, got {p!r}")
        out[name] = path
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kafka_ps_tpu.evaluation")
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("summarize")
    s.add_argument("--server", required=True)
    s.add_argument("--worker")

    s = sub.add_parser("plot")
    s.add_argument("--server", required=True)
    s.add_argument("--worker")
    s.add_argument("--out", required=True)
    s.add_argument("--spread-out", help="also plot worker clock spread")

    s = sub.add_parser("compare")
    s.add_argument("--runs", nargs="+", required=True, metavar="name=path")
    s.add_argument("--out")
    s.add_argument("--x", default="seconds", choices=["seconds", "vectorClock"])

    s = sub.add_parser("validate")
    s.add_argument("--worker")
    s.add_argument("--server")
    s.add_argument("-c", "--consistency_model", type=int, default=0)
    s.add_argument("--elastic", action="store_true",
                   help="run used failure_policy=rebalance; with "
                        "--events the full contract is re-derived per "
                        "membership epoch, without it only clock "
                        "monotonicity is checked")
    s.add_argument("--events", metavar="logs-events.csv",
                   help="the server's membership-change record "
                        "(timestamp;event;partition) — written by split-"
                        "mode runs with -l (cli/socket_mode.py)")

    s = sub.add_parser("ground-truth")
    s.add_argument("--train", required=True)
    s.add_argument("--test", required=True)
    s.add_argument("--steps", type=int, default=500)
    s.add_argument("--lr", type=float, default=0.5)
    s.add_argument("--num_classes", type=int,
                   help="default: inferred as max label in the data")

    args = ap.parse_args(argv)

    from kafka_ps_tpu.evaluation import logs as logs_mod

    if args.cmd == "summarize":
        sdf = logs_mod.load_server_log(args.server)
        wdf = logs_mod.load_worker_log(args.worker) if args.worker else None
        print(json.dumps(logs_mod.summarize_run(sdf, wdf).row(), indent=2))
    elif args.cmd == "plot":
        from kafka_ps_tpu.evaluation import plots
        if args.spread_out and not args.worker:
            raise SystemExit("--spread-out requires --worker")
        print(plots.plot_run(args.server, args.worker, args.out))
        if args.spread_out:
            print(plots.plot_clock_spread(args.worker, args.spread_out))
    elif args.cmd == "compare":
        from kafka_ps_tpu.evaluation import plots
        runs = _parse_runs(args.runs)
        table = plots.comparison_table(runs)
        print(table.to_string(index=False))
        if args.out:
            print(plots.plot_comparison(runs, args.out, x=args.x))
    elif args.cmd == "validate":
        from kafka_ps_tpu.evaluation import validate
        if not args.worker and not args.server:
            raise SystemExit("validate needs --worker and/or --server")
        wdf = logs_mod.load_worker_log(args.worker) if args.worker else None
        sdf = logs_mod.load_server_log(args.server) if args.server else None
        events = (validate.load_membership_events(args.events)
                  if args.events else None)
        violations = validate.validate_run(wdf, sdf, args.consistency_model,
                                           elastic=args.elastic or
                                           bool(events),
                                           membership_events=events)
        for v in violations:
            print(f"VIOLATION [{v.rule}] {v.detail}")
        print(f"{len(violations)} violation(s)")
        return 1 if violations else 0
    elif args.cmd == "ground-truth":
        from kafka_ps_tpu.data.stream import load_csv_dataset
        from kafka_ps_tpu.evaluation import ground_truth
        from kafka_ps_tpu.utils.config import ModelConfig
        train_x, train_y = load_csv_dataset(args.train)
        test_x, test_y = load_csv_dataset(args.test)
        # rows span 0..max_label (the reference's Spark sizing,
        # LogisticRegressionTaskSpark.java:98-104), so num_classes must
        # cover the data or out-of-range labels silently NaN the loss
        num_classes = args.num_classes or int(max(train_y.max(),
                                                  test_y.max()))
        cfg = ModelConfig(num_features=train_x.shape[1],
                          num_classes=num_classes)
        gt = ground_truth.compute(train_x, train_y, test_x, test_y, cfg,
                                  steps=args.steps, learning_rate=args.lr)
        print(json.dumps({"f1": round(gt.f1, 4),
                          "accuracy": round(gt.accuracy, 4),
                          "loss": round(gt.loss, 4)}, indent=2))
        print(gt.report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
