"""Plot generation — matplotlib port of the reference's two analysis
notebooks (evaluation/plot-generation.ipynb cells 0-10,
evaluation/evaluation-multipleDatasetsAtOnce.ipynb cells 0-9).

Per-run: loss / F1 / accuracy against wall-clock and tuples-seen.
Cross-run: consistency-model / event-frequency comparison of the F1
curves (the docs/plots/*.png family of the reference).
"""

from __future__ import annotations

import os

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import pandas as pd  # noqa: E402

from kafka_ps_tpu.evaluation import logs as logs_mod  # noqa: E402


def plot_run(server_log: str, worker_log: str | None, out_path: str,
             title: str | None = None) -> str:
    """One run: metric curves vs wall-clock (and vs tuples-seen when a
    worker log is available)."""
    sdf = logs_mod.load_server_log(server_log)
    wdf = logs_mod.load_worker_log(worker_log) if worker_log else None
    ncols = 3 if wdf is not None else 2
    fig, axes = plt.subplots(1, ncols, figsize=(5 * ncols, 4))

    ax = axes[0]
    ax.plot(sdf["seconds"], sdf["fMeasure"], label="weighted F1")
    ax.plot(sdf["seconds"], sdf["accuracy"], label="accuracy")
    ax.set_xlabel("seconds")
    ax.set_ylabel("metric")
    ax.set_title("test metrics vs wall-clock")
    ax.legend()
    ax.grid(alpha=0.3)

    ax = axes[1]
    valid_loss = sdf[sdf["loss"] >= 0]
    ax.plot(valid_loss["seconds"], valid_loss["loss"], color="tab:red")
    ax.set_xlabel("seconds")
    ax.set_ylabel("test loss")
    ax.set_title("loss vs wall-clock")
    ax.grid(alpha=0.3)

    if wdf is not None:
        curve = logs_mod.tuples_seen_curve(wdf)
        ax = axes[2]
        ax.plot(curve["numTuplesSeen"], curve["fMeasure"], label="weighted F1")
        ax.plot(curve["numTuplesSeen"], curve["accuracy"], label="accuracy")
        ax.set_xlabel("tuples seen")
        ax.set_title("metrics vs tuples seen")
        ax.legend()
        ax.grid(alpha=0.3)

    fig.suptitle(title or os.path.basename(server_log))
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def plot_comparison(named_server_logs: dict[str, str], out_path: str,
                    x: str = "seconds", title: str = "run comparison") -> str:
    """Overlayed F1 curves for several runs (consistency models, event
    frequencies, worker counts — the reference's comparison plots)."""
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(11, 4))
    for name, path in named_server_logs.items():
        sdf = logs_mod.load_server_log(path)
        ax1.plot(sdf[x], sdf["fMeasure"], label=name)
        ax2.plot(sdf[x], sdf["accuracy"], label=name)
    for ax, ylab in ((ax1, "weighted F1"), (ax2, "accuracy")):
        ax.set_xlabel(x)
        ax.set_ylabel(ylab)
        ax.legend()
        ax.grid(alpha=0.3)
    fig.suptitle(title)
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def plot_clock_spread(worker_log: str, out_path: str,
                      title: str | None = None) -> str:
    """Fastest-minus-slowest worker vector-clock spread over time — shows
    the staleness behavior of the three consistency models (README.md
    reports ~20-iteration spread for eventual, ≤k for bounded delay)."""
    wdf = logs_mod.load_worker_log(worker_log)
    spread = logs_mod.worker_clock_spread(wdf)
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.step(spread["second_bucket"], spread["spread"], where="post")
    ax.set_xlabel("seconds")
    ax.set_ylabel("max − min worker vector clock")
    ax.set_title(title or "worker iteration spread")
    ax.grid(alpha=0.3)
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def comparison_table(named_server_logs: dict[str, str]) -> pd.DataFrame:
    return logs_mod.compare_runs(named_server_logs)
