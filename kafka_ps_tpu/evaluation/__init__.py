"""Offline evaluation — the reference's evaluation/ notebooks as modules.

Ports (behavior, not code) of:
  * evaluation/plot-generation.ipynb        -> plots.plot_run
  * evaluation/evaluation-multipleDatasetsAtOnce.ipynb -> plots.plot_comparison
  * evaluation/python-ground-truth-algorithm.ipynb     -> ground_truth
All read the CSV log schema emitted by utils/csvlog.py (identical to the
reference's stdout-redirect schema, ServerAppRunner.java:81,
WorkerAppRunner.java:80).
"""

from kafka_ps_tpu.evaluation.logs import (  # noqa: F401
    RunSummary,
    load_server_log,
    load_worker_log,
    summarize_run,
)
