"""Offline ground-truth oracle — port of
evaluation/python-ground-truth-algorithm.ipynb (cells 4-7).

The reference trains an offline model (datawig SimpleImputer) on the full
training CSV and compares it to the streaming system via sklearn's
classification_report (README.md:221-233: weighted F1 0.47 on
fine-food-reviews).  Here the oracle is the same multinomial LR the
streaming system trains, fitted full-batch to convergence with the jit'd
loss/grad from models/logreg — answering "is the distributed system
learning correctly" with the identical hypothesis class.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from kafka_ps_tpu.models import logreg
from kafka_ps_tpu.models import metrics as metrics_mod
from kafka_ps_tpu.utils.config import ModelConfig


@dataclasses.dataclass(frozen=True)
class GroundTruth:
    theta: np.ndarray
    f1: float
    accuracy: float
    loss: float
    report: str          # sklearn classification_report text


# Trace counter for the PS101 regression test (tests/test_evaluation.py):
# the body runs only when XLA traces, so repeated same-shape calls must
# leave it unchanged.
_fit_traces = 0


@functools.partial(jax.jit, static_argnames=("cfg", "steps"))
def _fit(theta0, x, y, mask, learning_rate, cfg, steps):
    global _fit_traces
    _fit_traces += 1

    def step(theta, _):
        g, _loss = logreg.grad_loss(theta, x, y, mask, cfg)
        return theta - learning_rate * g, None

    theta, _ = jax.lax.scan(step, theta0, None, length=steps)
    return theta


def train_offline(train_x: np.ndarray, train_y: np.ndarray,
                  cfg: ModelConfig, *, steps: int = 500,
                  learning_rate: float = 0.5) -> np.ndarray:
    """Full-batch gradient descent to (near-)convergence.  The whole
    optimization is one lax.scan under jit — a single XLA program.

    The program is the module-level `_fit` (cached by jit per shape and
    per static (cfg, steps)): the original closed over the data with a
    fresh `@jax.jit def fit` per call, which re-traced and re-compiled
    the whole scan on EVERY oracle evaluation — pscheck PS101."""
    x = jnp.asarray(train_x, jnp.float32)
    y = jnp.asarray(train_y, jnp.int32)
    mask = jnp.ones((x.shape[0],), jnp.float32)
    theta = _fit(jnp.zeros((cfg.num_params,), jnp.float32), x, y, mask,
                 learning_rate, cfg, steps)
    return np.asarray(jax.block_until_ready(theta))


def classification_report_text(theta: np.ndarray, test_x: np.ndarray,
                               test_y: np.ndarray, cfg: ModelConfig) -> str:
    from sklearn.metrics import classification_report
    params = logreg.unflatten(jnp.asarray(theta), cfg)
    preds = np.asarray(jnp.argmax(logreg.logits(params, jnp.asarray(
        test_x, jnp.float32)), axis=-1))
    return classification_report(test_y, preds, zero_division=0)


def compute(train_x: np.ndarray, train_y: np.ndarray,
            test_x: np.ndarray, test_y: np.ndarray,
            cfg: ModelConfig | None = None, *, steps: int = 500,
            learning_rate: float = 0.5) -> GroundTruth:
    cfg = cfg or ModelConfig()
    theta = train_offline(train_x, train_y, cfg, steps=steps,
                          learning_rate=learning_rate)
    m = metrics_mod.evaluate(jnp.asarray(theta), jnp.asarray(test_x),
                             jnp.asarray(test_y), cfg=cfg)
    return GroundTruth(
        theta=theta,
        f1=float(m.f1),
        accuracy=float(m.accuracy),
        loss=float(m.loss),
        report=classification_report_text(theta, test_x, test_y, cfg),
    )
