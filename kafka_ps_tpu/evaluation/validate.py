"""Protocol conformance validation — the race/staleness auditor.

The reference's only protocol safety nets are runtime assertions inside
MessageTracker (clock-mismatch throws, MessageTracker.java:22-35 — its
substitute for a race detector, SURVEY §5).  This module audits a
finished run's logs against the consistency contract itself:

  * per-worker vector clocks advance by exactly +1 (no lost or
    duplicated iterations);
  * the cross-worker staleness bound holds at every moment:
    log-visible spread ≤ consistency_model + 1 (eventual −1:
    unbounded, no check);
  * the server's evaluation clock never regresses.

Derivation of the bound: the gate releases weights clock c to a worker
iff every gradient for iteration c − k − 1 has arrived, i.e. the
slowest tracker clock m ≥ c − k (MessageTracker.java:69-87,
parallel/tracker.py).  A tracker clock of m means that worker's last
*logged* iteration is m − 1 (it logs c while processing weights c,
before its gradient advances the tracker), so the spread between log
lines is ≤ (c) − (m − 1) ≤ k + 1.  Sequential is k = 0 → spread ≤ 1.
The TPU campaign in docs/EVALUATION.md measured 1 / 11 / 27 for
k = 0 / 10 / eventual — at the bound for both checked models.  Usage:

  python -m kafka_ps_tpu.evaluation validate \\
      --worker logs-worker.csv --server logs-server.csv -c 10
"""

from __future__ import annotations

import dataclasses
import warnings

import pandas as pd

from kafka_ps_tpu.utils.config import EVENTUAL


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    detail: str


#: (timestamp_ms, kind, worker) — kind is "evict" / "readmit" (worker
#: membership, ServerNode.remove_worker/readmit_worker) or "resume"
#: (checkpoint restore, worker = -1: every worker may re-log its last
#: clock once, the at-least-once redelivery of utils/checkpoint.py)
MembershipEvent = tuple[int, str, int]


def validate_worker_log(worker_df: pd.DataFrame,
                        consistency_model: int,
                        elastic: bool = False,
                        membership_events: list[MembershipEvent] | None = None
                        ) -> list[Violation]:
    """`elastic=True` validates a run with worker eviction/readmission
    (failure_policy=rebalance).

    With `membership_events` (the server's (timestamp_ms, "evict" |
    "readmit", worker) record — ServerNode.membership_events, or the
    logs-events.csv a split-mode server writes), the full contract is
    re-derived PER MEMBERSHIP EPOCH instead of being skipped:

      * per-worker clock step is exactly +1, except across that
        worker's own readmission, where any value is legal (rejoin is
        at the min ACTIVE clock, tracker.reactivate_worker — above,
        equal to, or below the worker's own frozen clock);
      * the k+1 staleness bound holds within every epoch over the
        workers active in that epoch (an eviction removes the dead
        worker's frozen clock from the spread; a readmission re-adds
        the worker at a gate-legal clock).

    Without events (legacy elastic call), only per-worker clock
    monotonicity is checked — membership changes void the static bound
    and nothing records where they happened."""
    out: list[Violation] = []
    if membership_events or (elastic and membership_events is not None):
        # membership events existing IS the elastic signal: a run whose
        # record carries evict/readmit/resume must be audited
        # epoch-aware, whatever the caller passed for `elastic` — the
        # static +1/spread contract is provably void across any of
        # those events (a resume rewinds clocks to the last periodic
        # checkpoint; an eviction freezes one).  `elastic` only matters
        # when the caller supplies NO events: True relaxes the static
        # +1 check to monotonicity (legacy eventless elastic runs).
        return _validate_elastic_epochs(worker_df, consistency_model,
                                        membership_events or [])
    # 1. per-worker clocks
    for w, g in worker_df.groupby("partition"):
        clocks = g["vectorClock"].tolist()
        for prev, cur in zip(clocks, clocks[1:]):
            bad = (cur < prev) if elastic else (cur != prev + 1)
            if bad:
                expect = "no regression" if elastic else f"{prev + 1}"
                out.append(Violation(
                    "clock-step",
                    f"worker {int(w)}: clock {prev} -> {cur} "
                    f"(expected {expect})"))
    # 2. staleness bound, evaluated at every log event in arrival order
    # (stable sort: ties keep file order — log files are written in
    # arrival order and millisecond timestamps collide)
    if consistency_model != EVENTUAL and not elastic:
        bound = consistency_model + 1   # see module docstring
        latest: dict[int, int] = {}
        ordered = worker_df.sort_values("timestamp", kind="stable")
        for _, row in ordered.iterrows():
            latest[int(row["partition"])] = int(row["vectorClock"])
            if len(latest) > 1:
                spread = max(latest.values()) - min(latest.values())
                if spread > bound:
                    out.append(Violation(
                        "staleness-bound",
                        f"spread {spread} > bound {bound} at "
                        f"timestamp {int(row['timestamp'])} "
                        f"(clocks {dict(sorted(latest.items()))})"))
    return out


# Membership-event timestamps come from the server's host clock; log
# rows from worker host clocks.  Ordering across that boundary is only
# trustworthy up to NTP-grade skew — interleavings wider than this are
# reported as suspicious rather than silently re-segmented.
CLOCK_SKEW_WARN_MS = 10_000


def _validate_elastic_epochs(worker_df: pd.DataFrame,
                             consistency_model: int,
                             membership_events: list[MembershipEvent]
                             ) -> list[Violation]:
    """Merge log rows and membership events into one timeline and audit
    each epoch (the interval between two membership changes) against
    the same contract a static run gets.  Events order before log rows
    on timestamp ties: the server records the change before the
    affected traffic flows.

    Cross-host clock skew (ADVICE r3): in split mode the events carry
    the SERVER host's clock and the rows each WORKER host's clock, so
    the merged order is only approximate.  Readmissions are therefore
    applied by PROTOCOL STATE, not wall clock: the rejoin row is the
    first row of an inactive worker that either follows its readmit
    event on the timeline or breaks its frozen +1 chain while an
    unconsumed readmit event for it exists nearby (within
    CLOCK_SKEW_WARN_MS) — a row skew-sorted before its own readmission
    is then still classified as the rejoin, counted into the spread,
    and the skew reported via `warnings`.  Evictions still segment on
    the merged timeline (a pre-evict row is indistinguishable from a
    legal last-gasp +1 continuation by content alone); last-gasp rows
    arriving implausibly long after the eviction are warned about."""
    out: list[Violation] = []
    bound = consistency_model + 1
    check_bound = consistency_model != EVENTUAL

    rows = worker_df.sort_values("timestamp", kind="stable")
    events_sorted = sorted(membership_events, key=lambda e: e[0])
    timeline: list[tuple[int, int, object]] = []   # (ts, order, item)
    for ev in events_sorted:
        timeline.append((int(ev[0]), 0, ev))
    for _, row in rows.iterrows():
        timeline.append((int(row["timestamp"]), 1,
                         (int(row["partition"]), int(row["vectorClock"]))))
    timeline.sort(key=lambda t: (t[0], t[1]))

    active = {int(w) for w in worker_df["partition"].unique()}
    active |= {int(w) for _, _, w in membership_events}
    latest: dict[int, int] = {}         # last logged clock per worker
    frozen: dict[int, int] = {}         # evicted workers' +1 chains
    evicted_at: dict[int, int] = {}     # worker -> evict event ts
    # per worker: timestamps of readmit events not yet consumed — either
    # reached on the timeline (-> pending) or claimed EARLY by a row
    # whose host clock sorts it before its own readmit event
    readmit_times: dict[int, list[int]] = {}
    evict_times: dict[int, list[int]] = {}
    for ts_, kind_, w_ in events_sorted:
        if kind_ == "readmit":
            readmit_times.setdefault(int(w_), []).append(int(ts_))
        else:
            evict_times.setdefault(int(w_), []).append(int(ts_))
    pending_readmit: dict[int, int] = {}
    early_claims: dict[int, int] = {}

    # Crash-truncation exemption: split-mode workers log through a
    # deferred sink (utils/asynclog.py), so a SIGKILL'd process loses
    # its final pending rows — its LOGGED clock then understates its
    # true protocol clock by however far it ran before dying, and the
    # apparent spread inflates without any real staleness.  In an epoch
    # that ends in a crash (marked by the following "resume" event), a
    # worker whose log has gone silent for the REST of that epoch
    # therefore stops constraining the spread from its last row onward
    # ("stalled" and "rows lost to the crash" are indistinguishable
    # from the log; bias to no false positives, like the rest of this
    # auditor).  Healthy epochs — no resume ahead — are unaffected.
    resume_ts = sorted(int(ts_) for ts_, kind_, _ in events_sorted
                       if kind_ == "resume")
    last_row_ts: dict[tuple[int, int], int] = {}
    for _, row in rows.iterrows():
        rts = int(row["timestamp"])
        epoch = sum(1 for r in resume_ts if r <= rts)
        key = (int(row["partition"]), epoch)
        last_row_ts[key] = max(last_row_ts.get(key, rts), rts)

    # workers already warned about per (worker, epoch): one exemption
    # warning per blind spot, not one per spread check
    warned_truncated: set[tuple[int, int]] = set()

    def spread_workers(ts: int) -> dict[int, int]:
        nxt = next((r for r in resume_ts if r > ts), None)
        if nxt is None:
            return latest
        epoch = sum(1 for r in resume_ts if r <= ts)
        kept = {w: c for w, c in latest.items()
                if last_row_ts.get((w, epoch), -1) >= ts}
        for w in latest:
            if w not in kept and (w, epoch) not in warned_truncated:
                warned_truncated.add((w, epoch))
                warnings.warn(
                    f"staleness audit: worker {w} exempted from the "
                    f"spread check from timestamp {ts} to the end of "
                    f"crash epoch {epoch} (its log went silent before "
                    "the crash — rows lost to the truncated deferred "
                    "sink and a genuine stall are indistinguishable, "
                    "so its clock no longer constrains the spread)")
        return kept

    def spread_check(ts: int) -> None:
        clocks = spread_workers(ts)
        if check_bound and len(clocks) > 1:
            spread = max(clocks.values()) - min(clocks.values())
            if spread > bound:
                out.append(Violation(
                    "staleness-bound",
                    f"spread {spread} > bound {bound} at timestamp "
                    f"{ts} (clocks {dict(sorted(clocks.items()))})"))

    # workers whose NEXT row follows a checkpoint resume: the crash
    # killed the in-flight messages and the restored server re-sends
    # each worker's CHECKPOINTED clock (at-least-once redelivery,
    # utils/checkpoint.py restore) — which a crash resume rewinds to
    # the last periodic save, below rows the surviving log already
    # holds.  That one row per worker may carry ANY clock, and the
    # pre-crash `latest` clocks are dead state (comparing rewound rows
    # against them would fake a staleness spread), so they leave the
    # spread until each worker's redelivered row re-enters it.
    resumed: set[int] = set()

    for ts, kind_order, item in timeline:
        if kind_order == 0:             # membership event
            _, kind, w = item
            w = int(w)
            if kind == "resume":
                # a crash resume rewinds the SERVER'S state — including
                # membership — to the last periodic save, which the
                # append-only events log cannot see.  All pre-resume
                # membership bookkeeping is void: a worker evicted
                # after that save is revived by the restore (its
                # checkpointed active flag) and legally logs again.
                # Bias to no false positives: treat every known worker
                # as active with one any-clock redelivery; post-resume
                # evict/readmit events re-segment from here.
                known = active | set(latest) | set(frozen)
                resumed |= known
                active |= known
                frozen.clear()
                latest.clear()
                pending_readmit.clear()
                early_claims.clear()
                for w_, times in readmit_times.items():
                    readmit_times[w_] = [t for t in times if t > ts]
                continue
            if kind == "evict":
                active.discard(w)
                if w in latest:         # frozen clock leaves the spread
                    frozen[w] = latest.pop(w)
                evicted_at[w] = ts
                # a readmission the worker never logged under is voided
                # by its re-eviction — without this, its next in-flight
                # row would be misread as a rejoin and its frozen clock
                # would re-enter the spread permanently
                for _ in range(pending_readmit.get(w, 0)):
                    if readmit_times.get(w):
                        readmit_times[w].pop(0)
                pending_readmit[w] = 0
            elif early_claims.get(w, 0) > 0:
                early_claims[w] -= 1    # a skew-sorted row already took it
            else:
                pending_readmit[w] = pending_readmit.get(w, 0) + 1
            continue
        w, clock = item
        if w not in active:
            prev = frozen.get(w)
            rejoin = False
            if pending_readmit.get(w, 0) > 0:
                pending_readmit[w] -= 1
                # guarded (ADVICE r4): a resume clears early_claims but
                # the early-claimed readmit event may still be ahead on
                # the timeline; when it re-increments pending_readmit its
                # timestamp was already popped, so the list can be empty
                # here — report via the normal paths, don't crash
                if readmit_times.get(w):
                    readmit_times[w].pop(0)
                rejoin = True
            elif (readmit_times.get(w)
                    # a truly broken +1 chain — `prev is None` is NOT a
                    # break: a worker evicted before its first row sends
                    # a perfectly legal in-flight first row, which must
                    # stay a last-gasp (the pending path classifies its
                    # real rejoin correctly)
                    and prev is not None and clock != prev + 1
                    and readmit_times[w][0] - ts <= CLOCK_SKEW_WARN_MS
                    # a claim must not reach ACROSS an evict for this
                    # worker: in a corrupted event log (e.g. double
                    # evict) that would swallow the readmit and push the
                    # worker's real rejoin rows out of the spread forever
                    and not any(ts < e <= readmit_times[w][0]
                                for e in evict_times.get(w, ()))):
                # protocol state says rejoin even though this row's host
                # clock sorts it before its own readmit event
                readmit_times[w].pop(0)
                early_claims[w] = early_claims.get(w, 0) + 1
                rejoin = True
                warnings.warn(
                    f"worker {w}: rejoin row at {ts} precedes its "
                    "readmit event — cross-host clock skew; ordered by "
                    "protocol state instead")
            if rejoin:
                active.add(w)
                frozen.pop(w, None)
                latest[w] = clock       # no +1 check on the rejoin row
                spread_check(ts)
            else:
                # last-gasp row in flight at the eviction: continues the
                # frozen chain but stays out of the spread
                if prev is not None and clock != prev + 1:
                    out.append(Violation(
                        "clock-step",
                        f"evicted worker {w}: clock {prev} -> {clock} "
                        f"(expected {prev + 1}) at timestamp {ts}"))
                frozen[w] = clock
                if ts - evicted_at.get(w, ts) > CLOCK_SKEW_WARN_MS:
                    warnings.warn(
                        f"worker {w}: row at {ts} arrived "
                        f"{ts - evicted_at[w]}ms after its eviction — "
                        "possible clock skew mis-segmenting this epoch "
                        "(epoch validation assumes NTP-synced hosts)")
            continue
        prev = latest.get(w)
        if w in resumed:
            # any clock is legal on the one redelivered row: a crash
            # resume restarts from the last PERIODIC save, so the clock
            # can regress below rows the surviving log already holds
            # (and then legitimately re-walk them, +1 from here)
            resumed.discard(w)
        elif prev is not None and clock != prev + 1:
            out.append(Violation(
                "clock-step",
                f"worker {w}: clock {prev} -> {clock} "
                f"(expected {prev + 1}) at timestamp {ts}"))
        latest[w] = clock
        spread_check(ts)
    return out


def validate_server_log(server_df: pd.DataFrame,
                        membership_events: list[MembershipEvent] | None = None
                        ) -> list[Violation]:
    """The server's eval clock never regresses — except across a
    checkpoint resume (a "resume" membership event), where a crash
    restart legitimately rewinds to the last periodic save and re-walks
    the lost iterations."""
    out: list[Violation] = []
    resume_ts = sorted(ts for ts, kind, _ in (membership_events or [])
                       if kind == "resume")
    ordered = server_df.sort_values("timestamp", kind="stable")
    prev_clock = prev_ts = None
    for ts, cur in zip(ordered["timestamp"].tolist(),
                       ordered["vectorClock"].tolist()):
        ts, cur = int(ts), int(cur)
        if prev_clock is not None and cur < prev_clock:
            crossed = any(prev_ts <= r <= ts for r in resume_ts)
            if crossed:
                resume_ts = [r for r in resume_ts
                             if not (prev_ts <= r <= ts)]
            else:
                out.append(Violation(
                    "server-clock-regression",
                    f"server eval clock {prev_clock} -> {cur}"))
        prev_clock, prev_ts = cur, ts
    return out


def validate_run(worker_df: pd.DataFrame | None,
                 server_df: pd.DataFrame | None,
                 consistency_model: int,
                 elastic: bool = False,
                 membership_events: list[MembershipEvent] | None = None
                 ) -> list[Violation]:
    out: list[Violation] = []
    if worker_df is not None and len(worker_df):
        out += validate_worker_log(worker_df, consistency_model,
                                   elastic=elastic,
                                   membership_events=membership_events)
    if server_df is not None and len(server_df):
        out += validate_server_log(server_df,
                                   membership_events=membership_events)
    return out


def load_membership_events(path: str) -> list[MembershipEvent]:
    """Parse a logs-events.csv (`timestamp;event;partition`, written
    incrementally by ServerNode.record_membership_event through the
    events CsvLogSink the CLIs install — csvlog.EVENTS_HEADER)."""
    df = pd.read_csv(path, sep=";")
    return [(int(r["timestamp"]), str(r["event"]), int(r["partition"]))
            for _, r in df.iterrows()]
