"""Protocol conformance validation — the race/staleness auditor.

The reference's only protocol safety nets are runtime assertions inside
MessageTracker (clock-mismatch throws, MessageTracker.java:22-35 — its
substitute for a race detector, SURVEY §5).  This module audits a
finished run's logs against the consistency contract itself:

  * per-worker vector clocks advance by exactly +1 (no lost or
    duplicated iterations);
  * the cross-worker staleness bound holds at every moment:
    log-visible spread ≤ consistency_model + 1 (eventual −1:
    unbounded, no check);
  * the server's evaluation clock never regresses.

Derivation of the bound: the gate releases weights clock c to a worker
iff every gradient for iteration c − k − 1 has arrived, i.e. the
slowest tracker clock m ≥ c − k (MessageTracker.java:69-87,
parallel/tracker.py).  A tracker clock of m means that worker's last
*logged* iteration is m − 1 (it logs c while processing weights c,
before its gradient advances the tracker), so the spread between log
lines is ≤ (c) − (m − 1) ≤ k + 1.  Sequential is k = 0 → spread ≤ 1.
The TPU campaign in docs/EVALUATION.md measured 1 / 11 / 27 for
k = 0 / 10 / eventual — at the bound for both checked models.  Usage:

  python -m kafka_ps_tpu.evaluation validate \\
      --worker logs-worker.csv --server logs-server.csv -c 10
"""

from __future__ import annotations

import dataclasses

import pandas as pd

from kafka_ps_tpu.utils.config import EVENTUAL


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    detail: str


def validate_worker_log(worker_df: pd.DataFrame,
                        consistency_model: int,
                        elastic: bool = False) -> list[Violation]:
    """`elastic=True` validates a run with worker eviction/readmission
    (failure_policy=rebalance): membership changes void the static
    staleness bound (survivors legitimately run past an evicted
    worker's frozen clock), so only per-worker clock monotonicity
    (never a regression) is checked.  An *equal* clock across a rejoin
    is legitimate: readmission joins at the min ACTIVE clock
    (tracker.reactivate_worker), which equals the evicted worker's own
    last logged clock when the survivors have not advanced yet."""
    out: list[Violation] = []
    # 1. per-worker clocks
    for w, g in worker_df.groupby("partition"):
        clocks = g["vectorClock"].tolist()
        for prev, cur in zip(clocks, clocks[1:]):
            bad = (cur < prev) if elastic else (cur != prev + 1)
            if bad:
                expect = "no regression" if elastic else f"{prev + 1}"
                out.append(Violation(
                    "clock-step",
                    f"worker {int(w)}: clock {prev} -> {cur} "
                    f"(expected {expect})"))
    # 2. staleness bound, evaluated at every log event in arrival order
    # (stable sort: ties keep file order — log files are written in
    # arrival order and millisecond timestamps collide)
    if consistency_model != EVENTUAL and not elastic:
        bound = consistency_model + 1   # see module docstring
        latest: dict[int, int] = {}
        ordered = worker_df.sort_values("timestamp", kind="stable")
        for _, row in ordered.iterrows():
            latest[int(row["partition"])] = int(row["vectorClock"])
            if len(latest) > 1:
                spread = max(latest.values()) - min(latest.values())
                if spread > bound:
                    out.append(Violation(
                        "staleness-bound",
                        f"spread {spread} > bound {bound} at "
                        f"timestamp {int(row['timestamp'])} "
                        f"(clocks {dict(sorted(latest.items()))})"))
    return out


def validate_server_log(server_df: pd.DataFrame) -> list[Violation]:
    out: list[Violation] = []
    clocks = server_df["vectorClock"].tolist()
    for prev, cur in zip(clocks, clocks[1:]):
        if cur < prev:
            out.append(Violation(
                "server-clock-regression",
                f"server eval clock {prev} -> {cur}"))
    return out


def validate_run(worker_df: pd.DataFrame | None,
                 server_df: pd.DataFrame | None,
                 consistency_model: int,
                 elastic: bool = False) -> list[Violation]:
    out: list[Violation] = []
    if worker_df is not None and len(worker_df):
        out += validate_worker_log(worker_df, consistency_model,
                                   elastic=elastic)
    if server_df is not None and len(server_df):
        out += validate_server_log(server_df)
    return out
