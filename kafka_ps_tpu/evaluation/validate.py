"""Protocol conformance validation — the race/staleness auditor.

The reference's only protocol safety nets are runtime assertions inside
MessageTracker (clock-mismatch throws, MessageTracker.java:22-35 — its
substitute for a race detector, SURVEY §5).  This module audits a
finished run's logs against the consistency contract itself:

  * per-worker vector clocks advance by exactly +1 (no lost or
    duplicated iterations);
  * the cross-worker staleness bound holds at every moment:
    log-visible spread ≤ consistency_model + 1 (eventual −1:
    unbounded, no check);
  * the server's evaluation clock never regresses.

Derivation of the bound: the gate releases weights clock c to a worker
iff every gradient for iteration c − k − 1 has arrived, i.e. the
slowest tracker clock m ≥ c − k (MessageTracker.java:69-87,
parallel/tracker.py).  A tracker clock of m means that worker's last
*logged* iteration is m − 1 (it logs c while processing weights c,
before its gradient advances the tracker), so the spread between log
lines is ≤ (c) − (m − 1) ≤ k + 1.  Sequential is k = 0 → spread ≤ 1.
The TPU campaign in docs/EVALUATION.md measured 1 / 11 / 27 for
k = 0 / 10 / eventual — at the bound for both checked models.  Usage:

  python -m kafka_ps_tpu.evaluation validate \\
      --worker logs-worker.csv --server logs-server.csv -c 10
"""

from __future__ import annotations

import dataclasses

import pandas as pd

from kafka_ps_tpu.utils.config import EVENTUAL


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    detail: str


MembershipEvent = tuple[int, str, int]    # (timestamp_ms, kind, worker)


def validate_worker_log(worker_df: pd.DataFrame,
                        consistency_model: int,
                        elastic: bool = False,
                        membership_events: list[MembershipEvent] | None = None
                        ) -> list[Violation]:
    """`elastic=True` validates a run with worker eviction/readmission
    (failure_policy=rebalance).

    With `membership_events` (the server's (timestamp_ms, "evict" |
    "readmit", worker) record — ServerNode.membership_events, or the
    logs-events.csv a split-mode server writes), the full contract is
    re-derived PER MEMBERSHIP EPOCH instead of being skipped:

      * per-worker clock step is exactly +1, except across that
        worker's own readmission, where any value is legal (rejoin is
        at the min ACTIVE clock, tracker.reactivate_worker — above,
        equal to, or below the worker's own frozen clock);
      * the k+1 staleness bound holds within every epoch over the
        workers active in that epoch (an eviction removes the dead
        worker's frozen clock from the spread; a readmission re-adds
        the worker at a gate-legal clock).

    Without events (legacy elastic call), only per-worker clock
    monotonicity is checked — membership changes void the static bound
    and nothing records where they happened."""
    out: list[Violation] = []
    if elastic and membership_events is not None:
        return _validate_elastic_epochs(worker_df, consistency_model,
                                        membership_events)
    # 1. per-worker clocks
    for w, g in worker_df.groupby("partition"):
        clocks = g["vectorClock"].tolist()
        for prev, cur in zip(clocks, clocks[1:]):
            bad = (cur < prev) if elastic else (cur != prev + 1)
            if bad:
                expect = "no regression" if elastic else f"{prev + 1}"
                out.append(Violation(
                    "clock-step",
                    f"worker {int(w)}: clock {prev} -> {cur} "
                    f"(expected {expect})"))
    # 2. staleness bound, evaluated at every log event in arrival order
    # (stable sort: ties keep file order — log files are written in
    # arrival order and millisecond timestamps collide)
    if consistency_model != EVENTUAL and not elastic:
        bound = consistency_model + 1   # see module docstring
        latest: dict[int, int] = {}
        ordered = worker_df.sort_values("timestamp", kind="stable")
        for _, row in ordered.iterrows():
            latest[int(row["partition"])] = int(row["vectorClock"])
            if len(latest) > 1:
                spread = max(latest.values()) - min(latest.values())
                if spread > bound:
                    out.append(Violation(
                        "staleness-bound",
                        f"spread {spread} > bound {bound} at "
                        f"timestamp {int(row['timestamp'])} "
                        f"(clocks {dict(sorted(latest.items()))})"))
    return out


def _validate_elastic_epochs(worker_df: pd.DataFrame,
                             consistency_model: int,
                             membership_events: list[MembershipEvent]
                             ) -> list[Violation]:
    """Merge log rows and membership events into one timeline and audit
    each epoch (the interval between two membership changes) against
    the same contract a static run gets.  Events order before log rows
    on timestamp ties: the server records the change before the
    affected traffic flows."""
    out: list[Violation] = []
    bound = consistency_model + 1
    check_bound = consistency_model != EVENTUAL

    rows = worker_df.sort_values("timestamp", kind="stable")
    timeline: list[tuple[int, int, object]] = []   # (ts, order, item)
    for ev in sorted(membership_events, key=lambda e: e[0]):
        timeline.append((int(ev[0]), 0, ev))
    for _, row in rows.iterrows():
        timeline.append((int(row["timestamp"]), 1,
                         (int(row["partition"]), int(row["vectorClock"]))))
    timeline.sort(key=lambda t: (t[0], t[1]))

    active = {int(w) for w in worker_df["partition"].unique()}
    active |= {int(w) for _, _, w in membership_events}
    latest: dict[int, int] = {}         # last logged clock per worker
    # workers whose NEXT log row follows their own readmission: the +1
    # step check is suspended for exactly that one row
    rejoined: set[int] = set()

    for ts, kind_order, item in timeline:
        if kind_order == 0:             # membership event
            _, kind, w = item
            w = int(w)
            if kind == "evict":
                active.discard(w)
                latest.pop(w, None)     # frozen clock leaves the spread
            else:                       # readmit
                active.add(w)
                rejoined.add(w)
            continue
        w, clock = item
        prev = latest.get(w)
        if w in rejoined:
            rejoined.discard(w)
        elif prev is not None and clock != prev + 1:
            out.append(Violation(
                "clock-step",
                f"worker {w}: clock {prev} -> {clock} "
                f"(expected {prev + 1}) at timestamp {ts}"))
        if w not in active:
            # last-gasp row from an evicted worker (in flight at the
            # eviction): legal, but its frozen clock must not rejoin
            # the spread
            continue
        latest[w] = clock
        if check_bound and len(latest) > 1:
            spread = max(latest.values()) - min(latest.values())
            if spread > bound:
                out.append(Violation(
                    "staleness-bound",
                    f"spread {spread} > bound {bound} at timestamp "
                    f"{ts} (clocks {dict(sorted(latest.items()))})"))
    return out


def validate_server_log(server_df: pd.DataFrame) -> list[Violation]:
    out: list[Violation] = []
    clocks = server_df["vectorClock"].tolist()
    for prev, cur in zip(clocks, clocks[1:]):
        if cur < prev:
            out.append(Violation(
                "server-clock-regression",
                f"server eval clock {prev} -> {cur}"))
    return out


def validate_run(worker_df: pd.DataFrame | None,
                 server_df: pd.DataFrame | None,
                 consistency_model: int,
                 elastic: bool = False,
                 membership_events: list[MembershipEvent] | None = None
                 ) -> list[Violation]:
    out: list[Violation] = []
    if worker_df is not None and len(worker_df):
        out += validate_worker_log(worker_df, consistency_model,
                                   elastic=elastic,
                                   membership_events=membership_events)
    if server_df is not None and len(server_df):
        out += validate_server_log(server_df)
    return out


def load_membership_events(path: str) -> list[MembershipEvent]:
    """Parse a logs-events.csv (`timestamp;event;partition`, written by
    cli/socket_mode.write_events_log)."""
    df = pd.read_csv(path, sep=";")
    return [(int(r["timestamp"]), str(r["event"]), int(r["partition"]))
            for _, r in df.iterrows()]
