"""Aggregator-relay entry point (docs/AGGREGATION.md) — no analogue
in the reference, whose broker fans every worker partition straight
into the one server consumer; this role is what lets hundreds of
workers fit behind one server gate by pre-reducing per host.

    python -m kafka_ps_tpu.cli.agg_runner --connect hostA:8477 \\
        --listen 8478 --agg-id 0 --worker_ids 0,1,2,3

Member worker processes then dial THIS process with
`worker_runner --aggregate host:8478`.
"""

from __future__ import annotations

import argparse

from kafka_ps_tpu.cli import run as run_mod


def build_parser() -> argparse.ArgumentParser:
    """The aggregator-role flag surface (also validated against the
    deployment manifests in tests/test_deploy.py)."""
    parser = run_mod.build_parser(include_server_flags=False,
                                  include_worker_flags=False,
                                  prog="AggregatorRunner")
    parser.add_argument(
        "--connect", required=True, metavar="HOST:PORT",
        help="the upstream server (or shard-0 server) this relay "
             "forwards composites to; the relay HELLOs there as an "
             "aggregator for every id in --worker_ids")
    parser.add_argument(
        "--listen", type=int, default=0, metavar="PORT",
        help="downstream port the member worker processes dial "
             "(--aggregate host:PORT); 0 = ephemeral, printed to "
             "stderr")
    parser.add_argument(
        "--agg-id", dest="agg_id", type=int, default=0, metavar="I",
        help="this relay's id — stamps composites, flight events and "
             "metrics so a multi-host postmortem can tell relays apart")
    parser.add_argument("--worker_ids", default="0",
                        help="comma-separated logical worker ids this "
                             "relay aggregates for (its member set)")
    parser.add_argument(
        "--summed", action="store_true",
        help="pre-reduce single-clock flushes into ONE delta per "
             "composite (exact by linearity under BSP, NOT bitwise-"
             "pinned to the direct path; default stacked mode is)")
    parser.add_argument(
        "--flush-interval", dest="flush_interval", type=float,
        default=0.002, metavar="SECONDS",
        help="max quiet time before a partial round flushes upstream "
             "(a full round — all members pending — flushes at once)")
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from kafka_ps_tpu.cli import socket_mode
    return socket_mode.run_aggregator(args)


if __name__ == "__main__":
    raise SystemExit(main())
