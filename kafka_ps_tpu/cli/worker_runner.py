"""Worker-role entry point — flag parity with the reference's
WorkerAppRunner (WorkerAppRunner.java:13-96: -test -min -max -bc
-v -h -r -l, same defaults).

Hosts the complete system with the server-side knobs at their reference
defaults (consistency 0, producer 200 ms/event) — see
cli/server_runner.py for why the roles are colocated on TPU.
"""

from __future__ import annotations

import argparse

from kafka_ps_tpu.cli import run as run_mod


def build_parser() -> argparse.ArgumentParser:
    """The worker-role flag surface (also validated against the
    deployment manifests in tests/test_deploy.py)."""
    parser = run_mod.build_parser(include_server_flags=False,
                                  include_worker_flags=True,
                                  prog="WorkerAppRunner")
    parser.add_argument(
        "--connect", default=None, metavar="HOST:PORT[,HOST:PORT...]",
        help="split deployment: host ONLY the logical workers in "
             "--worker_ids against a remote --listen server "
             "(cli/socket_mode.py) — the reference's worker-JVM role "
             "(run.sh:10-13).  A comma-separated list connects to a "
             "--shards N server fleet, one address per shard in "
             "shard-id order (docs/SHARDING.md)")
    parser.add_argument("--worker_ids", default="0",
                        help="--connect: comma-separated logical worker "
                             "ids this process hosts")
    parser.add_argument(
        "--aggregate", default=None, metavar="HOST:PORT",
        help="dial a per-host aggregator relay instead of the server "
             "(cli/agg_runner.py, docs/AGGREGATION.md): deltas are "
             "pre-reduced per host before the server sees them, and "
             "compression is delegated to the relay")
    parser.add_argument(
        "--ready-rows", dest="ready_rows", type=int, default=1,
        metavar="N",
        help="rows a worker's buffer must hold before it announces "
             "READY (default 1) — deterministic-ingestion gating for "
             "A/B comparisons (scripts/tier1.sh --agg)")
    parser.add_argument("--state_every", type=float, default=1.0,
                        metavar="SECONDS",
                        help="--connect + --checkpoint: cadence of the "
                             "durable buffer-state snapshots (the "
                             "changelog analogue, WorkerApp.java:40-42) "
                             "— a SIGKILL'd process loses at most one "
                             "interval of rows")
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    # server-side defaults (ServerAppRunner.java:59-63, BaseKafkaApp.java:35)
    args = argparse.Namespace(training_data_file_path="./data/train.csv",
                              consistency_model=0,
                              producer_time_per_event=200, **vars(args))
    if args.connect is not None and args.aggregate is not None:
        raise SystemExit("--connect and --aggregate are exclusive: a "
                         "worker dials its server OR its host's "
                         "aggregator relay, never both")
    if args.connect is not None or args.aggregate is not None:
        if getattr(args, "durable_log", None):
            # same gate as server_runner: the split deployment's
            # durability is --checkpoint + worker-local state files
            raise SystemExit(
                "--durable-log applies to the in-process fabric; in "
                "--connect split mode use --checkpoint instead")
        from kafka_ps_tpu.cli import socket_mode
        return socket_mode.run_worker(args)
    return run_mod.run_with_args(args)


if __name__ == "__main__":
    raise SystemExit(main())
