"""Split server/worker deployment over the socket transport — the
reference's ACTUAL process topology (one server JVM + worker JVMs
coupled through the broker, run.sh:10-18, kubernetes/*.yaml) for the
async consistency models.

    # host A — aggregator + consistency gate + stream producer
    python -m kafka_ps_tpu.cli.server_runner --listen 8477 \
        -c 10 -training train.csv -test test.csv --max_iterations 400 -l

    # host B (and C, ...) — the workers named by --worker_ids
    python -m kafka_ps_tpu.cli.worker_runner --connect hostA:8477 \
        --worker_ids 0,1,2,3 -test test.csv -l

WEIGHTS / GRADIENTS / INPUT_DATA cross the wire as binary serde frames
(runtime/net.py, runtime/serde.py) — ~24 KB per 6150-float model
message vs the reference's ~120 KB JSON.  The fused/BSP path scales via
jax.distributed instead (deploy/README.md); this mode exists so bounded
delay and eventual consistency have a real multi-host story too.
"""

from __future__ import annotations

import os
import queue
import sys
import threading
import time


from kafka_ps_tpu.analysis.lockgraph import OrderedLock
from kafka_ps_tpu.runtime import fabric as fabric_mod
from kafka_ps_tpu.runtime import net

def _make_cfg(args):
    from kafka_ps_tpu.cli.run import apply_platform_env
    from kafka_ps_tpu.utils.config import (BufferConfig, ModelConfig,
                                           PSConfig, StreamConfig,
                                           TierConfig)
    apply_platform_env()
    if getattr(args, "eval_every", 1) < 1:
        raise SystemExit("--eval_every must be >= 1")
    if getattr(args, "tier_warm_bytes", 0) \
            and not getattr(args, "durable_log", None):
        raise SystemExit(
            "--tier-warm-bytes demotes pages to commit-log records; "
            "run with --durable-log DIR so the cold partition has a "
            "home (docs/TIERING.md)")
    return PSConfig(
        num_workers=args.num_workers,
        consistency_model=getattr(args, "consistency_model", 0),
        task=args.task,
        model=ModelConfig(num_features=args.num_features,
                          num_classes=args.num_classes,
                          num_max_iter=args.local_iterations,
                          local_learning_rate=args.local_learning_rate,
                          hidden_dim=args.hidden_dim),
        buffer=BufferConfig(
            min_size=getattr(args, "min_buffer_size", 128),
            max_size=getattr(args, "max_buffer_size", 1024),
            coefficient=getattr(args, "buffer_size_coefficient", 0.3)),
        stream=StreamConfig(time_per_event_ms=getattr(
            args, "producer_time_per_event", 200)),
        eval_every=getattr(args, "eval_every", 1),
        eval_async=getattr(args, "eval_async", True),
        use_pallas=getattr(args, "pallas", False),
        # the wire protocol has no gang-notice frame (runtime/serde.py),
        # and a notice crossing a socket could not promise anything
        # about remote queue contents anyway — split mode stays
        # per-message
        use_gang=False,
        compress=getattr(args, "compress", "none") or "none",
        tier=TierConfig(
            hot_bytes=getattr(args, "tier_hot_bytes", 0),
            warm_bytes=getattr(args, "tier_warm_bytes", 0),
            page_params=getattr(args, "tier_page_params", 1024)),
    )


def _codec_spec(args):
    """Validate and parse --compress (host-side only, no jax import)."""
    from kafka_ps_tpu.compress import wire as cwire
    try:
        return cwire.parse_codec(getattr(args, "compress", "none") or "none")
    except ValueError as e:
        raise SystemExit(f"--compress: {e}") from None


def _attach_tier_store(server, cfg, key_range, cold_dir, telemetry):
    """Attach tiered hot/warm/cold residency per cfg.tier
    (kafka_ps_tpu/store/, docs/TIERING.md); no-op (None) when both caps
    are 0.  Called BEFORE the checkpoint restore so the restore can
    re-apply recorded residency.  Caller owns close() at teardown —
    after the final checkpoint save, which may still fault cold
    pages."""
    if not cfg.tier.enabled:
        return None
    import numpy as np

    from kafka_ps_tpu.store import ColdStore, TieredParamStore
    t = cfg.tier
    cold = ColdStore.open(cold_dir) if cold_dir is not None else None
    store = TieredParamStore(
        np.asarray(server.theta), key_range,
        hot_bytes=t.hot_bytes, warm_bytes=t.warm_bytes,
        page_params=t.page_params, cold=cold, telemetry=telemetry,
        rebalance_interval_s=t.rebalance_interval_s)
    server.attach_param_store(store)
    store.start_policy_thread()
    caps = {k: v for k, v in (("hot", t.hot_bytes),
                              ("warm", t.warm_bytes)) if v}
    print(f"tiered residency: caps {caps}, "
          f"{store.num_pages} pages of {t.page_params} keys",
          file=sys.stderr, flush=True)
    return store


def _make_telemetry(args):
    """Per-process observability handles (docs/OBSERVABILITY.md): each
    split-mode process owns its own Tracer (pid-stamped events — the
    merge CLI stitches the per-process dumps) and metrics registry."""
    from kafka_ps_tpu.telemetry import maybe_telemetry
    tracer = None
    if getattr(args, "trace", None):
        from kafka_ps_tpu.utils.trace import Tracer
        tracer = Tracer()
    # /varz serves this same registry, so a requested health plane
    # arms metrics even without a --metrics-file dump target
    telemetry = maybe_telemetry(
        tracer,
        want_metrics=bool(getattr(args, "metrics_file", None))
        or getattr(args, "health_port", None) is not None
        # the SLO plane judges registry families, so arming it arms them
        or getattr(args, "slo_serving_p99_ms", None) is not None
        or getattr(args, "slo_freshness_ms", None) is not None
        # model-health diagnostics are metric families first
        or getattr(args, "model_health", False))
    if getattr(args, "metrics_file", None) \
            and getattr(args, "metrics_every", 0.0) > 0:
        telemetry.start_dumper(args.metrics_file, args.metrics_every)
    return tracer, telemetry


def _make_ops(args, telemetry, *, role, shard=None, meta=None,
              modelhealth=None):
    """Flight recorder + watchdogs + health plane for one split-mode
    process (telemetry/health.py, docs/OBSERVABILITY.md).  Inert unless
    --flight-dir/--health-port, so every role wires it unconditionally;
    with --flight-dir the process also dumps its rings on SIGTERM/
    SIGABRT/fatal signals — the raw material of `python -m
    kafka_ps_tpu.telemetry postmortem`."""
    from kafka_ps_tpu.telemetry.health import OpsPlane
    from kafka_ps_tpu.telemetry.slo import plane_from_args
    return OpsPlane(flight_dir=getattr(args, "flight_dir", None),
                    health_port=getattr(args, "health_port", None),
                    telemetry=telemetry, role=role, shard=shard,
                    meta=meta,
                    profile=getattr(args, "profile", False),
                    slo_plane=plane_from_args(args, telemetry),
                    modelhealth=modelhealth)


def _make_modelhealth(args, telemetry, *, shard=None, num_features=None,
                      model="sequential", log_name=None):
    """Model-health plane for one split-mode process (--model-health,
    telemetry/modelhealth.py) plus its wall-clock-stamping drift-CSV
    sink — the monitor emits clock-free rows so telemetry/drift.py
    stays replay-pure (PS104); the stamp happens here, in CLI land.
    Returns (plane_or_None, sink_or_None); OpsPlane owns the plane's
    lifecycle, the caller closes the sink after ops.close()."""
    if not getattr(args, "model_health", False):
        return None, None
    from kafka_ps_tpu.telemetry.modelhealth import plane_from_args
    sink = None
    log = None
    if getattr(args, "logging", False) and log_name:
        from kafka_ps_tpu.utils.csvlog import CsvLogSink, DRIFT_HEADER
        sink = CsvLogSink(log_name, DRIFT_HEADER)
        log = (lambda rest:
               sink(f"{int(time.time() * 1000)};{rest}"))
    plane = plane_from_args(args, telemetry, shard=shard,
                            num_features=num_features, model=model,
                            log=log)
    return plane, sink


def _dump_telemetry(args, tracer, telemetry) -> None:
    """Exit-path flush for _make_telemetry (mirrors cli/run.py)."""
    if getattr(args, "metrics_file", None):
        telemetry.stop_dumper()
        telemetry.write_prometheus(args.metrics_file)
    if getattr(args, "trace", None) and tracer is not None:
        print(tracer.dump(args.trace), file=sys.stderr, flush=True)


class _BatchingSink:
    """Producer sink that coalesces stream rows into T_DATA_BATCH frames.

    Per-worker row buffers flush on size (one frame per `batch` rows) or
    age (`flush_aged`, called from the server main loop's poll tick, so
    a trickling stream never strands rows).  Delivery goes through
    ServerBridge.send_data_batch — one frame, one syscall, one receiver
    lock for the whole batch — and falls back to the per-row sink (which
    owns the reroute/eviction policy) whenever the batch path can't
    deliver.  Thread-safe: the producer thread adds while the main loop
    flushes; a size-flush racing an age-flush can reorder rows between
    frames, which the reroute path already permits (sliding-buffer
    ingest is order-insensitive beyond insertion ids).
    """

    def __init__(self, bridge, fallback, deliverable,
                 batch: int = 32, max_age: float = 0.05):
        self._bridge = bridge
        self._fallback = fallback      # per-row sink with reroute logic
        self._deliverable = deliverable
        self._batch = batch
        self._max_age = max_age
        self._rows: dict[int, list] = {}
        self._oldest: dict[int, float] = {}   # worker -> first-row time
        self._lock = OrderedLock("BatchingIngest.rows")

    def __call__(self, worker: int, features, label: int) -> None:
        with self._lock:
            rows = self._rows.setdefault(worker, [])
            if not rows:
                self._oldest[worker] = time.monotonic()
            rows.append((features, label))
            if len(rows) < self._batch:
                return
            del self._rows[worker]
            self._oldest.pop(worker, None)
        self._deliver(worker, rows)

    def flush_aged(self) -> None:
        """Flush every batch whose FIRST row has waited >= max_age."""
        now = time.monotonic()
        due = []
        with self._lock:
            for w, t0 in list(self._oldest.items()):
                if now - t0 >= self._max_age:
                    due.append((w, self._rows.pop(w)))
                    del self._oldest[w]
        for w, rows in due:
            self._deliver(w, rows)

    def flush_all(self) -> None:
        with self._lock:
            pending = [(w, self._rows.pop(w)) for w in list(self._rows)]
            self._oldest.clear()
        for w, rows in pending:
            self._deliver(w, rows)

    def _deliver(self, worker: int, rows) -> None:
        if self._deliverable(worker) and self._bridge.send_data_batch(
                worker, rows):
            return
        for features, label in rows:
            self._fallback(worker, features, label)


def run_server(args) -> int:
    """Server role: ServerNode + producer, all workers remote.

    Failure handling mirrors the in-process supervisor
    (runtime/app.py:run_threaded) across the wire — the reference gets
    the same from Kafka consumer-group rebalancing + k8s pod restarts
    (kubernetes/worker.yaml, SURVEY §5):
      * failure_policy=halt (default): a worker-connection loss stops
        the run with an error instead of deadlocking the gate;
      * failure_policy=rebalance: the dead connection's workers are
        evicted (gates stop waiting, their stream rows reroute to the
        survivors) and a reconnecting worker process is readmitted at
        the slowest active clock once its buffer holds data (READY).
    """
    from kafka_ps_tpu.cli.run import load_test_csv
    from kafka_ps_tpu.data.stream import CsvStreamProducer
    from kafka_ps_tpu.runtime.server import ServerNode
    from kafka_ps_tpu.utils.csvlog import (CsvLogSink, EVENTS_HEADER,
                                           NullLogSink, SERVER_HEADER)

    cfg = _make_cfg(args)
    codec_spec = _codec_spec(args)
    failure_policy = getattr(args, "failure_policy", "halt")
    hb_timeout = getattr(args, "heartbeat_timeout", None)
    test_x, test_y = load_test_csv(args.test_data_file_path,
                                   args.num_features)
    # a resumed run must CONTINUE the prior run's logs, not truncate
    # them (mirrors cli/run.py's make_app_from_args; post-run validation
    # audits the logs across the resume)
    checkpoint_path = getattr(args, "checkpoint", None)
    resuming = bool(checkpoint_path) and os.path.exists(checkpoint_path)
    log = CsvLogSink("./logs-server.csv" if args.logging else None,
                     SERVER_HEADER, append=resuming)
    # events persist incrementally — an end-of-run dump would lose the
    # auditor's eviction/readmission record on a crash
    events_log = (CsvLogSink("./logs-events.csv", EVENTS_HEADER,
                             append=resuming)
                  if args.logging else NullLogSink())
    # the logical-run id the bridge advertises (T_CONFIG): a resume
    # continues the checkpointed run, a fresh start mints a new one —
    # worker processes match their local state files against it
    run_id = None
    if resuming:
        from kafka_ps_tpu.utils import checkpoint as ckpt
        run_id = ckpt.peek_run_id(checkpoint_path)
    if run_id is None:
        run_id = time.time_ns()
    tracer, telemetry = _make_telemetry(args)
    bridge = net.ServerBridge(
        port=args.listen,
        heartbeat_interval=min(1.0, hb_timeout / 3) if hb_timeout else 1.0,
        heartbeat_timeout=hb_timeout,
        run_id=run_id,
        codec=codec_spec,
        tracer=tracer, telemetry=telemetry,
        shm=getattr(args, "serve_shm", False),
        coalesce=getattr(args, "wire_coalesce", True))
    print(f"listening on port {bridge.port}", file=sys.stderr, flush=True)
    from kafka_ps_tpu.utils.asynclog import DeferredSink
    fabric = bridge.wrap(fabric_mod.Fabric())
    server = ServerNode(cfg, fabric, test_x, test_y, DeferredSink(log),
                        tracer=tracer, telemetry=telemetry)
    # aggregation-tier hooks (kafka_ps_tpu/agg/, docs/AGGREGATION.md):
    # releases to workers behind an aggregator relay group into one
    # T_WEIGHTS_AGG frame per relay (no-op while no relay is connected)
    server.weights_group_send = bridge.send_weights_group
    if getattr(args, "bsp_order", False):
        # deterministic BSP apply order (worker-id per round) so an
        # aggregated run is bitwise-comparable to a direct socket run
        server.bsp_order = True
        print("bsp-order: buffering rounds for worker-id-ordered "
              "applies", file=sys.stderr, flush=True)
    if codec_spec.codec_id != net.CODEC_NONE:
        # weights leave this process quantize-dequantized so both sides
        # train against the SAME decoded theta; per-connection fallback
        # (a peer that negotiated NONE gets plain frames) lives in
        # ServerBridge._send
        from kafka_ps_tpu import compress
        codec = compress.get_codec(codec_spec, server.task.num_params)
        server.compressor = compress.WeightsCompressor(codec)
        print(f"compression: {codec_spec.name}", file=sys.stderr,
              flush=True)
    server.run_id = run_id
    server.membership_log = events_log   # before restore: it logs "resume"
    # async coalescing eval plane (evaluation/engine.py): default-on,
    # `--no-eval-async` restores the fused-eval apply programs
    eval_engine = None
    if cfg.eval_async and test_x is not None:
        from kafka_ps_tpu.evaluation.engine import EvalEngine
        eval_engine = server.attach_eval_engine(EvalEngine(
            server.task, server.test_x, server.test_y, server._emit_eval,
            telemetry=telemetry, tracer=tracer))

    from kafka_ps_tpu.log.durable_fabric import COLD_PARTITION_DIR
    from kafka_ps_tpu.runtime.messages import KeyRange
    tier_store = _attach_tier_store(
        server, cfg, KeyRange(0, server.task.num_params),
        cold_dir=(os.path.join(args.durable_log, COLD_PARTITION_DIR)
                  if getattr(args, "durable_log", None) else None),
        telemetry=telemetry)

    if checkpoint_path:
        from kafka_ps_tpu.utils import checkpoint as ckpt
        ckpt.maybe_restore(checkpoint_path, server)
        server.checkpoint_path = checkpoint_path
        server.checkpoint_every = getattr(args, "checkpoint_every", 50)
        if resuming:
            print(f"restored checkpoint at iteration {server.iterations}",
                  file=sys.stderr, flush=True)

    # online serving plane on the SAME port as the workers: predict-only
    # clients never HELLO, so the bridge routes them nothing but their
    # own T_PREDICTION replies (docs/SERVING.md)
    engine = None
    if getattr(args, "serve", False):
        from kafka_ps_tpu.serving.engine import PredictionEngine
        from kafka_ps_tpu.serving.snapshot import SnapshotRegistry
        registry = SnapshotRegistry(
            capacity=getattr(args, "serve_snapshots", 8))
        server.serving = registry
        shed_ms = getattr(args, "serve_shed_ms", 0.0)
        engine = PredictionEngine(
            server.task, registry,
            max_batch=getattr(args, "serve_batch", 16),
            deadline_s=getattr(args, "serve_deadline_ms", 2.0) / 1000.0,
            queue_limit=getattr(args, "serve_queue", 0),
            shed_deadline_s=shed_ms / 1000.0 if shed_ms else None,
            auto=getattr(args, "serve_auto", True),
            tracer=tracer, telemetry=telemetry)
        bridge.attach_serving(engine)
        server.publish_snapshot()    # cold start: restored/fresh theta
        # compile every bucket shape + calibrate the dispatch cost
        # model now, not in some client's p99 (docs/SERVING.md)
        engine.warmup()
        print(f"serving predictions on port {bridge.port}",
              file=sys.stderr, flush=True)

    # model-health plane (--model-health): the apply path feeds it;
    # the producer's row sink feeds its feature sketch below (in split
    # mode the buffers live in the worker processes, but every stream
    # row passes through HERE first)
    from kafka_ps_tpu.telemetry.registry import model_name
    modelhealth, drift_sink = _make_modelhealth(
        args, telemetry, num_features=cfg.model.num_features,
        model=model_name(cfg.consistency_model),
        log_name="./logs-drift.csv")
    if modelhealth is not None:
        server.attach_model_health(modelhealth)

    ops = _make_ops(args, telemetry, role="server",
                    modelhealth=modelhealth)
    ops.add_gate_watchdog(server)
    if eval_engine is not None:
        ops.add_eval_engine(eval_engine)   # /evalz detail row
    if engine is not None:
        ops.add_serving_watchdog(engine)
    ops.start()

    # membership events cross threads (bridge readers -> main loop):
    # ServerNode is single-threaded by design, so evictions/readmissions
    # are applied only between gradient polls
    events: "queue.Queue[tuple[str, object]]" = queue.Queue()
    bridge.on_disconnect = lambda ids: events.put(("disconnect", ids))
    bridge.on_ready = lambda w: events.put(("ready", w))

    workers = server.tracker.active_workers   # a checkpoint may carry evictions
    bridge.wait_for_connected(workers, timeout=args.connect_timeout)

    reroute = {"rr": 0, "dropped": 0}

    def sink(worker: int, features: dict[int, float], label: int) -> None:
        # Rows flow to whoever holds the worker's connection — including
        # (under rebalance) a reconnected-but-not-yet-readmitted process,
        # whose buffer must fill before READY triggers readmission.
        # Under halt an inactive worker can never be readmitted, so a
        # reconnected-evicted target (checkpoint carrying evictions)
        # would swallow its partition's rows forever — reroute instead.
        # A dead target reroutes round-robin to the survivors (the
        # partition reassignment of a consumer-group rebalance); with
        # nobody left the row is counted, not silently discarded.
        deliverable = (failure_policy == "rebalance"
                       or server.tracker.tracker[worker].active)
        if deliverable and bridge.send_data(worker, features, label):
            return
        active = server.tracker.active_workers
        for _ in range(len(active)):
            alt = active[reroute["rr"] % len(active)]
            reroute["rr"] += 1
            if alt != worker and bridge.send_data(alt, features, label):
                return
        reroute["dropped"] += 1

    batch_sink = _BatchingSink(
        bridge, sink,
        deliverable=lambda w: (failure_policy == "rebalance"
                               or server.tracker.tracker[w].active))
    row_sink = batch_sink
    if modelhealth is not None:
        def row_sink(worker: int, features, label: int) -> None:
            # sampled feature sketch (population-stability signal,
            # telemetry/drift.py) on the producer thread, before the
            # row fans out to whichever worker holds the connection
            modelhealth.drift.observe_row(features)
            batch_sink(worker, features, label)
    producer = CsvStreamProducer(
        args.training_data_file_path, cfg.num_workers, row_sink,
        time_per_event_ms=cfg.stream.time_per_event_ms,
        prefill_per_worker=cfg.stream.prefill_per_worker)
    producer.run_in_background()
    bridge.wait_for_workers(workers, timeout=args.connect_timeout)

    # one entry per worker that has announced READY this server
    # lifetime: a SECOND ready from a still-ACTIVE worker is a
    # restarted process (a member behind an aggregation relay — its
    # death never surfaces here as a disconnect) whose in-flight
    # weights assignment died with it
    seen_ready: set = set()

    def apply_events() -> None:
        while True:
            try:
                kind, val = events.get_nowait()
            except queue.Empty:
                return
            if kind == "disconnect":
                live = [w for w in val
                        if server.tracker.tracker[w].active]
                if not live:
                    continue
                if failure_policy == "halt":
                    raise RuntimeError(
                        f"worker connection lost for {sorted(live)} "
                        "(failure_policy=halt; use "
                        "--failure_policy rebalance to continue on "
                        "the survivors)")
                for w in live:
                    try:
                        server.remove_worker(w)
                    except ValueError:
                        raise RuntimeError(
                            "all worker connections lost") from None
                    print(f"evicted worker {w} (connection lost)",
                          file=sys.stderr, flush=True)
            elif kind == "ready":
                w = int(val)
                status = server.tracker.tracker[w]
                if (failure_policy == "rebalance"
                        and not status.active):
                    clock = server.readmit_worker(w)
                    seen_ready.add(w)
                    print(f"readmitted worker {w} at clock {clock}",
                          file=sys.stderr, flush=True)
                elif (w in seen_ready and status.active
                        and status.weights_message_sent):
                    # liveness reissue, mirroring ServerNode.
                    # _composite_member_live: the worker process
                    # restarted (durable state restored, so it READYs
                    # again immediately) while its round assignment was
                    # lost mid-flight — re-send the current weights so
                    # the stalled gate completes.  Idempotent for
                    # theta: a recompute yields a duplicate gradient
                    # the clock filter already drops.
                    server.send_weights(w, status.vector_clock)
                    print(f"reissued weights to restarted worker {w} "
                          f"at clock {status.vector_clock}",
                          file=sys.stderr, flush=True)
                else:
                    seen_ready.add(w)

    # live pulse (utils/status.py): iters/s, clocks, membership, queue
    # depth — the split-mode face of `--status_every`
    from kafka_ps_tpu.utils.status import StatusReporter

    rolling_critpath = None
    if telemetry.enabled:
        from kafka_ps_tpu.telemetry.critpath import RollingCritpath
        rolling_critpath = RollingCritpath(telemetry)

    def status() -> dict:
        tr = server.tracker
        active = tr.active_workers
        out = {
            "iters": server.iterations,
            "clocks": [f"{w}:{tr.tracker[w].vector_clock}"
                       for w in range(cfg.num_workers)],
            "active": f"{len(active)}/{cfg.num_workers}",
            "pending": {"gradients": fabric.total_pending(
                fabric_mod.GRADIENTS_TOPIC)},
            "rows_sent": producer.rows_sent,
        }
        if engine is not None:
            s = engine.stats()
            out["predictions_per_s"] = s["requests"]
            out["serving"] = {"occ": s["occupancy"],
                              "p50_ms": s["p50_ms"], "p99_ms": s["p99_ms"],
                              "stale": s["rejections"]}
        if telemetry.enabled:
            out["metrics"] = telemetry.summary()
        if rolling_critpath is not None:
            # per-heartbeat histogram deltas -> dominant-segment verdict
            # for this window (telemetry/critpath.py)
            out["critpath"] = rolling_critpath.sample()
        if modelhealth is not None:
            # model-health pulse: update norms, direction cosine,
            # drift verdict (telemetry/modelhealth.py)
            out["modelhealth"] = modelhealth.summary()
        return out

    reporter = StatusReporter(getattr(args, "status_every", 0.0) or 0.0,
                              status).start()

    server.start_training_loop()
    max_iters = args.max_iterations or sys.maxsize
    try:
        while server.iterations < max_iters:
            apply_events()
            batch_sink.flush_aged()   # age-bound the batched ingest path
            g = fabric.poll_blocking(fabric_mod.GRADIENTS_TOPIC, 0,
                                     timeout=0.2)
            if g is not None:
                server.process(g)
    except KeyboardInterrupt:
        # mirror cli/run.py: Ctrl-C is an orderly shutdown — the
        # finally block still checkpoints and flushes logs/events
        print("interrupted — shutting down", file=sys.stderr, flush=True)
    finally:
        reporter.stop()
        producer.stop()      # join the pump before teardown (SIGABRT
                             # discipline: no native-code daemon threads
                             # may outlive the main thread)
        batch_sink.flush_all()   # after the pump join: no concurrent adds
        bridge.close()       # workers see EOF and shut down; joins
                             # accept/heartbeat/reader threads
        if engine is not None:
            engine.close()   # after the bridge: no reader can submit now
        if eval_engine is not None:
            eval_engine.close()   # drains pending evals into server.log
        if checkpoint_path:
            from kafka_ps_tpu.utils import checkpoint as ckpt
            ckpt.save(checkpoint_path, server)
        if tier_store is not None:
            tier_store.close()   # after the save: it may fault cold pages
        if reroute["dropped"] or bridge.dropped_sends:
            print(f"dropped rows: {reroute['dropped']}, dropped sends: "
                  f"{bridge.dropped_sends}", file=sys.stderr, flush=True)
        server.log.close()           # joins drain thread + closes sink
        events_log.close()
        ops.close()                  # final flight dump + health down
        if drift_sink is not None:
            # after ops.close(): the plane's final drain may still
            # emit a verdict row
            drift_sink.close()
        _dump_telemetry(args, tracer, telemetry)
    return 0


def run_worker(args) -> int:
    """Worker role: the logical workers in --worker_ids, server remote.

    `--connect` with a comma-separated address list enters the
    range-sharded deployment (docs/SHARDING.md): one connection per
    shard-server process, gradient slices routed per shard, weights
    slices reassembled at a common clock.

    `--aggregate HOST:PORT` dials a per-host aggregator relay instead
    of the server (docs/AGGREGATION.md) and reuses the sharded path
    with one address: the relay speaks the server protocol downstream,
    and the router's redelivery cache is exactly the buffer-and-resend
    a SIGKILL'd relay needs (deltas it held die with it; the stale
    weights that follow reconnection trigger cache resends)."""
    if getattr(args, "aggregate", None):
        return _run_worker_sharded(args, [args.aggregate],
                                   aggregate=True)
    if "," in args.connect:
        return _run_worker_sharded(
            args, [a for a in args.connect.split(",") if a])
    from kafka_ps_tpu.cli.run import load_test_csv
    from kafka_ps_tpu.data.buffer import SlidingBuffer
    from kafka_ps_tpu.runtime.worker import WorkerNode
    from kafka_ps_tpu.utils.csvlog import CsvLogSink, WORKER_HEADER

    host, _, port = args.connect.rpartition(":")
    ids = [int(w) for w in args.worker_ids.split(",")]
    cfg = _make_cfg(args)
    test_x, test_y = load_test_csv(args.test_data_file_path,
                                   args.num_features)

    # connect FIRST: the handshake (net.T_CONFIG) carries the server's
    # logical-run id, which decides whether local state is valid below,
    # and the NEGOTIATED codec — compression runs at what the server
    # agreed to, not at what this process asked for (a mixed-version
    # server replies NONE and both sides ship plain frames)
    tracer, telemetry = _make_telemetry(args)
    bridge = net.WorkerBridge(
        host or "127.0.0.1", int(port), ids,
        heartbeat_timeout=getattr(args, "heartbeat_timeout", None),
        codec=_codec_spec(args),
        tracer=tracer, telemetry=telemetry,
        coalesce=getattr(args, "wire_coalesce", True))
    fabric = bridge.make_fabric()
    # per-process model-health plane (--model-health): each worker
    # process watches its OWN local training stream — eval rows from
    # _finish, sampled buffer arrivals into the feature sketch
    from kafka_ps_tpu.telemetry.registry import model_name
    modelhealth, drift_sink = _make_modelhealth(
        args, telemetry, num_features=cfg.model.num_features,
        model=model_name(cfg.consistency_model),
        log_name="./logs-drift-worker.csv")
    # death hooks armed before training: a SIGTERM'd worker leaves its
    # flight dump for the postmortem merge even mid-iteration
    ops = _make_ops(args, telemetry, role="worker",
                    modelhealth=modelhealth)
    ops.start()

    compressors = None
    if bridge.negotiated.codec_id != net.CODEC_NONE:
        from kafka_ps_tpu import compress
        from kafka_ps_tpu.models.task import get_task
        codec = compress.get_codec(
            bridge.negotiated, get_task(cfg.task, cfg.model).num_params)
        compressors = {w: compress.ErrorFeedback(codec) for w in ids}
        print(f"compression: {bridge.negotiated.name} (negotiated)",
              file=sys.stderr, flush=True)

    # worker-local durable state (utils/checkpoint.py): the per-process
    # analogue of the reference's changelog-backed store restore
    # (WorkerApp.java:40-42) — a worker process restarted WITHIN a run
    # recovers its training window instead of cold-starting an empty
    # buffer.  State written under a different run (the server started
    # fresh since) is stale: restoring it would seed this run with the
    # old run's rows and append to a log the server side truncated.
    state_path = None
    restoring = False
    if getattr(args, "checkpoint", None):
        from kafka_ps_tpu.utils import checkpoint as ckpt
        state_path = ckpt.worker_state_path(args.checkpoint, ids)
        stored = ckpt.peek_run_id(state_path)
        restoring = stored is not None and stored == bridge.server_run_id
        if not restoring and os.path.exists(state_path):
            print(f"discarding stale worker state {state_path} "
                  f"(run {stored} != server run {bridge.server_run_id})",
                  file=sys.stderr, flush=True)
            os.remove(state_path)
    # Log continuity is decided by RUN continuity, not by whether buffer
    # state restored (ADVICE r4): a worker SIGKILL'd before its first
    # state snapshot has no state file, but its pre-crash log rows still
    # belong to this logical run — truncating them would break the
    # cross-restart audit trail.  A sidecar marker records which run the
    # log belongs to.
    log_path = "./logs-worker.csv" if args.logging else None
    append_log = restoring
    if log_path is not None:
        marker = log_path + ".runid"
        try:
            with open(marker) as fh:
                append_log = append_log or (
                    int(fh.read().strip()) == bridge.server_run_id)
        except (OSError, ValueError):
            pass
        with open(marker, "w") as fh:
            fh.write(str(bridge.server_run_id))
    log = CsvLogSink(log_path, WORKER_HEADER, append=append_log)

    buffers = {w: SlidingBuffer(cfg.model.num_features, cfg.buffer,
                                telemetry=telemetry, worker=w)
               for w in ids}
    if restoring:
        from kafka_ps_tpu.utils import checkpoint as ckpt
        if ckpt.maybe_restore_worker(state_path, buffers,
                                     run_id=bridge.server_run_id,
                                     residuals=compressors):
            print("restored worker buffers: " + ", ".join(
                f"{w}:{buffers[w].count} rows (seen "
                f"{buffers[w].num_tuples_seen})" for w in ids),
                file=sys.stderr, flush=True)
    from kafka_ps_tpu.utils.asynclog import DeferredSink
    worker_log = DeferredSink(log)
    nodes = {w: WorkerNode(w, cfg, fabric, buffers[w], test_x, test_y,
                           worker_log, tracer=tracer, telemetry=telemetry)
             for w in ids}
    if compressors is not None:
        for w in ids:
            nodes[w].compressor = compressors[w]
    if modelhealth is not None:
        # all logical workers in this process share the one plane;
        # the reader thread's buffer inserts feed the feature sketch
        for w in ids:
            nodes[w].modelhealth = modelhealth
            buffers[w].attach_drift(modelhealth.drift)

    if state_path is not None:
        from kafka_ps_tpu.utils import checkpoint as ckpt
        state_stop = threading.Event()

        state_every = getattr(args, "state_every", 1.0)
        if state_every is None or state_every <= 0:
            raise SystemExit("--state_every must be > 0 (seconds between "
                             "durable buffer snapshots)")

        def state_saver():
            # the changelog analogue: snapshot on a cadence (the
            # --state_every flag) so a SIGKILL'd process loses at most
            # one interval of rows; skip idle intervals.  The
            # fingerprint covers insertions AND iteration counts: under
            # compression the error-feedback residuals advance on every
            # local iteration even when no new rows arrived, and a
            # snapshot that missed them would replay a biased stream
            # after a crash.
            last = None
            while not state_stop.wait(state_every):
                fp = (tuple(buffers[w].num_tuples_seen for w in ids),
                      tuple(nodes[w].iterations for w in ids))
                if fp != last:
                    ckpt.save_worker(state_path, buffers,
                                     run_id=bridge.server_run_id,
                                     residuals=compressors)
                    last = fp

        state_saver_thread = threading.Thread(
            target=state_saver, daemon=True, name="kps-worker-state")
        state_saver_thread.start()

    reader_thread = threading.Thread(target=bridge.run_reader,
                                     args=(buffers,), daemon=True,
                                     name="kps-worker-reader")
    reader_thread.start()

    # READY per worker once its buffer has data (the server gates the
    # training-loop bootstrap on this, net.ServerBridge.wait_for_workers)
    # — or `--ready-rows N` rows of it, when a test wants training to
    # start only after a deterministic ingestion prefix
    ready_stop = threading.Event()
    ready_rows = max(1, int(getattr(args, "ready_rows", 1) or 1))

    def announce_ready():
        pending = set(ids)
        while (pending and not bridge.disconnected.is_set()
               and not ready_stop.is_set()):
            for w in list(pending):
                if buffers[w].count >= ready_rows:
                    bridge.mark_ready(w)
                    pending.discard(w)
            time.sleep(0.01)

    ready_thread = threading.Thread(target=announce_ready, daemon=True,
                                    name="kps-worker-ready")
    ready_thread.start()

    stop = threading.Event()
    errors: list[BaseException] = []

    def worker_loop(node: WorkerNode):
        try:
            while not stop.is_set():
                msg = fabric.poll_blocking(fabric_mod.WEIGHTS_TOPIC,
                                           node.worker_id, timeout=0.1)
                if msg is not None:
                    node.on_weights(msg)
        except (ConnectionError, OSError):
            pass                      # server hung up mid-send
        except BaseException as e:    # pragma: no cover - diagnostics
            errors.append(e)
            stop.set()

    threads = [threading.Thread(target=worker_loop, args=(nodes[w],),
                                daemon=True, name=f"worker-{w}")
               for w in ids]
    for t in threads:
        t.start()
    bridge.disconnected.wait()        # run until the server closes
    stop.set()
    ready_stop.set()
    # Shutdown discipline (the round-4 SIGABRT root cause, docs/
    # TESTING.md): every thread that can touch JAX/XLA or numpy native
    # code MUST be joined before the interpreter finalizes — a daemon
    # thread killed inside C++ noexcept frames calls std::terminate.
    # A worker loop is bounded (poll timeout 0.1 s + one local update),
    # but the first post-load iteration can pay tens of seconds of jit
    # compilation on a loaded machine, so the joins are generous.
    leftover = []
    for t in threads:
        t.join(timeout=120.0)
        if t.is_alive():
            leftover.append(t.name)
    if state_path is not None:
        from kafka_ps_tpu.utils import checkpoint as ckpt
        state_stop.set()
        # join BEFORE the final save: two concurrent save_worker calls
        # share one tmp path and would corrupt the state file
        state_saver_thread.join(timeout=60.0)
        if state_saver_thread.is_alive():   # wedged in a stalled write
            print("warning: state saver still writing; skipping final "
                  "snapshot", file=sys.stderr, flush=True)
            leftover.append(state_saver_thread.name)
        else:
            ckpt.save_worker(state_path, buffers,   # final snapshot
                             run_id=bridge.server_run_id,
                             residuals=compressors)
    worker_log.close()    # joins the drain thread, flushes, closes log
    bridge.close()
    reader_thread.join(timeout=10.0)  # EOF/closed socket ends it
    ready_thread.join(timeout=10.0)
    for t in (reader_thread, ready_thread):
        if t.is_alive():
            leftover.append(t.name)
    # dump BEFORE the potential os._exit below — a wedged thread must
    # not cost the process its trace/metrics/flight files
    ops.close()
    if drift_sink is not None:
        drift_sink.close()
    _dump_telemetry(args, tracer, telemetry)
    rc = 0
    if errors:
        print(f"worker failed: {errors[0]!r}", file=sys.stderr, flush=True)
        rc = 1
    if leftover:
        # a thread survived its join and may be inside native code:
        # skip interpreter finalization entirely rather than risk the
        # teardown abort (this is a CLI process, nothing else to run)
        print(f"warning: threads still alive at exit: {leftover}; "
              "exiting without finalization", file=sys.stderr, flush=True)
        sys.stdout.flush()
        os._exit(rc)
    if errors:
        raise RuntimeError("worker failed") from errors[0]
    return 0


# -- range-sharded split deployment (docs/SHARDING.md) -----------------------

def run_server_shard(args) -> int:
    """One shard-server process of a `--shards N` split deployment:
    owns `ShardPlan.ranges[shard_id]` of theta with its own per-worker
    vector clocks, its own consistency gate (all three models evaluate
    per shard), its own per-shard checkpoint file
    (utils/checkpoint.shard_state_path) and — with `--durable-log DIR`
    — its own commit-log partition under `DIR/shard<I>of<N>`, so a
    SIGKILL'd shard recovers bitwise from checkpoint + log-tail replay
    while the other shards keep running (scripts/tier1.sh --shard).

    Shard 0 additionally hosts the stream producer (the data plane is
    unsharded — rows go to workers, not servers).  No shard hosts the
    server-side eval or the serving plane: each owns only a slice, and
    assembled-theta serving is the in-process ShardedServerGroup /
    FrontierCutPublisher story.  Worker-side gradient sparsification
    (`--compress topk:R` on the WORKER processes) is what shrinks the
    per-shard wire traffic; shard servers themselves run uncompressed
    weights slices.
    """
    from kafka_ps_tpu.models.task import get_task
    from kafka_ps_tpu.data.stream import CsvStreamProducer
    from kafka_ps_tpu.runtime.server import ServerNode
    from kafka_ps_tpu.runtime.sharding import ShardPlan
    from kafka_ps_tpu.utils import checkpoint as ckpt

    cfg = _make_cfg(args)
    num_shards, shard_id = args.shards, args.shard_id
    plan = ShardPlan(get_task(cfg.task, cfg.model).num_params, num_shards)
    key_range = plan.ranges[shard_id]
    if getattr(args, "serve", False):
        raise SystemExit(
            "--serve is unsharded-only in split mode: a shard process "
            "holds one theta slice; assembled-theta serving is the "
            "in-process ShardedServerGroup path (docs/SHARDING.md)")
    failure_policy = getattr(args, "failure_policy", "halt")
    hb_timeout = getattr(args, "heartbeat_timeout", None)

    checkpoint_path = None
    if getattr(args, "checkpoint", None):
        checkpoint_path = ckpt.shard_state_path(
            args.checkpoint, shard_id, num_shards)
    resuming = bool(checkpoint_path) and os.path.exists(checkpoint_path)
    run_id = ckpt.peek_run_id(checkpoint_path) if resuming else None
    if run_id is None:
        run_id = time.time_ns()

    tracer, telemetry = _make_telemetry(args)
    inner = fabric_mod.Fabric()
    if getattr(args, "durable_log", None):
        # one durable-log partition set per shard: gradients keyed 0
        # locally, rooted under a shard-suffixed directory so N shard
        # processes never share a segment file
        from kafka_ps_tpu.log import DurableFabric, LogConfig
        inner = DurableFabric(
            os.path.join(args.durable_log,
                         f"shard{shard_id}of{num_shards}"),
            LogConfig(fsync=getattr(args, "fsync", "interval")),
            tracer=tracer, telemetry=telemetry)
    bridge = net.ServerBridge(
        port=args.listen,
        heartbeat_interval=min(1.0, hb_timeout / 3) if hb_timeout else 1.0,
        heartbeat_timeout=hb_timeout,
        run_id=run_id, tracer=tracer, telemetry=telemetry,
        coalesce=getattr(args, "wire_coalesce", True))
    print(f"shard {shard_id}/{num_shards} range "
          f"[{key_range.start}, {key_range.end}) listening on port "
          f"{bridge.port}", file=sys.stderr, flush=True)
    fabric = bridge.wrap(inner)     # preserves DurableFabric's class
    server = ServerNode(cfg, fabric, None, None, None,
                        tracer=tracer, telemetry=telemetry,
                        key_range=key_range, shard_id=shard_id,
                        num_shards=num_shards)
    server.run_id = run_id
    tier_store = _attach_tier_store(
        server, cfg, key_range,
        cold_dir=(inner.cold_dir()      # under the shard-suffixed root
                  if getattr(inner, "durable", False) else None),
        telemetry=telemetry)
    if checkpoint_path:
        ckpt.maybe_restore(checkpoint_path, server)
        server.checkpoint_path = checkpoint_path
        server.checkpoint_every = getattr(args, "checkpoint_every", 50)
        if resuming:
            print(f"shard {shard_id}: restored checkpoint at iteration "
                  f"{server.iterations}", file=sys.stderr, flush=True)
    if getattr(inner, "durable", False):
        # crash recovery: re-enqueue the unconsumed gradient-slice tail
        # past the checkpoint's committed offsets; the tracker dedups
        # whatever the checkpoint already covers (at-least-once replay)
        counts = inner.recover(server.restored_log_offsets)
        if any(counts.values()):
            print(f"shard {shard_id}: durable-log replay {counts}",
                  file=sys.stderr, flush=True)

    # per-shard model-health plane: every metric family carries
    # shard=<I>, so fleet dashboards can tell WHICH slice went sour
    from kafka_ps_tpu.telemetry.registry import model_name
    modelhealth, drift_sink = _make_modelhealth(
        args, telemetry, shard=shard_id,
        num_features=cfg.model.num_features,
        model=model_name(cfg.consistency_model),
        log_name=f"./logs-drift-shard{shard_id}.csv")
    if modelhealth is not None:
        server.attach_model_health(modelhealth)

    # per-shard ops plane: the dump carries shard identity, so the
    # postmortem merge can tell WHICH gate in the fleet wedged
    ops = _make_ops(args, telemetry, role="server", shard=shard_id,
                    meta={"shards": list(range(num_shards))},
                    modelhealth=modelhealth)
    ops.add_gate_watchdog(server)
    if getattr(inner, "durable", False):
        ops.add_fsync_watchdog()
    ops.start()

    events: "queue.Queue[tuple[str, object]]" = queue.Queue()
    bridge.on_disconnect = lambda ids: events.put(("disconnect", ids))
    bridge.on_ready = lambda w: events.put(("ready", w))
    workers = server.tracker.active_workers
    bridge.wait_for_connected(workers, timeout=args.connect_timeout)

    producer = None
    batch_sink = None
    reroute = {"rr": 0, "dropped": 0}
    if shard_id == 0:
        # the data plane lives on shard 0 only — same sink/reroute
        # policy as the unsharded run_server
        def sink(worker: int, features: dict[int, float],
                 label: int) -> None:
            deliverable = (failure_policy == "rebalance"
                           or server.tracker.tracker[worker].active)
            if deliverable and bridge.send_data(worker, features, label):
                return
            active = server.tracker.active_workers
            for _ in range(len(active)):
                alt = active[reroute["rr"] % len(active)]
                reroute["rr"] += 1
                if alt != worker and bridge.send_data(alt, features,
                                                      label):
                    return
            reroute["dropped"] += 1

        batch_sink = _BatchingSink(
            bridge, sink,
            deliverable=lambda w: (failure_policy == "rebalance"
                                   or server.tracker.tracker[w].active))
        producer = CsvStreamProducer(
            args.training_data_file_path, cfg.num_workers, batch_sink,
            time_per_event_ms=cfg.stream.time_per_event_ms,
            prefill_per_worker=cfg.stream.prefill_per_worker)
        producer.run_in_background()
    bridge.wait_for_workers(workers, timeout=args.connect_timeout)

    def apply_events() -> None:
        while True:
            try:
                kind, val = events.get_nowait()
            except queue.Empty:
                return
            if kind == "disconnect":
                live = [w for w in val
                        if server.tracker.tracker[w].active]
                if not live:
                    continue
                if failure_policy == "halt":
                    raise RuntimeError(
                        f"shard {shard_id}: worker connection lost for "
                        f"{sorted(live)} (failure_policy=halt)")
                for w in live:
                    try:
                        server.remove_worker(w)
                    except ValueError:
                        raise RuntimeError(
                            "all worker connections lost") from None
            elif kind == "ready" and failure_policy == "rebalance":
                w = int(val)
                if not server.tracker.tracker[w].active:
                    server.readmit_worker(w)

    server.start_training_loop()
    max_iters = args.max_iterations or sys.maxsize
    try:
        while server.iterations < max_iters:
            apply_events()
            if batch_sink is not None:
                batch_sink.flush_aged()
            g = fabric.poll_blocking(fabric_mod.GRADIENTS_TOPIC, 0,
                                     timeout=0.2)
            if g is not None:
                server.process(g)
    except KeyboardInterrupt:
        print(f"shard {shard_id}: interrupted — shutting down",
              file=sys.stderr, flush=True)
    finally:
        if producer is not None:
            producer.stop()
        if batch_sink is not None:
            batch_sink.flush_all()
        bridge.close()
        if checkpoint_path:
            # commit point: checkpoint + committed log offsets describe
            # the same instant (ServerNode.save_checkpoint_now commits
            # a durable fabric's offsets after the save)
            server.save_checkpoint_now()
        if tier_store is not None:
            tier_store.close()   # after the save: it may fault cold pages
        if getattr(inner, "durable", False):
            inner.close()
        if reroute["dropped"] or bridge.dropped_sends:
            print(f"shard {shard_id}: dropped rows "
                  f"{reroute['dropped']}, dropped sends "
                  f"{bridge.dropped_sends}", file=sys.stderr, flush=True)
        ops.close()
        if drift_sink is not None:
            drift_sink.close()
        _dump_telemetry(args, tracer, telemetry)
    return 0


# -- hierarchical aggregation tier (kafka_ps_tpu/agg/) -----------------------

def run_aggregator(args) -> int:
    """Aggregator-relay role (docs/AGGREGATION.md): one per host,
    between that host's worker processes and the server.

        # the relay: HELLOs upstream as aggregator for workers 0-3,
        # listens for those worker processes downstream
        python -m kafka_ps_tpu.cli.agg_runner --connect hostA:8477 \\
            --listen 8478 --agg-id 0 --worker_ids 0,1,2,3

        # each member worker dials the RELAY, not the server
        python -m kafka_ps_tpu.cli.worker_runner --aggregate host:8478 \\
            --worker_ids 0 -test test.csv

    The server sees ONE connection, one composite gradient frame per
    (host, flush) and one grouped weights frame per release set —
    fan-in collapses from O(workers) to O(hosts).  The relay holds no
    durable protocol state (workers buffer-and-resend, the server gate
    deduplicates); with --compress it owns the error-feedback
    residuals, persisted via --checkpoint so a SIGKILL keeps the
    compressed path bitwise-pinned."""
    from kafka_ps_tpu.agg.relay import AggregatorRelay
    from kafka_ps_tpu.models.task import get_task

    connect = getattr(args, "connect", None)
    if not connect:
        raise SystemExit("aggregator role requires --connect HOST:PORT "
                         "(the upstream server)")
    host, _, port = connect.rpartition(":")
    ids = [int(w) for w in args.worker_ids.split(",")]
    cfg = _make_cfg(args)
    num_params = get_task(cfg.task, cfg.model).num_params
    tracer, telemetry = _make_telemetry(args)
    ops = _make_ops(args, telemetry, role="aggregator")
    ops.start()
    spec = _codec_spec(args)
    relay = AggregatorRelay(
        int(getattr(args, "agg_id", 0) or 0),
        host or "127.0.0.1", int(port), ids, num_params,
        listen_port=int(getattr(args, "listen", 0) or 0),
        codec_spec=spec if spec.codec_id != net.CODEC_NONE else None,
        summed=bool(getattr(args, "summed", False)),
        checkpoint_path=getattr(args, "checkpoint", None),
        flush_interval=float(getattr(args, "flush_interval", 0.002)
                             or 0.002),
        heartbeat_interval=1.0,
        heartbeat_timeout=getattr(args, "heartbeat_timeout", None),
        tracer=tracer, telemetry=telemetry,
        coalesce=getattr(args, "wire_coalesce", True))
    if relay.restored:
        print("restored aggregator error-feedback residuals",
              file=sys.stderr, flush=True)
    print(f"aggregator {relay.agg_id} listening on port {relay.port} "
          f"(members {','.join(map(str, ids))}, upstream {connect})",
          file=sys.stderr, flush=True)
    try:
        relay.run()               # until the server closes the run
    except KeyboardInterrupt:
        pass
    finally:
        relay.close()
        ops.close()
        _dump_telemetry(args, tracer, telemetry)
    return 0


class _AssemblerSink:
    """Per-bridge weights sink (net.WorkerBridge.set_weights_sink):
    feeds one shard's weights slices into the shared WeightsAssembler
    under a lock — N reader threads offer concurrently, and assembly
    state must mutate atomically per slice."""

    def __init__(self, shard_id: int, assembler, lock):
        self._shard_id = shard_id
        self._assembler = assembler
        self._lock = lock

    def send(self, topic: str, key: int, message) -> None:
        with self._lock:
            self._assembler.offer(self._shard_id, key, message)


def _run_worker_sharded(args, addrs: list[str],
                        aggregate: bool = False) -> int:
    """Worker role against a `--shards N` server fleet: one bridge per
    shard address (in shard-id order), a ShardRouter per logical worker
    splitting each delta into per-shard slices, and a WeightsAssembler
    reassembling per-shard weights slices into the one full-range
    message the WorkerNodes train on.

    A dead bridge is NOT fatal while any other shard is alive: the
    supervisor reconnects to the restarted shard process, and the
    router's redelivery cache resends the gradient slices the dead
    shard missed (bitwise — never recomputed).  The run ends when every
    shard has closed its connection (servers reached max iterations).

    `aggregate=True` (--aggregate, docs/AGGREGATION.md) points the one
    address at a per-host aggregator relay instead of a shard server.
    Same machinery, two differences: compression is delegated (raw f32
    to the relay, which owns the error-feedback residuals), and a
    reconnect resends the router's WHOLE cache — the relay is
    stateless, so unlike a checkpoint-restored shard nothing on the
    other side knows to ask for the deltas that died with it."""
    from kafka_ps_tpu.cli.run import load_test_csv
    from kafka_ps_tpu.data.buffer import SlidingBuffer
    from kafka_ps_tpu.models.task import get_task
    from kafka_ps_tpu.runtime.sharding import ShardPlan, ShardRouter, \
        WeightsAssembler
    from kafka_ps_tpu.runtime.worker import WorkerNode
    from kafka_ps_tpu.utils.csvlog import CsvLogSink, WORKER_HEADER

    ids = [int(w) for w in args.worker_ids.split(",")]
    cfg = _make_cfg(args)
    test_x, test_y = load_test_csv(args.test_data_file_path,
                                   args.num_features)
    num_params = get_task(cfg.task, cfg.model).num_params
    plan = ShardPlan(num_params, len(addrs))
    tracer, telemetry = _make_telemetry(args)
    # per-process model-health plane (--model-health): the sharded
    # worker watches its local training stream just like run_worker
    from kafka_ps_tpu.telemetry.registry import model_name
    modelhealth, drift_sink = _make_modelhealth(
        args, telemetry, num_features=cfg.model.num_features,
        model=model_name(cfg.consistency_model),
        log_name="./logs-drift-worker.csv")
    # meta names the FULL shard roster: the postmortem analyzer's
    # dead-shard detection is (known shards) - (shards that dumped),
    # and the worker's dump is what survives when a shard is SIGKILL'd
    ops = _make_ops(args, telemetry, role="worker",
                    meta={"shards": list(range(len(addrs)))},
                    modelhealth=modelhealth)
    ops.start()

    def connect(addr: str, timeout: float = 30.0):
        host, _, port = addr.rpartition(":")
        return net.WorkerBridge(host or "127.0.0.1", int(port), ids,
                                connect_timeout=timeout,
                                heartbeat_timeout=getattr(
                                    args, "heartbeat_timeout", None),
                                tracer=tracer, telemetry=telemetry,
                                coalesce=getattr(
                                    args, "wire_coalesce", True))

    slots: list = [connect(a) for a in addrs]

    fabric = fabric_mod.Fabric()        # local: assembled WEIGHTS only
    assemble_lock = OrderedLock("ShardedWorker.assemble")
    routers: dict[int, ShardRouter] = {}

    def resend_cb(shard_id: int, worker: int, clock: int) -> bool:
        router = routers.get(worker)
        return router.resend(shard_id, clock) if router else False

    assembler = WeightsAssembler(
        plan,
        deliver=lambda w, m: fabric.send(fabric_mod.WEIGHTS_TOPIC, w, m),
        resend=resend_cb)
    sinks = [_AssemblerSink(i, assembler, assemble_lock)
             for i in range(len(addrs))]
    for i, b in enumerate(slots):
        b.set_weights_sink(sinks[i])

    def safe_send(shard_id: int, message) -> None:
        # a slice to a crashed shard is dropped here and recovered by
        # the redelivery protocol once the shard is back (the router
        # cache holds it; the shard's stale weights slice triggers the
        # resend) — the worker must not die on a shard's crash
        try:
            slots[shard_id].send_gradients(0, message)
        except (ConnectionError, OSError):
            pass

    for w in ids:
        routers[w] = ShardRouter(plan, send=safe_send)

    compressors = None
    spec = _codec_spec(args)
    if spec.codec_id != net.CODEC_NONE:
        if aggregate:
            # the relay owns the error-feedback residuals and encodes
            # ONCE at the aggregator→server edge (agg/core.py);
            # encoding here too would quantize the signal twice
            print(f"compression: {spec.name} (delegated to aggregator)",
                  file=sys.stderr, flush=True)
        else:
            # no per-connection negotiation in the sharded fleet:
            # slices cross the wire DECODED (dense tid-1 / sparse
            # tid-6 frames), so --compress here is the local gradient
            # sparsifier — topk is what makes a delta touch few shards
            # (docs/SHARDING.md)
            from kafka_ps_tpu import compress
            codec = compress.get_codec(spec, num_params)
            compressors = {w: compress.ErrorFeedback(codec)
                           for w in ids}
            print(f"compression: {spec.name} (local sparsifier)",
                  file=sys.stderr, flush=True)

    buffers = {w: SlidingBuffer(cfg.model.num_features, cfg.buffer,
                                telemetry=telemetry, worker=w)
               for w in ids}

    # worker-local durable state, exactly as in run_worker: a member
    # process restarted WITHIN a run recovers its training window
    # instead of cold-starting an empty buffer.  Run continuity is
    # keyed on slots[0]'s advertised run id — one relay in aggregate
    # mode; in sharded mode shard 0 stands in for the fleet (per-shard
    # run ids are independent, so cross-restart state is best-effort
    # there).
    run_id = slots[0].server_run_id
    state_path = None
    restoring = False
    if getattr(args, "checkpoint", None):
        from kafka_ps_tpu.utils import checkpoint as ckpt
        state_path = ckpt.worker_state_path(args.checkpoint, ids)
        stored = ckpt.peek_run_id(state_path)
        restoring = stored is not None and stored == run_id
        if not restoring and os.path.exists(state_path):
            print(f"discarding stale worker state {state_path} "
                  f"(run {stored} != server run {run_id})",
                  file=sys.stderr, flush=True)
            os.remove(state_path)
    if restoring:
        from kafka_ps_tpu.utils import checkpoint as ckpt
        if ckpt.maybe_restore_worker(state_path, buffers, run_id=run_id,
                                     residuals=compressors):
            print("restored worker buffers: " + ", ".join(
                f"{w}:{buffers[w].count} rows (seen "
                f"{buffers[w].num_tuples_seen})" for w in ids),
                file=sys.stderr, flush=True)

    # log continuity decided by RUN continuity, not by whether state
    # restored (same rule as run_worker): pre-crash rows belong to this
    # logical run even when the crash beat the first state snapshot
    log_path = "./logs-worker.csv" if args.logging else None
    append_log = restoring
    if log_path is not None:
        marker = log_path + ".runid"
        try:
            with open(marker) as fh:
                append_log = append_log or (
                    int(fh.read().strip()) == run_id)
        except (OSError, ValueError):
            pass
        with open(marker, "w") as fh:
            fh.write(str(run_id))
    log = CsvLogSink(log_path, WORKER_HEADER, append=append_log)
    from kafka_ps_tpu.utils.asynclog import DeferredSink
    worker_log = DeferredSink(log)
    nodes = {w: WorkerNode(w, cfg, fabric, buffers[w], test_x, test_y,
                           worker_log, tracer=tracer, telemetry=telemetry)
             for w in ids}
    for w in ids:
        nodes[w].shard_router = routers[w]
        if compressors is not None:
            nodes[w].compressor = compressors[w]
        if modelhealth is not None:
            nodes[w].modelhealth = modelhealth
            buffers[w].attach_drift(modelhealth.drift)

    if state_path is not None:
        from kafka_ps_tpu.utils import checkpoint as ckpt
        state_stop = threading.Event()

        state_every = getattr(args, "state_every", 1.0)
        if state_every is None or state_every <= 0:
            raise SystemExit("--state_every must be > 0 (seconds between "
                             "durable buffer snapshots)")

        def state_saver():
            # snapshot on the --state_every cadence; the fingerprint
            # covers insertions and iterations (run_worker's rule)
            last = None
            while not state_stop.wait(state_every):
                fp = (tuple(buffers[w].num_tuples_seen for w in ids),
                      tuple(nodes[w].iterations for w in ids))
                if fp != last:
                    ckpt.save_worker(state_path, buffers, run_id=run_id,
                                     residuals=compressors)
                    last = fp

        state_saver_thread = threading.Thread(
            target=state_saver, daemon=True, name="kps-worker-state")
        state_saver_thread.start()

    reader_threads: list[threading.Thread] = []

    def start_reader(bridge) -> None:
        t = threading.Thread(target=bridge.run_reader, args=(buffers,),
                             daemon=True, name="kps-worker-reader")
        t.start()
        reader_threads.append(t)

    for b in slots:
        start_reader(b)

    stop = threading.Event()
    ready_rows = max(1, int(getattr(args, "ready_rows", 1) or 1))

    def announce_ready() -> None:
        pending = {(i, w) for i in range(len(slots)) for w in ids}
        while pending and not stop.is_set():
            for i, w in list(pending):
                if buffers[w].count >= ready_rows:
                    try:
                        slots[i].mark_ready(w)
                    except (ConnectionError, OSError):
                        continue
                    pending.discard((i, w))
            time.sleep(0.01)

    ready_thread = threading.Thread(target=announce_ready, daemon=True,
                                    name="kps-worker-ready")
    ready_thread.start()

    # A dead aggregator relay is indistinguishable from end-of-run to
    # its members by the socket alone: both drop their ONLY connection.
    # They are told apart explicitly — a cleanly-closing relay sends the
    # GOODBYE config first (net.GOODBYE_RUN_ID, agg/relay.py), a
    # SIGKILL'd one sends nothing, so its members hold the run open for
    # this grace window and resend their caches once the restarted relay
    # answers.  Sharded mode keeps the simple rule: the run ends when
    # every shard has closed (shard servers recover from their own
    # durable logs; nothing is lost by stopping).
    AGG_RECONNECT_GRACE = 30.0
    down_since = [None]

    def fleet_is_done() -> bool:
        if not all(s.disconnected.is_set() for s in slots):
            down_since[0] = None
            return False
        if not aggregate or any(s.run_over for s in slots):
            return True
        if down_since[0] is None:
            down_since[0] = time.monotonic()
        return time.monotonic() - down_since[0] > AGG_RECONNECT_GRACE

    def supervise() -> None:
        # reconnect crashed shards/relays; end the run when the whole
        # fleet is gone for good (normal completion: every shard closes
        # at max iterations, a relay forwards the goodbye)
        while not stop.is_set():
            if fleet_is_done():
                stop.set()
                return
            for i in range(len(slots)):
                if not slots[i].disconnected.is_set():
                    continue
                try:
                    nb = connect(addrs[i], timeout=3.0)
                except (ConnectionError, OSError):
                    continue        # shard still down; retry next sweep
                nb.set_weights_sink(sinks[i])
                start_reader(nb)
                slots[i] = nb
                for w in ids:
                    if buffers[w].count >= ready_rows:
                        try:
                            nb.mark_ready(w)
                        except (ConnectionError, OSError):
                            pass
                if aggregate:
                    # buffer-and-resend (docs/AGGREGATION.md): the
                    # relay is stateless, so deltas it held died with
                    # it and NOTHING on the restarted side will ask
                    # for them (a shard server replays its durable
                    # log; a relay cannot).  Resend the whole cached
                    # tail unprompted — the server deduplicates what
                    # it already applied and its duplicate-liveness
                    # rule re-issues any weights reply that was lost
                    # in flight.
                    for w in ids:
                        routers[w].resend(i, 0)
                print(("reconnected to aggregator" if aggregate else
                       f"reconnected to shard {i}") + f" ({addrs[i]})",
                      file=sys.stderr, flush=True)
            time.sleep(0.2)

    supervisor = threading.Thread(target=supervise, daemon=True,
                                  name="kps-worker-supervisor")
    supervisor.start()

    errors: list[BaseException] = []

    def worker_loop(node: WorkerNode) -> None:
        try:
            while not stop.is_set():
                msg = fabric.poll_blocking(fabric_mod.WEIGHTS_TOPIC,
                                           node.worker_id, timeout=0.1)
                if msg is not None:
                    node.on_weights(msg)
        except BaseException as e:    # pragma: no cover - diagnostics
            errors.append(e)
            stop.set()

    threads = [threading.Thread(target=worker_loop, args=(nodes[w],),
                                daemon=True, name=f"worker-{w}")
               for w in ids]
    for t in threads:
        t.start()
    stop.wait()                       # supervisor ends the run
    leftover = []
    for t in threads:
        t.join(timeout=120.0)
        if t.is_alive():
            leftover.append(t.name)
    if state_path is not None:
        from kafka_ps_tpu.utils import checkpoint as ckpt
        state_stop.set()
        # join BEFORE the final save: two concurrent save_worker calls
        # share one tmp path and would corrupt the state file
        state_saver_thread.join(timeout=60.0)
        if state_saver_thread.is_alive():
            print("warning: state saver still writing; skipping final "
                  "snapshot", file=sys.stderr, flush=True)
            leftover.append(state_saver_thread.name)
        else:
            ckpt.save_worker(state_path, buffers, run_id=run_id,
                             residuals=compressors)
    worker_log.close()
    for b in slots:
        b.close()
    supervisor.join(timeout=10.0)
    ready_thread.join(timeout=10.0)
    for t in reader_threads:
        t.join(timeout=10.0)
    for t in [supervisor, ready_thread, *reader_threads]:
        if t.is_alive():
            leftover.append(t.name)
    ops.close()                  # before any os._exit: the flight dump
    if drift_sink is not None:
        drift_sink.close()
    _dump_telemetry(args, tracer, telemetry)
    rc = 0
    if errors:
        print(f"worker failed: {errors[0]!r}", file=sys.stderr, flush=True)
        rc = 1
    if leftover:
        print(f"warning: threads still alive at exit: {leftover}; "
              "exiting without finalization", file=sys.stderr, flush=True)
        sys.stdout.flush()
        os._exit(rc)
    if errors:
        raise RuntimeError("worker failed") from errors[0]
    return 0


# -- log-following read replicas (docs/SERVING.md) ---------------------------

def run_replica(args) -> int:
    """Read-replica serving process: follow `--durable-log DIR` and
    answer T_PREDICT frames, never touching the training deployment.

    The replica tails the log strictly read-only (log/tail.py), so it
    can run against a LIVE training process's directory: read load
    scales by starting more of these, and training is provably
    unperturbed (scripts/tier1.sh --load asserts bitwise-identical
    theta with and without replica traffic).  For a `--shards N`
    deployment the replica assembles per-shard slices through
    FrontierCutPublisher and serves the full-range theta stamped with
    the frontier clock — the serving story the live sharded runtime
    itself does not offer (run_server_shard rejects --serve).
    """
    from kafka_ps_tpu.models.task import get_task
    from kafka_ps_tpu.serving.engine import PredictionEngine
    from kafka_ps_tpu.serving.replica import ReplicaFollower
    from kafka_ps_tpu.serving.snapshot import SnapshotRegistry

    root = getattr(args, "durable_log", None)
    if not root:
        raise SystemExit("--serve-replica requires --durable-log DIR "
                         "(the training deployment's commit log to "
                         "follow)")
    cfg = _make_cfg(args)
    tracer, telemetry = _make_telemetry(args)
    task = get_task(cfg.task, cfg.model)
    registry = SnapshotRegistry(
        capacity=getattr(args, "serve_snapshots", 8))
    follower = ReplicaFollower(root, registry, tracer=tracer)
    shed_ms = getattr(args, "serve_shed_ms", 0.0)
    engine = PredictionEngine(
        task, registry,
        max_batch=getattr(args, "serve_batch", 16),
        deadline_s=getattr(args, "serve_deadline_ms", 2.0) / 1000.0,
        queue_limit=getattr(args, "serve_queue", 0),
        shed_deadline_s=shed_ms / 1000.0 if shed_ms else None,
        auto=getattr(args, "serve_auto", True),
        tracer=tracer, telemetry=telemetry)
    follower.catch_up()              # cold start: serve what's logged
    ops = _make_ops(args, telemetry, role="replica")
    ops.add_replica_watchdog()
    ops.add_serving_watchdog(engine)
    ops.start()
    port = getattr(args, "serve_port", None)
    bridge = net.ServerBridge(port=0 if port is None else port,
                              run_id=time.time_ns(), tracer=tracer,
                              telemetry=telemetry,
                              shm=getattr(args, "serve_shm", False),
                              coalesce=getattr(args, "wire_coalesce",
                                               True))
    bridge.attach_serving(engine)
    follower.start()
    mode = (f"{follower.num_shards}-shard assembled"
            if follower.num_shards else "single-server")
    print(f"replica serving on port {bridge.port} "
          f"({mode} log {root}, clock {follower.clock})",
          file=sys.stderr, flush=True)
    if engine.warmup():
        print(f"replica warm at clock {follower.clock}",
              file=sys.stderr, flush=True)
    else:
        # started against an empty log: warm (compile buckets +
        # calibrate the dispatch cost model) the moment theta appears
        warmed = threading.Event()

        def _warm_on_first_publish(clock, _e=warmed):
            if not _e.is_set() and engine.warmup():
                _e.set()
                print(f"replica warm at clock {clock}",
                      file=sys.stderr, flush=True)

        follower.on_publish = _warm_on_first_publish
    try:
        # serve until killed — a replica has no natural end of run;
        # deployment manifests (deploy/k8s/replica.yaml) scale and
        # reap these processes
        duration = getattr(args, "replica_duration", None)
        if duration:
            time.sleep(float(duration))
        else:
            while True:
                time.sleep(3600.0)
    except KeyboardInterrupt:
        pass
    finally:
        follower.stop()
        engine.close()
        bridge.close()
        ops.close()
        _dump_telemetry(args, tracer, telemetry)
    return 0
