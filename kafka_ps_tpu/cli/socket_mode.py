"""Split server/worker deployment over the socket transport — the
reference's ACTUAL process topology (one server JVM + worker JVMs
coupled through the broker, run.sh:10-18, kubernetes/*.yaml) for the
async consistency models.

    # host A — aggregator + consistency gate + stream producer
    python -m kafka_ps_tpu.cli.server_runner --listen 8477 \
        -c 10 -training train.csv -test test.csv --max_iterations 400 -l

    # host B (and C, ...) — the workers named by --worker_ids
    python -m kafka_ps_tpu.cli.worker_runner --connect hostA:8477 \
        --worker_ids 0,1,2,3 -test test.csv -l

WEIGHTS / GRADIENTS / INPUT_DATA cross the wire as binary serde frames
(runtime/net.py, runtime/serde.py) — ~24 KB per 6150-float model
message vs the reference's ~120 KB JSON.  The fused/BSP path scales via
jax.distributed instead (deploy/README.md); this mode exists so bounded
delay and eventual consistency have a real multi-host story too.
"""

from __future__ import annotations

import sys
import threading
import time

import numpy as np

from kafka_ps_tpu.runtime import fabric as fabric_mod
from kafka_ps_tpu.runtime import net


def _make_cfg(args):
    from kafka_ps_tpu.cli.run import apply_platform_env
    from kafka_ps_tpu.utils.config import (BufferConfig, ModelConfig,
                                           PSConfig, StreamConfig)
    apply_platform_env()
    if getattr(args, "eval_every", 1) < 1:
        raise SystemExit("--eval_every must be >= 1")
    return PSConfig(
        num_workers=args.num_workers,
        consistency_model=getattr(args, "consistency_model", 0),
        task=args.task,
        model=ModelConfig(num_features=args.num_features,
                          num_classes=args.num_classes,
                          num_max_iter=args.local_iterations,
                          local_learning_rate=args.local_learning_rate,
                          hidden_dim=args.hidden_dim),
        buffer=BufferConfig(
            min_size=getattr(args, "min_buffer_size", 128),
            max_size=getattr(args, "max_buffer_size", 1024),
            coefficient=getattr(args, "buffer_size_coefficient", 0.3)),
        stream=StreamConfig(time_per_event_ms=getattr(
            args, "producer_time_per_event", 200)),
        eval_every=getattr(args, "eval_every", 1),
        use_pallas=getattr(args, "pallas", False),
    )


def run_server(args) -> int:
    """Server role: ServerNode + producer, all workers remote."""
    from kafka_ps_tpu.cli.run import load_test_csv
    from kafka_ps_tpu.data.stream import CsvStreamProducer
    from kafka_ps_tpu.runtime.server import ServerNode
    from kafka_ps_tpu.utils.csvlog import CsvLogSink, SERVER_HEADER

    cfg = _make_cfg(args)
    test_x, test_y = load_test_csv(args.test_data_file_path,
                                   args.num_features)
    log = CsvLogSink("./logs-server.csv" if args.logging else None,
                     SERVER_HEADER)
    bridge = net.ServerBridge(port=args.listen)
    print(f"listening on port {bridge.port}", file=sys.stderr, flush=True)
    fabric = bridge.wrap(fabric_mod.Fabric())
    server = ServerNode(cfg, fabric, test_x, test_y, log)

    workers = list(range(cfg.num_workers))
    bridge.wait_for_connected(workers, timeout=args.connect_timeout)

    def sink(worker: int, features: dict[int, float], label: int) -> None:
        bridge.send_data(worker, features, label)

    producer = CsvStreamProducer(
        args.training_data_file_path, cfg.num_workers, sink,
        time_per_event_ms=cfg.stream.time_per_event_ms,
        prefill_per_worker=cfg.stream.prefill_per_worker)
    producer.run_in_background()
    bridge.wait_for_workers(workers, timeout=args.connect_timeout)

    server.start_training_loop()
    max_iters = args.max_iterations or sys.maxsize
    try:
        while server.iterations < max_iters:
            g = fabric.poll_blocking(fabric_mod.GRADIENTS_TOPIC, 0,
                                     timeout=0.2)
            if g is not None:
                server.process(g)
    finally:
        bridge.close()       # workers see EOF and shut down
        log.close()
    return 0


def run_worker(args) -> int:
    """Worker role: the logical workers in --worker_ids, server remote."""
    from kafka_ps_tpu.cli.run import load_test_csv
    from kafka_ps_tpu.data.buffer import SlidingBuffer
    from kafka_ps_tpu.runtime.worker import WorkerNode
    from kafka_ps_tpu.utils.csvlog import CsvLogSink, WORKER_HEADER

    host, _, port = args.connect.rpartition(":")
    ids = [int(w) for w in args.worker_ids.split(",")]
    cfg = _make_cfg(args)
    test_x, test_y = load_test_csv(args.test_data_file_path,
                                   args.num_features)
    log = CsvLogSink("./logs-worker.csv" if args.logging else None,
                     WORKER_HEADER)

    bridge = net.WorkerBridge(host or "127.0.0.1", int(port), ids)
    fabric = bridge.make_fabric()
    buffers = {w: SlidingBuffer(cfg.model.num_features, cfg.buffer)
               for w in ids}
    nodes = {w: WorkerNode(w, cfg, fabric, buffers[w], test_x, test_y, log)
             for w in ids}

    threading.Thread(target=bridge.run_reader, args=(buffers,),
                     daemon=True, name="kps-worker-reader").start()

    # READY per worker once its buffer has data (the server gates the
    # training-loop bootstrap on this, net.ServerBridge.wait_for_workers)
    def announce_ready():
        pending = set(ids)
        while pending and not bridge.disconnected.is_set():
            for w in list(pending):
                if buffers[w].count > 0:
                    bridge.mark_ready(w)
                    pending.discard(w)
            time.sleep(0.01)

    threading.Thread(target=announce_ready, daemon=True).start()

    stop = threading.Event()
    errors: list[BaseException] = []

    def worker_loop(node: WorkerNode):
        try:
            while not stop.is_set():
                msg = fabric.poll_blocking(fabric_mod.WEIGHTS_TOPIC,
                                           node.worker_id, timeout=0.1)
                if msg is not None:
                    node.on_weights(msg)
        except (ConnectionError, OSError):
            pass                      # server hung up mid-send
        except BaseException as e:    # pragma: no cover - diagnostics
            errors.append(e)
            stop.set()

    threads = [threading.Thread(target=worker_loop, args=(nodes[w],),
                                daemon=True, name=f"worker-{w}")
               for w in ids]
    for t in threads:
        t.start()
    bridge.disconnected.wait()        # run until the server closes
    stop.set()
    for t in threads:
        t.join(timeout=5.0)
    log.close()
    bridge.close()
    if errors:
        raise RuntimeError("worker failed") from errors[0]
    return 0
