"""Server-role entry point — flag parity with the reference's
ServerAppRunner (ServerAppRunner.java:14-104: -training -test -c -p
-v -h -r -l, same defaults).

The reference runs server and workers as separate JVMs coupled through
Kafka; on TPU one host process owns all devices, so this runner hosts
the complete system (producer + server + logical workers) with the
worker-side knobs at their reference defaults.  Use cli/run.py for the
full flag surface.
"""

from __future__ import annotations

import argparse

from kafka_ps_tpu.cli import run as run_mod


def build_parser() -> argparse.ArgumentParser:
    """The server-role flag surface (also validated against the
    deployment manifests in tests/test_deploy.py)."""
    parser = run_mod.build_parser(include_server_flags=True,
                                  include_worker_flags=False,
                                  prog="ServerAppRunner")
    parser.add_argument(
        "--listen", type=int, default=None, metavar="PORT",
        help="split deployment: host ONLY the server (aggregator + "
             "consistency gate + producer) and serve remote worker "
             "processes over the socket transport (cli/socket_mode.py; "
             "0 = ephemeral port, printed to stderr) — the reference's "
             "separate-server-JVM topology (run.sh:15-18)")
    parser.add_argument("--connect_timeout", type=float, default=60.0,
                        help="--listen: seconds to wait for all workers")
    parser.add_argument(
        "--shards", type=int, default=1, metavar="N",
        help="--listen: total server shards of a range-sharded "
             "deployment (docs/SHARDING.md) — run N of these processes, "
             "one per --shard-id, each owning a contiguous key range of "
             "theta with its own consistency gate, checkpoint file and "
             "durable-log partition; workers --connect to all N")
    parser.add_argument(
        "--shard-id", dest="shard_id", type=int, default=0, metavar="I",
        help="--shards: this process's shard index in [0, N) — shard 0 "
             "additionally hosts the stream producer")
    parser.add_argument(
        "--bsp-order", dest="bsp_order", action="store_true",
        help="--listen + -c 0: buffer each BSP round and apply it in "
             "worker-id order (docs/AGGREGATION.md) — float addition "
             "is order-sensitive, so this is the determinism knob that "
             "makes an aggregated run bitwise-comparable to a direct "
             "one (scripts/tier1.sh --agg)")
    parser.add_argument(
        "--serve-replica", dest="serve_replica", action="store_true",
        help="read-replica serving process (docs/SERVING.md): follow "
             "--durable-log DIR strictly read-only and answer T_PREDICT "
             "frames on --serve_port, never joining the training "
             "fabric.  Works against a live single-server log or a "
             "--shards N deployment's per-shard logs (the replica "
             "assembles the full-range theta stamped with the frontier "
             "clock).  Scale reads by running more of these "
             "(deploy/k8s/replica.yaml)")
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    # worker-side defaults (WorkerAppRunner.java:55-58)
    args = argparse.Namespace(min_buffer_size=128, max_buffer_size=1024,
                              buffer_size_coefficient=0.3, **vars(args))
    if args.shards < 1 or not 0 <= args.shard_id < args.shards:
        raise SystemExit(
            f"--shard-id {args.shard_id} must be in [0, --shards "
            f"{args.shards}) and --shards must be >= 1")
    if args.shards > 1 and args.listen is None:
        raise SystemExit("--shards N > 1 requires --listen (one shard "
                         "server process per port, docs/SHARDING.md); "
                         "in-process sharding is the "
                         "runtime.sharding.ShardedServerGroup API")
    if getattr(args, "serve_replica", False):
        if args.listen is not None:
            raise SystemExit("--serve-replica is a standalone serving "
                             "process; drop --listen (the replica only "
                             "follows --durable-log, it never hosts the "
                             "training fabric)")
        from kafka_ps_tpu.cli import socket_mode
        return socket_mode.run_replica(args)
    if args.listen is not None:
        if args.shards > 1:
            # sharded split mode OWNS a durable-log story: one commit-
            # log partition per shard process, replayed on restart —
            # the SIGKILL-recovery path (scripts/tier1.sh --shard)
            from kafka_ps_tpu.cli import socket_mode
            return socket_mode.run_server_shard(args)
        if getattr(args, "durable_log", None):
            # the socket split already has its own durability story
            # (--checkpoint + per-worker state files, cli/socket_mode);
            # the commit log is the in-process fabric's
            raise SystemExit(
                "--durable-log applies to the in-process fabric; in "
                "--listen split mode use --checkpoint instead (or "
                "--shards N > 1, whose shard processes each own a "
                "durable-log partition)")
        from kafka_ps_tpu.cli import socket_mode
        return socket_mode.run_server(args)
    return run_mod.run_with_args(args)


if __name__ == "__main__":
    raise SystemExit(main())
