"""Server-role entry point — flag parity with the reference's
ServerAppRunner (ServerAppRunner.java:14-104: -training -test -c -p
-v -h -r -l, same defaults).

The reference runs server and workers as separate JVMs coupled through
Kafka; on TPU one host process owns all devices, so this runner hosts
the complete system (producer + server + logical workers) with the
worker-side knobs at their reference defaults.  Use cli/run.py for the
full flag surface.
"""

from __future__ import annotations

import argparse

from kafka_ps_tpu.cli import run as run_mod


def main(argv=None) -> int:
    parser = run_mod.build_parser(include_server_flags=True,
                                  include_worker_flags=False,
                                  prog="ServerAppRunner")
    args = parser.parse_args(argv)
    # worker-side defaults (WorkerAppRunner.java:55-58)
    args = argparse.Namespace(min_buffer_size=128, max_buffer_size=1024,
                              buffer_size_coefficient=0.3, **vars(args))
    return run_mod.run_with_args(args)


if __name__ == "__main__":
    raise SystemExit(main())
