"""Server-role entry point — flag parity with the reference's
ServerAppRunner (ServerAppRunner.java:14-104: -training -test -c -p
-v -h -r -l, same defaults).

The reference runs server and workers as separate JVMs coupled through
Kafka; on TPU one host process owns all devices, so this runner hosts
the complete system (producer + server + logical workers) with the
worker-side knobs at their reference defaults.  Use cli/run.py for the
full flag surface.
"""

from __future__ import annotations

import argparse

from kafka_ps_tpu.cli import run as run_mod


def build_parser() -> argparse.ArgumentParser:
    """The server-role flag surface (also validated against the
    deployment manifests in tests/test_deploy.py)."""
    parser = run_mod.build_parser(include_server_flags=True,
                                  include_worker_flags=False,
                                  prog="ServerAppRunner")
    parser.add_argument(
        "--listen", type=int, default=None, metavar="PORT",
        help="split deployment: host ONLY the server (aggregator + "
             "consistency gate + producer) and serve remote worker "
             "processes over the socket transport (cli/socket_mode.py; "
             "0 = ephemeral port, printed to stderr) — the reference's "
             "separate-server-JVM topology (run.sh:15-18)")
    parser.add_argument("--connect_timeout", type=float, default=60.0,
                        help="--listen: seconds to wait for all workers")
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    # worker-side defaults (WorkerAppRunner.java:55-58)
    args = argparse.Namespace(min_buffer_size=128, max_buffer_size=1024,
                              buffer_size_coefficient=0.3, **vars(args))
    if args.listen is not None:
        if getattr(args, "durable_log", None):
            # the socket split already has its own durability story
            # (--checkpoint + per-worker state files, cli/socket_mode);
            # the commit log is the in-process fabric's
            raise SystemExit(
                "--durable-log applies to the in-process fabric; in "
                "--listen split mode use --checkpoint instead")
        from kafka_ps_tpu.cli import socket_mode
        return socket_mode.run_server(args)
    return run_mod.run_with_args(args)


if __name__ == "__main__":
    raise SystemExit(main())
