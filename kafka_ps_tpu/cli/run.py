"""Canonical CLI — the whole streaming PS system in one process.

The reference splits server and worker into two JVMs because Kafka is
the transport (run.sh:10-18); on TPU one host process owns every device,
so this runner hosts producer + server + N logical workers together.
`cli/server_runner.py` and `cli/worker_runner.py` keep the reference's
per-role flag surfaces and delegate here.

Flags are the union of ServerAppRunner.java:19-26 and
WorkerAppRunner.java:17-24, same names and defaults; TPU-native extras
are prefixed with `--`-only long names.
"""

from __future__ import annotations

import argparse
import os
import sys



def build_parser(include_server_flags: bool = True,
                 include_worker_flags: bool = True,
                 prog: str = "kafka_ps_tpu") -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog=prog, description="TPU-native streaming parameter server")
    if include_server_flags:
        p.add_argument("-training", "--training_data_file_path",
                       default="./data/train.csv",
                       help="path to the training-data CSV "
                            "(BaseKafkaApp.java:35)")
        p.add_argument("-c", "--consistency_model", type=int, default=0,
                       help="0 sequential, k>0 bounded delay, -1 eventual")
        p.add_argument("-p", "--producer_time_per_event", type=int,
                       default=200, help="ms per produced event")
    if include_worker_flags:
        p.add_argument("-min", "--min_buffer_size", type=int, default=128)
        p.add_argument("-max", "--max_buffer_size", type=int, default=1024)
        p.add_argument("-bc", "--buffer_size_coefficient", type=float,
                       default=0.3)
    p.add_argument("-test", "--test_data_file_path",
                   default="./data/test.csv",
                   help="path to the test-data CSV (BaseKafkaApp.java:36)")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="print the parameters that are used")
    p.add_argument("-r", "--remote", action="store_true",
                   help="distributed mode: join the multi-host job "
                        "(parallel/multihost.py; KPS_* env vars) and run "
                        "the fused BSP step over the global device mesh — "
                        "the reference's remote-Kafka-broker role "
                        "(ServerAppRunner.java:63)")
    p.add_argument("-l", "--logging", action="store_true",
                   help="write performance logs to ./logs-server.csv / "
                        "./logs-worker.csv instead of stdout")
    # TPU-native extras
    p.add_argument("--num_workers", type=int, default=4,
                   help="logical workers (reference hardcodes 4, "
                        "BaseKafkaApp.java:25)")
    p.add_argument("--num_features", type=int, default=1024)
    p.add_argument("--num_classes", type=int, default=5)
    p.add_argument("--task", choices=["logreg", "mlp"], default="logreg",
                   help="model family (models/task.py registry); logreg "
                        "is the reference's task")
    p.add_argument("--hidden_dim", type=int, default=128,
                   help="hidden width of the mlp task")
    p.add_argument("--local_iterations", type=int, default=2,
                   help="k local solver steps per iteration "
                        "(numMaxIter, LogisticRegressionTaskSpark.java:35)")
    p.add_argument("--local_learning_rate", type=float, default=0.5)
    p.add_argument("--eval_every", type=int, default=1,
                   help="evaluate test metrics every Nth vector clock "
                        "(1 = the reference's every-iteration cadence, "
                        "LogisticRegressionTaskSpark.java:186; larger "
                        "values trade metric resolution for throughput "
                        "— eval dominates per-node wall-clock)")
    p.add_argument("--eval-async", dest="eval_async", action="store_true",
                   default=True,
                   help="async coalescing eval engine (default ON, "
                        "evaluation/engine.py): test-set evaluation "
                        "leaves the server's apply critical path — a "
                        "dedicated thread coalesces pending (theta, "
                        "clock) snapshots into batched vmap dispatches "
                        "and emits the SAME CSV rows in clock order "
                        "(bitwise-identical to the fused path, "
                        "docs/EVALUATION.md)")
    p.add_argument("--no-eval-async", dest="eval_async",
                   action="store_false",
                   help="fuse evaluation back into the apply dispatch "
                        "(the pre-engine behaviour; the A/B lever "
                        "bench.py eval_ab measures)")
    p.add_argument("--max_iterations", type=int, default=0,
                   help="stop after this many server iterations "
                        "(0 = run until Ctrl-C, like the reference)")
    p.add_argument("--fused", action="store_true",
                   help="sequential model as fused shard_map steps "
                        "(TPU fast path)")
    p.add_argument("--param_shards", type=int, default=1,
                   help="with --fused: shard the parameter vector over "
                        "this many devices (2-D workers x params mesh — "
                        "the reference's latent KeyRange axis, "
                        "messages/KeyRange.java, parallel/range_sharded.py)")
    p.add_argument("--status_every", type=float, default=0.0,
                   metavar="SECONDS",
                   help="emit a [status] line to stderr every N seconds "
                        "(iters/s, per-worker clocks, membership, queue "
                        "depths, buffer fill) — the live-observability "
                        "stand-in for the reference's Confluent Control "
                        "Center UI (utils/status.py; 0 = off)")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="write a Chrome trace-event JSON (spans + message "
                        "counters) on exit and print span stats — replaces "
                        "the reference's Confluent monitoring interceptors")
    p.add_argument("--metrics-file", dest="metrics_file", default=None,
                   metavar="PATH",
                   help="enable the metrics registry "
                        "(kafka_ps_tpu/telemetry/) and write a "
                        "Prometheus-style text dump of every counter/"
                        "gauge/histogram family to PATH at exit (and "
                        "every --metrics-every seconds); also folds a "
                        "flat metrics summary into each [status] line")
    p.add_argument("--metrics-every", dest="metrics_every", type=float,
                   default=0.0, metavar="SECONDS",
                   help="with --metrics-file: rewrite the dump every N "
                        "seconds (atomic replace; 0 = only at exit)")
    p.add_argument("--flight-dir", dest="flight_dir", default=None,
                   metavar="DIR",
                   help="enable the always-on flight recorder "
                        "(telemetry/flight.py, docs/OBSERVABILITY.md): "
                        "per-thread rings of structured events (gate "
                        "decisions, queue depths, frame sends, fsyncs, "
                        "snapshot publishes) dumped atomically to "
                        "DIR/flightdump-<pid>.json on SIGTERM/SIGABRT/"
                        "fatal signals, on watchdog trips, and at clean "
                        "exit; `python -m kafka_ps_tpu.telemetry "
                        "postmortem DIR` merges dumps across processes "
                        "and names the culprit")
    p.add_argument("--health-port", dest="health_port", type=int,
                   default=None, metavar="PORT",
                   help="serve the health/introspection plane on this "
                        "port (0 = ephemeral, printed to stderr): "
                        "/healthz watchdog-derived liveness/readiness "
                        "(the k8s probe target, deploy/k8s/*.yaml), "
                        "/varz Prometheus metrics snapshot, /flightz "
                        "recent flight-ring tail, /profilez collapsed "
                        "stacks when --profile is armed")
    p.add_argument("--profile", action="store_true",
                   help="arm the continuous sampling profiler "
                        "(telemetry/profiler.py, ~100 Hz stdlib stack "
                        "sampler, docs/OBSERVABILITY.md): collapsed-"
                        "stack text on /profilez (--health-port) and "
                        "the hottest stacks in every flight dump, so a "
                        "watchdog trip ships its own profile; <2%% "
                        "overhead asserted by the profiling_overhead "
                        "bench block")
    p.add_argument("--slo-serving-p99-ms", dest="slo_serving_p99_ms",
                   type=float, default=None, metavar="MS",
                   help="arm the SLO plane (telemetry/slo.py) with a "
                        "serving-latency objective: 99%% of requests "
                        "answered within MS.  Burn rates over 5min/1h "
                        "windows export as slo_burn_rate gauges, ride "
                        "/healthz, and a sustained fast-window burn "
                        "trips a flight dump (serving availability is "
                        "always tracked once any --slo-* flag is set)")
    p.add_argument("--slo-freshness-ms", dest="slo_freshness_ms",
                   type=float, default=None, metavar="MS",
                   help="arm the SLO plane with a snapshot-freshness "
                        "objective: 99%% of served reads see a snapshot "
                        "younger than MS (snapshot_age_ms histogram; "
                        "same burn-rate windows and watchdog as "
                        "--slo-serving-p99-ms)")
    p.add_argument("--model-health", dest="model_health",
                   action="store_true",
                   help="arm the model-health plane (telemetry/"
                        "modelhealth.py, docs/OBSERVABILITY.md): per-"
                        "update delta norms + aggregate-direction "
                        "cosine + per-worker contribution accounting, "
                        "plus online drift detection over the streaming "
                        "eval metrics and sampled arrivals (telemetry/"
                        "drift.py).  Surfaces on /modelz, the [status] "
                        "heartbeat, and a latched DRIFT ships one "
                        "flight dump; <2%% overhead asserted by the "
                        "modelhealth_overhead bench block")
    p.add_argument("--drift-detector", dest="drift_detector",
                   choices=["ph", "adwin"], default="ph",
                   help="drift detector for --model-health: ph (Page-"
                        "Hinkley, directional mean-shift, the default) "
                        "or adwin (windowed adaptive cut, shift-"
                        "direction agnostic)")
    p.add_argument("--drift-threshold", dest="drift_threshold",
                   type=float, default=None, metavar="T",
                   help="detector trip threshold override (default: "
                        "the detector's own calibration; ph "
                        "statistic > T trips, adwin gap/bound > T)")
    p.add_argument("--device_trace", default=None, metavar="LOGDIR",
                   help="capture a jax.profiler device trace (TensorBoard "
                        "logdir) for the whole run")
    p.add_argument("--pallas", action="store_true",
                   help="use the Pallas fused local-update kernel for "
                        "worker iterations — logreg and mlp families "
                        "(ops/fused_update.py; auto-falls-back off-TPU "
                        "or past the VMEM budget)")
    p.add_argument("--compress", default="none", metavar="CODEC",
                   help="compressed delta transport "
                        "(kafka_ps_tpu/compress/, docs/COMPRESSION.md): "
                        "none | bf16 | int8 | topk:<ratio>.  Applied "
                        "symmetrically — server->worker weights are "
                        "quantize-dequantized, worker->server deltas go "
                        "through per-worker error-feedback residuals.  "
                        "In socket mode both processes must name the "
                        "same codec (negotiated on HELLO; mismatches "
                        "fall back to none).  Incompatible with --fused")
    p.add_argument("--slab-dtype", dest="slab_dtype",
                   choices=["f32", "bf16", "int8"], default="f32",
                   help="storage precision of each worker's "
                        "device-resident training slab (compress/slab.py, "
                        "docs/PERFORMANCE.md): bf16 halves and int8 "
                        "(per-row max-abs scales) quarters the bytes the "
                        "training step streams from HBM; decode is fused "
                        "into the solver.  f32 is bitwise-identical to a "
                        "build without the flag.  Incompatible with "
                        "--fused (its BSP step keeps its own slab cache)")
    p.add_argument("--full-slab-upload", action="store_true",
                   dest="full_slab_upload",
                   help="disable incremental device-slab updates: "
                        "re-upload the whole slab whenever the buffer "
                        "changes instead of scattering only dirty rows "
                        "(the pre-PERFORMANCE.md behavior; the A/B lever "
                        "behind the slab_ab bench block)")
    p.add_argument("--tier-hot-bytes", dest="tier_hot_bytes", type=int,
                   default=0, metavar="BYTES",
                   help="tiered parameter residency (kafka_ps_tpu/store/, "
                        "docs/TIERING.md): cap the device-resident (hot) "
                        "tier of the server's parameter vector at BYTES; "
                        "overflow pages live in pinned host RAM (warm).  "
                        "0 = unbounded, today's fully-resident behavior.  "
                        "Capped runs stay bitwise-identical — they only "
                        "bound resident bytes.  Per process; split evenly "
                        "across in-process shards.  Incompatible with "
                        "--fused")
    p.add_argument("--tier-warm-bytes", dest="tier_warm_bytes", type=int,
                   default=0, metavar="BYTES",
                   help="cap the host-RAM (warm) tier at BYTES; overflow "
                        "pages demote to CRC-framed records in the commit "
                        "log and fault back in on demand — requires "
                        "--durable-log (the cold partition lives under "
                        "it).  0 = unbounded")
    p.add_argument("--tier-page-params", dest="tier_page_params", type=int,
                   default=1024, metavar="KEYS",
                   help="keys per residency page (the promotion/demotion "
                        "unit; must match across checkpoint resumes)")
    p.add_argument("--no-gang", action="store_true", dest="no_gang",
                   help="disable gang-scheduled dispatch: process every "
                        "gate release as its own device step instead of "
                        "coalescing simultaneous releases into one "
                        "batched step (runtime/gang.py, "
                        "docs/GANG_DISPATCH.md)")
    p.add_argument("--failure_policy", choices=["halt", "rebalance"],
                   default="halt",
                   help="threaded mode: evict crashed/hung workers and "
                        "continue on the survivors (rebalance), or stop "
                        "the run (halt)")
    p.add_argument("--heartbeat_timeout", type=float, default=None,
                   help="threaded+rebalance: seconds without worker "
                        "progress (with work pending) before eviction")
    p.add_argument("--mode", choices=["threaded", "serial"],
                   default="threaded")
    p.add_argument("--checkpoint", default=None,
                   help="path to save/restore parameters "
                        "(improvement over the reference's cold start)")
    p.add_argument("--checkpoint_every", type=int, default=50,
                   help="server iterations between checkpoint saves")
    p.add_argument("--durable-log", dest="durable_log", default=None,
                   metavar="DIR",
                   help="persist every WEIGHTS/GRADIENTS/INPUT_DATA "
                        "message to a segmented commit log under DIR "
                        "(kafka_ps_tpu/log/ — the reference's Kafka "
                        "broker durability); on restart the run replays "
                        "the unconsumed tail past the last checkpoint's "
                        "committed offsets (docs/DURABILITY.md)")
    p.add_argument("--fsync", choices=["none", "interval", "always"],
                   default="interval",
                   help="--durable-log fsync policy: page-cache only / "
                        "at most once per second / every append "
                        "(log/log.py)")
    # -- online serving plane (kafka_ps_tpu/serving/, docs/SERVING.md) --
    p.add_argument("--serve", action="store_true",
                   help="serve predictions while training: the server "
                        "publishes a weights snapshot at every "
                        "consistency-gate release and a micro-batching "
                        "engine answers staleness-bounded reads against "
                        "the newest one (never blocks training)")
    p.add_argument("--serve_port", type=int, default=None, metavar="PORT",
                   help="with --serve: also accept T_PREDICT frames on "
                        "this TCP port (0 = ephemeral; the bound port is "
                        "printed to stderr).  Omit for in-process-only "
                        "serving")
    p.add_argument("--serve_batch", type=int, default=16,
                   help="serving micro-batch size cap (one jit shape; "
                        "the gang-dispatch analogue for reads)")
    p.add_argument("--serve_deadline_ms", type=float, default=2.0,
                   help="max milliseconds a prediction waits for its "
                        "micro-batch to fill")
    p.add_argument("--serve_snapshots", type=int, default=8,
                   help="snapshot ring capacity (exact-clock audit reads)")
    p.add_argument("--serve-queue", dest="serve_queue", type=int, default=0,
                   metavar="N",
                   help="admission control: max outstanding admitted "
                        "requests PER MODEL before the engine sheds with "
                        "a typed Overloaded rejection (0 = unbounded, "
                        "the pre-admission-control behaviour)")
    p.add_argument("--serve-shed", dest="serve_shed_ms", type=float,
                   default=0.0, metavar="MS",
                   help="predictive shedding: reject a request whose "
                        "estimated queueing delay (EWMA batch service "
                        "time x queued batches) exceeds MS milliseconds "
                        "(0 = off)")
    p.add_argument("--serve-auto", dest="serve_auto", action="store_true",
                   default=True,
                   help="adaptive dispatch (default ON): the engine "
                        "learns per-model dispatch cost vs occupancy, "
                        "bypasses the batching queue below the measured "
                        "break-even, and sizes the batch window from the "
                        "live arrival rate (docs/SERVING.md, 'Dispatch "
                        "economics')")
    p.add_argument("--no-serve-auto", dest="serve_auto",
                   action="store_false",
                   help="disable adaptive dispatch: always micro-batch "
                        "with the full configured window (the pre-cost-"
                        "model behaviour)")
    p.add_argument("--serve-shm", dest="serve_shm", action="store_true",
                   help="offer co-located PredictClients a shared-memory "
                        "fast path (skips TCP framing); remote or legacy "
                        "clients fall back to sockets transparently")
    p.add_argument("--wire-coalesce", dest="wire_coalesce",
                   action="store_true", default=True,
                   help="frame coalescing on socket bridges (default ON): "
                        "sends queue behind a per-connection writer "
                        "thread that ships every queued frame in one "
                        "scatter-gather sendmsg; receives parse all "
                        "complete frames per recv_into chunk "
                        "(docs/WIRE.md)")
    p.add_argument("--no-wire-coalesce", dest="wire_coalesce",
                   action="store_false",
                   help="disable frame coalescing: one sendall per frame "
                        "under the connection lock (the pre-wire-engine "
                        "behaviour; byte stream is identical either way)")
    return p


def load_test_csv(path: str, num_features: int):
    """Test set: dense CSV with header, label in the last column
    (LogisticRegressionTaskSpark.java:77-92)."""
    from kafka_ps_tpu.data.stream import load_csv_dataset
    x, y = load_csv_dataset(path)
    if x.shape[1] != num_features:
        raise SystemExit(
            f"test CSV has {x.shape[1] + 1} columns, expected "
            f"{num_features + 1} (features + label)")
    return x, y


def make_app_from_args(args, resuming: bool = False,
                       process_index: int = 0):
    """`process_index` > 0 (a non-coordinator host of a multi-process
    job) writes no server log and a process-suffixed worker log — one
    writer per file on a shared filesystem (deploy/README.md)."""
    from kafka_ps_tpu.runtime.app import StreamingPSApp
    from kafka_ps_tpu.utils.config import (BufferConfig, ModelConfig,
                                           PSConfig, ServingConfig,
                                           StreamConfig, TierConfig)
    from kafka_ps_tpu.utils.csvlog import (CsvLogSink, NullLogSink,
                                           SERVER_HEADER, WORKER_HEADER)

    cfg = PSConfig(
        num_workers=args.num_workers,
        consistency_model=args.consistency_model,
        task=args.task,
        model=ModelConfig(num_features=args.num_features,
                          num_classes=args.num_classes,
                          num_max_iter=args.local_iterations,
                          local_learning_rate=args.local_learning_rate,
                          hidden_dim=args.hidden_dim),
        buffer=BufferConfig(min_size=args.min_buffer_size,
                            max_size=args.max_buffer_size,
                            coefficient=args.buffer_size_coefficient),
        stream=StreamConfig(time_per_event_ms=args.producer_time_per_event),
        use_pallas=args.pallas,
        eval_every=getattr(args, "eval_every", 1),
        eval_async=getattr(args, "eval_async", True),
        use_gang=not getattr(args, "no_gang", False),
        compress=getattr(args, "compress", "none") or "none",
        slab_dtype=getattr(args, "slab_dtype", "f32") or "f32",
        slab_incremental=not getattr(args, "full_slab_upload", False),
        serving=ServingConfig(
            enabled=getattr(args, "serve", False),
            port=getattr(args, "serve_port", None),
            max_batch=getattr(args, "serve_batch", 16),
            deadline_ms=getattr(args, "serve_deadline_ms", 2.0),
            ring_capacity=getattr(args, "serve_snapshots", 8),
            queue_limit=getattr(args, "serve_queue", 0),
            shed_deadline_ms=getattr(args, "serve_shed_ms", 0.0),
            auto=getattr(args, "serve_auto", True),
            shm=getattr(args, "serve_shm", False)),
        tier=TierConfig(
            hot_bytes=getattr(args, "tier_hot_bytes", 0),
            warm_bytes=getattr(args, "tier_warm_bytes", 0),
            page_params=getattr(args, "tier_page_params", 1024)),
    )
    test_x, test_y = load_test_csv(args.test_data_file_path,
                                   args.num_features)
    suffix = f".p{process_index}" if process_index else ""
    if process_index == 0:
        server_log = CsvLogSink(
            "./logs-server.csv" if args.logging else None,
            SERVER_HEADER, append=resuming)
    else:
        # a CsvLogSink(None) falls back to stdout (the reference's
        # default); non-coordinator processes must write NO server log
        server_log = NullLogSink()
    worker_log = CsvLogSink(
        f"./logs-worker{suffix}.csv" if args.logging else None,
        WORKER_HEADER, append=resuming)
    tracer = None
    if getattr(args, "trace", None):
        from kafka_ps_tpu.utils.trace import Tracer
        tracer = Tracer()
    from kafka_ps_tpu.telemetry import maybe_telemetry
    # /varz serves this same registry, so a requested health plane
    # arms metrics even without a --metrics-file dump target
    telemetry = maybe_telemetry(
        tracer,
        want_metrics=bool(getattr(args, "metrics_file", None))
        or getattr(args, "health_port", None) is not None
        # the SLO plane judges registry families, so arming it arms them
        or getattr(args, "slo_serving_p99_ms", None) is not None
        or getattr(args, "slo_freshness_ms", None) is not None
        # model-health diagnostics are metric families first
        or getattr(args, "model_health", False))
    fabric = None
    if getattr(args, "durable_log", None):
        from kafka_ps_tpu.log import DurableFabric, LogConfig
        fabric = DurableFabric(
            args.durable_log,
            LogConfig(fsync=getattr(args, "fsync", "interval")),
            tracer=tracer, telemetry=telemetry)
    app = StreamingPSApp(cfg, test_x=test_x, test_y=test_y,
                         server_log=server_log, worker_log=worker_log,
                         tracer=tracer, fabric=fabric, telemetry=telemetry)
    return app, (server_log, worker_log)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return run_with_args(args)


def apply_platform_env() -> None:
    """Deployment hook shared by every CLI entry (this runner and the
    socket roles, cli/socket_mode.py): KPS_PLATFORM pins the JAX
    platform (e.g. =cpu for a broker-less smoke run or a CPU-mesh CI
    job).  Must happen before first backend use; a plain JAX_PLATFORMS
    env var can be overridden by accelerator plugins at interpreter
    start."""
    platform = os.environ.get("KPS_PLATFORM")
    if platform:
        import jax
        jax.config.update("jax_platforms", platform)


def run_with_args(args) -> int:
    apply_platform_env()
    if getattr(args, "eval_every", 1) < 1:
        raise SystemExit("--eval_every must be >= 1")
    if args.fused and args.pallas:
        raise SystemExit(
            "--pallas applies to the per-node worker path only; the "
            "--fused BSP path runs its own shard_map program "
            "(parallel/bsp.py) — drop one of the two flags")
    if getattr(args, "param_shards", 1) > 1 and not args.fused:
        raise SystemExit("--param_shards requires --fused (the "
                         "range-sharded server is a fused-mesh mode)")
    if args.pallas and args.task not in ("logreg", "mlp"):
        raise SystemExit(
            "--pallas implements the logreg and mlp local updates "
            f"(ops/fused_update.py); got --task {args.task}")
    if getattr(args, "serve_port", None) is not None \
            and not getattr(args, "serve", False):
        raise SystemExit("--serve_port requires --serve")
    if getattr(args, "slab_dtype", "f32") != "f32" and args.fused:
        # the fused BSP step (runtime/app.run_fused_bsp) keeps its own
        # whole-slab device cache outside the worker SlabStore path —
        # silently ignoring the dtype would misreport what ran
        raise SystemExit(
            "--slab-dtype applies to the per-node worker slab "
            "(compress/slab.py); the --fused BSP path keeps its own "
            "slab cache — drop one of the two flags")
    tier_hot = getattr(args, "tier_hot_bytes", 0)
    tier_warm = getattr(args, "tier_warm_bytes", 0)
    if tier_hot < 0 or tier_warm < 0:
        raise SystemExit("--tier-*-bytes caps must be >= 0")
    if (tier_hot or tier_warm) and args.fused:
        # the fused BSP step owns theta inside its shard_map program —
        # paged residency has no seam there; silently ignoring the caps
        # would misreport what ran
        raise SystemExit(
            "--tier-hot-bytes/--tier-warm-bytes apply to the per-node "
            "server (kafka_ps_tpu/store/); the --fused BSP path keeps "
            "theta inside its mesh program — drop one of the two flags")
    if tier_warm and not getattr(args, "durable_log", None):
        raise SystemExit(
            "--tier-warm-bytes demotes pages to commit-log records; "
            "run with --durable-log DIR so the cold partition has a "
            "home (docs/TIERING.md)")
    if getattr(args, "tier_page_params", 1024) < 1:
        raise SystemExit("--tier-page-params must be >= 1")
    compress = getattr(args, "compress", "none") or "none"
    if compress != "none":
        from kafka_ps_tpu.compress.wire import parse_codec
        try:
            parse_codec(compress)
        except ValueError as e:
            raise SystemExit(f"--compress: {e}") from None
        if args.fused:
            # the fused BSP step moves deltas through shard_map
            # collectives that never cross a serde boundary — there is
            # no wire to compress, and silently ignoring the flag would
            # misreport what ran
            raise SystemExit(
                "--compress applies to the message transport (per-node "
                "and socket modes); the --fused collectives never cross "
                "a serde boundary — drop one of the two flags")
    distributed = False
    if args.remote:
        from kafka_ps_tpu.parallel import multihost
        # join the job BEFORE building the app: process identity gates
        # the log sinks and checkpoint writer below
        distributed = multihost.initialize()
        if distributed and getattr(args, "durable_log", None):
            # the commit log is single-writer per partition; a
            # multi-host job would need per-host roots + a replicated
            # offsets store (ROADMAP)
            raise SystemExit(
                "--durable-log is single-process; a multi-host job "
                "must run without it (use --checkpoint for resume)")
        if distributed and not args.fused:
            # only the fused BSP step runs over the global mesh; the
            # host-orchestrated modes are single-host by design
            # (deploy/README.md)
            raise SystemExit(
                "-r joined a multi-host job but only --fused runs over "
                "the global mesh; add --fused (or run the async "
                "consistency modes single-host)")
        # unconfigured: behave like the reference's remote flag on a
        # local run — nothing to switch (ServerAppRunner.java:63)
    if args.verbose:
        print("\nUsed parameter:")
        for k, v in sorted(vars(args).items()):
            print(f"    {k}: {v}")

    process_index = 0
    if distributed:
        import jax
        process_index = jax.process_index()
    resuming = bool(args.checkpoint and os.path.exists(args.checkpoint))
    app, logs = make_app_from_args(args, resuming=resuming,
                                   process_index=process_index)

    # membership/resume events persist incrementally (one writer per
    # job): an end-of-run dump would lose the auditor's record on a
    # crash — the exact case the events segment elastic logs for
    from kafka_ps_tpu.utils.csvlog import (CsvLogSink as _Sink,
                                           NullLogSink as _Null,
                                           EVENTS_HEADER)
    events_log = (_Sink("./logs-events.csv", EVENTS_HEADER,
                        append=resuming)
                  if (args.logging and process_index == 0) else _Null())
    app.server.membership_log = events_log
    logs = [*logs, events_log]

    if tier_hot or tier_warm:
        # attach BEFORE the checkpoint restore below so the restore can
        # re-apply the recorded tier residency (utils/checkpoint.py)
        if distributed:
            raise SystemExit(
                "--tier-*-bytes is single-process (residency is a "
                "per-process resource; multi-host runs are --fused)")
        from kafka_ps_tpu.log.durable_fabric import COLD_PARTITION_DIR
        cold_dir = (os.path.join(args.durable_log, COLD_PARTITION_DIR)
                    if getattr(args, "durable_log", None) else None)
        app.enable_tiering(cold_dir)

    if args.checkpoint:
        from kafka_ps_tpu.utils import checkpoint as ckpt
        # single-process runs fold every worker's buffer into the
        # checkpoint (the durable training window); in a multi-host job
        # buffers are fed process-locally, so the coordinator's copies
        # of remote workers' buffers would be empty lies — skip them
        ckpt_buffers = app.buffers if not distributed else None
        restored = ckpt.maybe_restore(args.checkpoint, app.server,
                                      buffers=ckpt_buffers,
                                      residuals=app.compressors or None)
        if restored and args.verbose:
            print(f"    restored checkpoint at iteration "
                  f"{app.server.iterations}")
        if process_index == 0:   # one checkpoint writer per job
            app.server.checkpoint_path = args.checkpoint
            app.server.checkpoint_every = args.checkpoint_every
            app.server.checkpoint_buffers = ckpt_buffers

    if getattr(args, "durable_log", None):
        # replay the unconsumed tail past the restored checkpoint's
        # offsets (or the committed ones) BEFORE the producer starts:
        # recovery re-enqueues in-flight weights/gradients, refills the
        # buffers' post-checkpoint rows, and arms the re-ingestion skip
        counts = app.recover_durable()
        if args.verbose:
            print(f"    durable-log replay: {counts}")

    serve_bridge = None
    serve_engine = None
    if getattr(args, "serve", False):
        if distributed:
            raise SystemExit(
                "--serve is single-process: the serving plane reads the "
                "server's snapshot registry in-process (run a dedicated "
                "serving host against the checkpoint instead)")
        engine = serve_engine = app.enable_serving()
        # cold start (docs/SERVING.md): the restored (or fresh) theta is
        # servable before the first gate release...
        app.server.publish_snapshot()
        if getattr(args, "durable_log", None):
            # ...and when the durable log holds RELEASED weights strictly
            # ahead of the restored stable clock, publish those too —
            # readers immediately see everything the dead process had
            # already promised to some worker
            latest = app.fabric.latest_logged_weights()
            if (latest is not None
                    and latest.vector_clock > app.server.serving_clock()):
                app.server.publish_snapshot(latest.values,
                                            latest.vector_clock)
        if getattr(args, "serve_port", None) is not None:
            from kafka_ps_tpu.runtime import net
            serve_bridge = net.ServerBridge(port=args.serve_port,
                                            run_id=app.server.run_id,
                                            tracer=app.tracer,
                                            telemetry=app.telemetry,
                                            shm=getattr(args, "serve_shm",
                                                        False))
            serve_bridge.attach_serving(engine)
            print(f"serving on port {serve_bridge.port}",
                  file=sys.stderr, flush=True)

    # mesh + data-partition assignment come AFTER checkpoint restore: a
    # restored checkpoint can carry evictions, and both the divisibility
    # check and the local-worker filter must see the real membership
    mesh = None
    param_shards = getattr(args, "param_shards", 1)
    if param_shards > 1:
        if distributed:
            raise SystemExit("--param_shards is single-process (drop the "
                             "KPS_* multi-process env, or use plain -r)")
        import jax

        from kafka_ps_tpu.parallel import mesh as mesh_mod
        n_dev = len(jax.devices())
        if n_dev % param_shards != 0:
            raise SystemExit(
                f"--param_shards {param_shards} must divide the device "
                f"count {n_dev}")
        mesh = mesh_mod.worker_param_mesh(n_dev // param_shards,
                                          param_shards)
        active = app.server.tracker.active_workers
        if len(active) % mesh.devices.size != 0:
            raise SystemExit(
                f"{len(active)} active workers must be a multiple of "
                f"the {mesh.devices.size}-device mesh (workers shard "
                "over both mesh axes)")
    elif args.fused and args.remote:
        from kafka_ps_tpu.parallel import multihost
        mesh = multihost.global_worker_mesh()
        active = app.server.tracker.active_workers
        if len(active) % mesh.devices.size != 0:
            raise SystemExit(
                f"{len(active)} active workers must be a "
                f"multiple of the {mesh.devices.size}-device "
                f"mesh in --remote mode")
        if distributed:
            local_pos = multihost.local_worker_ids(len(active), mesh)
            app.local_workers = {active[i] for i in local_pos}

    # flight recorder + watchdogs + health plane (docs/OBSERVABILITY.md)
    # — wired unconditionally; inert unless --flight-dir/--health-port
    from kafka_ps_tpu.telemetry.health import OpsPlane
    from kafka_ps_tpu.telemetry.modelhealth import \
        plane_from_args as modelhealth_from_args
    from kafka_ps_tpu.telemetry.registry import model_name
    from kafka_ps_tpu.telemetry.slo import plane_from_args
    # model-health plane (--model-health): the server's apply path
    # feeds it, buffers feed its feature sketch, OpsPlane owns its
    # sampler thread + the armed drift watchdog.  The drift CSV sink
    # stamps wall-clock time HERE — the monitor emits clock-free rows
    # (PS104 keeps telemetry/drift.py replay-pure).
    drift_sink = None
    drift_log = None
    if getattr(args, "model_health", False) and getattr(args, "logging",
                                                        False):
        import time as _time
        from kafka_ps_tpu.utils.csvlog import DRIFT_HEADER
        drift_sink = _Sink("./logs-drift.csv", DRIFT_HEADER)
        drift_log = (lambda rest:
                     drift_sink(f"{int(_time.time() * 1000)};{rest}"))
    modelhealth = modelhealth_from_args(
        args, app.telemetry,
        num_features=app.cfg.model.num_features,
        model=model_name(app.cfg.consistency_model), log=drift_log)
    if modelhealth is not None:
        app.server.attach_model_health(modelhealth)
        for b in app.buffers:
            b.attach_drift(modelhealth.drift)
    ops = OpsPlane(flight_dir=getattr(args, "flight_dir", None),
                   health_port=getattr(args, "health_port", None),
                   telemetry=app.telemetry, role="run",
                   profile=getattr(args, "profile", False),
                   slo_plane=plane_from_args(args, app.telemetry),
                   modelhealth=modelhealth)
    ops.add_gate_watchdog(app.server)
    if getattr(args, "durable_log", None):
        ops.add_fsync_watchdog()
    if serve_engine is not None:
        ops.add_serving_watchdog(serve_engine)
    if app.eval_engine is not None:
        ops.add_eval_engine(app.eval_engine)   # /evalz detail row
    ops.start()

    metrics_file = getattr(args, "metrics_file", None)
    if metrics_file and getattr(args, "metrics_every", 0.0) > 0:
        # periodic Prometheus-style dump (atomic replace) so an external
        # scraper/tail can watch a long run; the exit path below writes
        # the final state either way
        app.telemetry.start_dumper(metrics_file, args.metrics_every)

    producer = app.make_producer(args.training_data_file_path)
    producer.run_in_background()
    app.wait_for_prefill(min_per_worker=1, timeout=120.0)
    app.wait_for_stream_settle(producer)

    max_iters = args.max_iterations or sys.maxsize
    from kafka_ps_tpu.utils.trace import device_trace
    try:
        with device_trace(args.device_trace):
            status_every = getattr(args, "status_every", 0.0)
            if args.fused:
                app.run_fused_bsp(max_server_iterations=max_iters,
                                  mesh=mesh, status_every=status_every)
            elif args.mode == "serial":
                app.run_serial(max_server_iterations=max_iters,
                               pump=lambda: None,
                               status_every=status_every)
            else:
                app.run_threaded(max_server_iterations=max_iters,
                                 failure_policy=args.failure_policy,
                                 heartbeat_timeout=args.heartbeat_timeout,
                                 status_every=status_every)
    except KeyboardInterrupt:
        print("interrupted — shutting down", file=sys.stderr)
        app.stop()
    finally:
        # teardown discipline (docs/TESTING.md): join every thread that
        # can touch native code BEFORE interpreter finalization — the
        # producer sinks rows into numpy slabs and the deferred-log
        # drain threads dispatch device fetches
        producer.stop()
        # serving teardown: close the socket endpoint FIRST (stops new
        # requests), then the engine's batcher thread (holds jit'd
        # callables — joined before interpreter exit)
        if serve_bridge is not None:
            serve_bridge.close()
        app.close_serving()
        # ops plane after serving, before the logs: the final flight
        # dump still sees live telemetry and a coherent ring
        ops.close()
        if args.checkpoint and process_index == 0:
            # routed through the server so a durable fabric commits the
            # offsets this final snapshot covers (a commit point)
            app.server.save_checkpoint_now()
        # AFTER the final checkpoint: saving assembles theta, which may
        # fault cold pages and needs the cold log still open
        app.close_tiering()
        if getattr(args, "durable_log", None):
            app.fabric.close()
        app.close_logs()
        for log in logs:
            log.close()
        if drift_sink is not None:
            # after ops.close(): the plane's final drain may still emit
            # a verdict row
            drift_sink.close()
        if metrics_file:
            app.telemetry.stop_dumper()
            app.telemetry.write_prometheus(metrics_file)
        if args.trace:
            import json as _json
            print(app.tracer.dump(args.trace), file=sys.stderr)
            print(_json.dumps({"spans": app.tracer.span_stats(),
                               "counters": app.tracer.counters()},
                              indent=2), file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
