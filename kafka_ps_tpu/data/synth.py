"""Synthetic dataset shaped like the reference's benchmark data.

The reference benchmarks on Amazon fine-food-reviews embedded to 1024
hashed features with 5 classes, ≤20k tuples per label (README.md:210-216)
— the actual embedding CSVs are not redistributable (reference
.MISSING_LARGE_BLOBS).  This generator produces a drop-in shaped stand-in:
dense float features, labels 1..num_classes in the last column, linearly
separable per-class structure plus noise so streaming F1 curves behave
like the published plots (monotone rise toward an offline ceiling).

Usage: python -m kafka_ps_tpu.data.synth --out_dir ./data --rows 20000
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def generate(rows: int, num_features: int = 1024, num_classes: int = 5,
             noise: float = 2.0, sparsity: float = 0.7,
             seed: int = 0, center_scale: float = 1.0,
             label_noise: float = 0.0) -> tuple[np.ndarray, np.ndarray]:
    """(x, y) with y in 1..num_classes (the reference's label convention,
    LogisticRegressionTaskSpark.java:122-140).

    `center_scale` shrinks the class centers toward each other
    (class overlap) and `label_noise` flips that fraction of labels to a
    uniformly random OTHER class — together they set the offline
    F1 ceiling below 1.0, which the default easy regime
    (center_scale=1) never does.  NOTE: draw train and test in ONE call
    and split — different seeds draw different class centers.
    """
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=1.0,
                         size=(num_classes, num_features)) * center_scale
    y = rng.integers(1, num_classes + 1, size=rows).astype(np.int32)
    x = centers[y - 1] + rng.normal(scale=noise, size=(rows, num_features))
    # zero out a fraction of entries: the reference's hashed-feature CSVs
    # are sparse and the producer drops zeros (CsvProducer.java:52-57)
    drop = rng.random(size=x.shape) < sparsity
    x = np.where(drop, 0.0, x).astype(np.float32)
    if label_noise > 0.0:
        flip = rng.random(rows) < label_noise
        shift = rng.integers(1, num_classes, size=rows)
        y = np.where(flip, (y - 1 + shift) % num_classes + 1,
                     y).astype(np.int32)
    return x, y


# The "hard" benchmark regime: class overlap tuned so an offline LR
# ceiling lands at weighted F1 well below 1.0 at the reference's shapes
# (1024 features, 5 classes) — the non-separable setting the reference's
# headline numbers live on (offline 0.47 / best streaming 0.4482,
# README.md:223-233,277).  Measured ceiling (sklearn LogisticRegression,
# unpenalized) grows with training rows: 0.542 on a 5k-row fit, 0.642 on
# the 12k-row campaign dataset (docs/EVALUATION.md).
HARD_CENTER_SCALE = 0.2
HARD_LABEL_NOISE = 0.0


def generate_hard(rows: int, num_features: int = 1024,
                  num_classes: int = 5,
                  seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """The hard regime with default noise/sparsity (see HARD_* above)."""
    return generate(rows, num_features, num_classes,
                    seed=seed, center_scale=HARD_CENTER_SCALE,
                    label_noise=HARD_LABEL_NOISE)


def write_csv(path: str, x: np.ndarray, y: np.ndarray) -> None:
    header = ",".join([str(i) for i in range(x.shape[1])] + ["Score"])
    with open(path, "w") as f:
        f.write(header + "\n")
        for i in range(len(x)):
            f.write(",".join(f"{v:g}" for v in x[i]) + f",{y[i]}\n")


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out_dir", default="./data")
    p.add_argument("--rows", type=int, default=20000)
    p.add_argument("--test_rows", type=int, default=2000)
    p.add_argument("--num_features", type=int, default=1024)
    p.add_argument("--num_classes", type=int, default=5)
    p.add_argument("--noise", type=float, default=2.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--center_scale", type=float, default=1.0)
    p.add_argument("--label_noise", type=float, default=0.0)
    p.add_argument("--hard", action="store_true",
                   help="non-separable benchmark regime (offline F1 "
                        "ceiling ~0.54, see generate_hard)")
    args = p.parse_args(argv)
    if args.hard:
        args.center_scale = HARD_CENTER_SCALE
        args.label_noise = HARD_LABEL_NOISE
    os.makedirs(args.out_dir, exist_ok=True)
    x, y = generate(args.rows + args.test_rows, args.num_features,
                    args.num_classes, noise=args.noise, seed=args.seed,
                    center_scale=args.center_scale,
                    label_noise=args.label_noise)
    write_csv(os.path.join(args.out_dir, "train.csv"),
              x[:args.rows], y[:args.rows])
    write_csv(os.path.join(args.out_dir, "test.csv"),
              x[args.rows:], y[args.rows:])
    print(f"wrote {args.rows} train + {args.test_rows} test rows to "
          f"{args.out_dir}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
