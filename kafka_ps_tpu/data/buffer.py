"""Dynamic sliding training-data buffer — one per logical worker.

Behavioral re-design of the reference's WorkerSamplingProcessor
(processors/WorkerSamplingProcessor.java:18-136).  The reference stores
sparse JSON rows in a Kafka Streams KV store keyed into a per-worker key
space; here each worker owns **fixed-capacity dense numpy arrays plus a
validity mask** — static shapes so the jit'd training step never
recompiles, and the device transfer is one contiguous slab instead of a
per-row range scan.

Policy preserved exactly:
  * inter-arrival times tracked over a 500-event window
    (WorkerSamplingProcessor.java:21-23,124-135);
  * target size = clamp(round(coefficient * events_per_minute), min, max)
    with events_per_minute = 60000 / mean_inter_arrival_ms, default mean
    1000 ms before any samples (WorkerSamplingProcessor.java:115-122);
  * insertion: below target → fill first empty slot; at target →
    overwrite oldest; above target (target shrank) → delete the n oldest
    then overwrite the next-oldest survivor
    (WorkerSamplingProcessor.java:79-112);
  * insertion IDs are buffer-relative: new ID = max ID currently in the
    buffer + 1 (0 when empty) (WorkerSamplingProcessor.java:74-77,110-111).

The reference's buffer-scan off-by-one (training scans one key into the
next worker's space, SURVEY §3.5.2) is intentionally NOT reproduced —
each buffer is a private object, so there is no adjacent key space to
leak into.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable

import numpy as np

from kafka_ps_tpu.analysis.lockgraph import OrderedLock
from kafka_ps_tpu.models.logreg import sparse_to_dense
from kafka_ps_tpu.utils.config import BufferConfig


def _default_clock_ms() -> float:
    return time.monotonic() * 1000.0


class SlidingBuffer:
    """Fixed-capacity masked ring buffer with a rate-adaptive target size."""

    def __init__(self, num_features: int, cfg: BufferConfig,
                 clock_ms: Callable[[], float] | None = None,
                 telemetry=None, worker: int | None = None):
        self.cfg = cfg
        self.num_features = num_features
        if telemetry is None:
            from kafka_ps_tpu.telemetry import NULL_TELEMETRY
            telemetry = NULL_TELEMETRY
        self._telemetry = telemetry
        self._m_rows = telemetry.counter(
            "buffer_rows_ingested_total",
            worker="all" if worker is None else str(worker))
        cap = cfg.max_size
        self.x = np.zeros((cap, num_features), dtype=np.float32)
        self.y = np.zeros((cap,), dtype=np.int32)
        # insertion_id[i] == 0 marks an empty slot (reference IDs start at 1).
        self.insertion_id = np.zeros((cap,), dtype=np.int64)
        self._clock_ms = clock_ms or _default_clock_ms
        self._inter_arrival_ms: deque[float] = deque(maxlen=cfg.arrival_window)
        self._last_arrival_ms: float | None = None
        # Slots whose (x, y, insertion_id) changed since the last
        # drain/clearing snapshot — the incremental device-slab path
        # (compress/slab.SlabStore.apply_rows) uploads only these.
        self._dirty: set[int] = set()
        # Monotonic mutation counter.  num_tuples_seen is NOT a valid
        # change detector (restore_state can rewind it; a mass-delete
        # with one insert moves it by 1 while touching many slots), so
        # the worker keys its device-slab cache off this instead.
        self._version = 0
        # add() and snapshot() are internally synchronized so the producer
        # thread and the training loop need no external locking.
        self._lock = OrderedLock("SlidingBuffer.state")
        # optional drift monitor (telemetry/drift.py): sampled arrivals
        # feed its per-feature Welford sketch.  None keeps ingest
        # byte-identical to today's path.
        self._drift = None

    def attach_drift(self, monitor) -> None:
        """Feed sampled arrivals to a DriftMonitor's feature sketch
        (population-stability scoring, --model-health)."""
        self._drift = monitor

    # -- rate tracking (WorkerSamplingProcessor.java:124-135) --------------

    def _record_arrival(self) -> None:
        now = self._clock_ms()
        if self._last_arrival_ms is not None:
            self._inter_arrival_ms.append(now - self._last_arrival_ms)
        self._last_arrival_ms = now

    def target_size(self) -> int:
        """clamp(round(coefficient * events_per_minute), min, max)."""
        if self._inter_arrival_ms:
            mean_ms = sum(self._inter_arrival_ms) / len(self._inter_arrival_ms)
        else:
            mean_ms = 1000.0
        if mean_ms <= 0:
            # burst arrivals within clock resolution: rate is effectively
            # infinite, clamp straight to the cap
            return self.cfg.max_size
        calculated = round(self.cfg.coefficient * 60000.0 / mean_ms)
        return max(self.cfg.min_size, min(self.cfg.max_size, int(calculated)))

    # -- insertion policy (WorkerSamplingProcessor.java:79-112) ------------

    def add(self, features, label: int) -> None:
        """Insert one sample, evicting per the dynamic-target policy."""
        with self._lock:
            self._add_locked(features, label)
        if self._telemetry.enabled:
            self._m_rows.inc()
        if self._drift is not None:
            # outside the buffer lock (lockgraph: never hold two);
            # observe_row itself samples every Nth arrival
            self._drift.observe_row(features)

    def add_many(self, rows) -> None:
        """Insert N (features, label) samples under ONE lock acquisition
        — the bulk half of the batched ingest path (net.T_DATA_BATCH,
        ServerBridge.send_data_batch).  Policy-identical to N add()
        calls: arrival recording and the dynamic-target eviction run
        per row, only the lock round-trips are amortized."""
        n = 0
        # rows may be a one-shot iterable: capture features while
        # inserting, sketch them after the lock is released (lockgraph:
        # never hold two)
        sampled = [] if self._drift is not None else None
        with self._lock:
            for features, label in rows:
                self._add_locked(features, label)
                n += 1
                if sampled is not None:
                    sampled.append(features)
        if n and self._telemetry.enabled:
            self._m_rows.inc(n)
        if sampled:
            for features in sampled:
                self._drift.observe_row(features)

    def _add_locked(self, features, label: int) -> None:
        self._record_arrival()
        target = self.target_size()

        filled = np.flatnonzero(self.insertion_id > 0)
        count = len(filled)
        new_id = int(self.insertion_id.max()) + 1 if count else 1

        if count < target:
            # fill the first empty slot
            slot = int(np.flatnonzero(self.insertion_id == 0)[0])
        elif count == target:
            # overwrite the oldest
            slot = int(filled[np.argmin(self.insertion_id[filled])])
        else:
            # target shrank: drop the n oldest, overwrite the next-oldest
            n = count - target
            oldest_first = filled[np.argsort(self.insertion_id[filled])]
            self.insertion_id[oldest_first[:n]] = 0
            self._dirty.update(int(s) for s in oldest_first[:n])
            slot = int(oldest_first[n])

        if isinstance(features, dict):
            row = sparse_to_dense([features], self.num_features)[0]
        else:
            row = np.asarray(features, dtype=np.float32)
        self.x[slot] = row
        self.y[slot] = label
        self.insertion_id[slot] = new_id
        self._dirty.add(slot)
        self._version += 1

    # -- views for the training step ---------------------------------------

    @property
    def count(self) -> int:
        with self._lock:
            return int((self.insertion_id > 0).sum())

    @property
    def num_tuples_seen(self) -> int:
        """Worker log column: max insertion ID in the buffer
        (WorkerTrainingProcessor.java:80-92)."""
        with self._lock:
            return int(self.insertion_id.max())

    @property
    def version(self) -> int:
        """Monotonic mutation counter (bumps on every add/restore).
        Compare against a cached value to detect staleness — unlike
        num_tuples_seen this never aliases across restore_state."""
        with self._lock:
            return self._version

    @property
    def dirty_slots(self) -> list[int]:
        """Sorted slots touched since the last drain (non-clearing view,
        for tests/inspection; drain_dirty is the consuming call)."""
        with self._lock:
            return sorted(self._dirty)

    def drain_dirty(self):
        """(slots, x_rows, y_rows, mask_rows) for every slot touched
        since the last drain, then forget them — the delta the
        incremental device-slab path scatters instead of re-uploading
        the whole slab.  One lock acquisition, so the rows are a
        consistent cut: a slot deleted by a target shrink comes back
        with mask 0 and whatever stale x/y it holds (the mask is what
        the solver trusts, exactly as in snapshot())."""
        with self._lock:
            slots = np.asarray(sorted(self._dirty), dtype=np.int64)
            self._dirty.clear()
            mask = (self.insertion_id[slots] > 0).astype(np.float32)
            return slots, self.x[slots].copy(), self.y[slots].copy(), mask

    def snapshot(self, clear_dirty: bool = False
                 ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(x, y, mask) — a consistent copy of the static-shape slab
        shipped to the device; safe to use without holding any lock.
        clear_dirty=True marks the copy as the new device baseline
        (a full upload subsumes any pending incremental delta)."""
        with self._lock:
            mask = (self.insertion_id > 0).astype(np.float32)
            if clear_dirty:
                self._dirty.clear()
            return self.x.copy(), self.y.copy(), mask

    # -- durability (utils/checkpoint.py) ----------------------------------

    def state(self) -> dict[str, np.ndarray]:
        """Serializable durable state: slab contents, insertion IDs, and
        the inter-arrival window behind the rate-adaptive target size —
        the changelog-backed state store the reference's workers restore
        from on reassignment (WorkerApp.java:40-42, Kafka Streams
        logged KV store)."""
        with self._lock:
            return {"x": self.x.copy(), "y": self.y.copy(),
                    "ids": self.insertion_id.copy(),
                    "arrivals": np.asarray(self._inter_arrival_ms,
                                           dtype=np.float64)}

    def restore_state(self, st) -> None:
        """Inverse of state().  The arrival CLOCK does not survive a
        restart (monotonic time is process-local), so the gap between
        the crash and the first post-restore arrival is not counted as
        an inter-arrival — only the restored window is."""
        if st["x"].shape != self.x.shape:
            raise ValueError(
                f"buffer state shape {st['x'].shape} != slab "
                f"{self.x.shape} (capacity/features changed?)")
        with self._lock:
            self.x[:] = st["x"]
            self.y[:] = st["y"]
            self.insertion_id[:] = st["ids"]
            self._inter_arrival_ms.clear()
            self._inter_arrival_ms.extend(float(v) for v in st["arrivals"])
            self._last_arrival_ms = None
            # every slot may differ from what a device slab holds
            self._dirty.update(range(self.x.shape[0]))
            self._version += 1
