"""Streaming ingestion simulator — the reference's CsvProducer re-designed.

Reads a training CSV row by row, converts each row into a sparse sample
(zero features dropped, label = last column — CsvProducer.java:52-58),
assigns it round-robin to a logical worker (row_count % num_workers,
CsvProducer.java:61), and paces delivery: the first
num_workers * prefill_per_worker rows go unthrottled to pre-fill the
buffers, after which the producer sleeps 1 s every
(1000 / time_per_event_ms) rows (CsvProducer.java:73-83).

The Kafka INPUT_DATA topic hop disappears: the sink is a plain callable
(in-process fabric or directly the per-worker SlidingBuffer), which on
TPU means samples land in pinned host buffers awaiting the next
host→device slab transfer rather than a JSON round-trip through a broker.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterator

Sink = Callable[[int, dict[int, float], int], None]  # (worker, features, label)


def iter_csv_rows(csv_path: str, has_header: bool = True,
                  num_features: int | None = None,
                  use_native: bool | None = None
                  ) -> Iterator[tuple[dict[int, float], int]]:
    """Yield (sparse_features, label) per CSV row, dropping zero features
    (CsvProducer.java:52-58).

    `use_native`: True forces the C++ parser (kafka_ps_tpu.native),
    False forces pure Python, None (default) auto-selects — the native
    path parses the whole file in one pass and replays rows; the Python
    path streams line by line."""
    if use_native is not False:
        from kafka_ps_tpu import native
        parsed = None
        if native.is_available():
            try:
                parsed = native.parse_csv(csv_path, has_header=has_header)
            except RuntimeError:
                # the C parser is stricter (uniform width, no stray
                # whitespace); on auto-select fall through to Python
                if use_native:
                    raise
        elif use_native:
            raise RuntimeError("native CSV parser requested but unavailable")
        if parsed is not None:
            if (num_features is not None and parsed.num_rows > 0
                    and parsed.num_features != num_features):
                raise ValueError(
                    f"rows have {parsed.num_features + 1} columns, "
                    f"expected {num_features + 1}")
            for i in range(parsed.num_rows):
                yield parsed.row(i)
            return
    with open(csv_path) as f:
        if has_header:
            f.readline()
        for line in f:
            line = line.strip()
            if not line:
                continue
            cols = line.split(",")
            if num_features is not None and len(cols) != num_features + 1:
                raise ValueError(
                    f"row has {len(cols)} columns, expected {num_features + 1}")
            feats = {i: float(v) for i, v in enumerate(cols[:-1])
                     if float(v) != 0.0}
            yield feats, int(float(cols[-1]))


class CsvStreamProducer:
    """Paced row pump: CSV → sink(worker, features, label)."""

    def __init__(self, csv_path: str, num_workers: int, sink: Sink,
                 time_per_event_ms: float = 200.0,
                 prefill_per_worker: int = 128,
                 has_header: bool = True,
                 num_features: int | None = None,
                 use_native: bool | None = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.csv_path = csv_path
        self.num_workers = num_workers
        self.sink = sink
        self.time_per_event_ms = time_per_event_ms
        self.prefill_per_worker = prefill_per_worker
        self.has_header = has_header
        self.num_features = num_features
        # None = auto (native one-pass parse when available — O(file)
        # memory, faster); False = force the lazy line-by-line Python
        # path (constant memory, first row immediately)
        self.use_native = use_native
        # default pacing waits on the stop event, so stop() interrupts a
        # sleep instantly; an injected sleep (tests) is called directly
        self._sleep = sleep if sleep is not time.sleep else None
        # pscheck: disable=PS201 (producer-thread counter; read for end-of-run reporting after join)
        self.rows_sent = 0
        self.finished = threading.Event()
        self.stopped = threading.Event()
        self._thread: threading.Thread | None = None

    def run(self) -> None:
        prefill = self.num_workers * self.prefill_per_worker
        # 1 s sleep every this many rows (CsvProducer.java:75-78); a
        # time_per_event above 1000 ms degenerates to sleeping every row;
        # <= 0 means unthrottled (no pacing at all).
        rows_per_sleep = (max(1, int(1000 / self.time_per_event_ms))
                          if self.time_per_event_ms > 0 else 0)
        for feats, label in iter_csv_rows(self.csv_path, self.has_header,
                                          self.num_features,
                                          use_native=self.use_native):
            if self.stopped.is_set():
                break
            worker = self.rows_sent % self.num_workers
            self.sink(worker, feats, label)
            self.rows_sent += 1
            if (rows_per_sleep and self.rows_sent >= prefill
                    and self.rows_sent % rows_per_sleep == 0):
                if self._sleep is not None:
                    self._sleep(1.0)
                elif self.stopped.wait(1.0):
                    break
        self.finished.set()

    def run_in_background(self) -> threading.Thread:
        t = threading.Thread(target=self.run, daemon=True,
                             name="csv-stream-producer")
        self._thread = t
        t.start()
        return t

    def stop(self, join_timeout: float = 10.0) -> None:
        """Stop the pump and JOIN its thread: the drive loops call this
        on exit so the process never finalizes while the producer is
        mid-sink (a daemon thread dying inside native numpy/XLA code
        aborts the interpreter — the round-4 flake)."""
        self.stopped.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=join_timeout)


def load_csv_dataset(csv_path: str, has_header: bool = True
                     ) -> tuple["np.ndarray", "np.ndarray"]:
    """Whole CSV as dense (x, y) — label in the last column, the
    reference's file layout (CsvProducer.java:52-58, header column
    `Score`, LogisticRegressionTaskSpark.java:86-92)."""
    import numpy as np
    data = np.loadtxt(csv_path, delimiter=",",
                      skiprows=1 if has_header else 0)
    if data.ndim == 1:
        data = data[None, :]
    return data[:, :-1].astype(np.float32), data[:, -1].astype(np.int32)
