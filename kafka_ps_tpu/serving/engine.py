"""Micro-batching prediction engine — gang dispatch for the read path.

Requests queue up; a single batcher thread coalesces them until either
`max_batch` rows are waiting or `deadline_s` has elapsed since the first
row arrived, then runs ONE jit'd forward pass over a padded fixed-shape
batch. The amortization argument is identical to training-side gang
dispatch (docs/GANG_DISPATCH.md): dispatch overhead is per-XLA-call, so
k requests per call cost ~1/k of the per-request dispatch tax. The
fixed (max_batch, F) shape means exactly one compile per model family.

Each micro-batch resolves the snapshot registry ONCE — all rows in a
batch are answered from the same (theta, clock) pair, and each row's
read bound is checked against that snapshot (the registry only ever
serves its newest snapshot, so a bound the newest fails no snapshot
passes; see serving/policy.py).

jax imports are deferred to the first dispatch so thin clients can
import this module (for the Prediction type) without a backend.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, NamedTuple

import numpy as np

from kafka_ps_tpu.serving import policy
from kafka_ps_tpu.serving.snapshot import SnapshotRegistry
from kafka_ps_tpu.telemetry import NULL_TELEMETRY
from kafka_ps_tpu.utils.trace import NULL_TRACER, LatencyRecorder


class Prediction(NamedTuple):
    label: int             # argmax class
    confidence: float      # softmax mass on the argmax class
    vector_clock: int      # clock of the snapshot that answered
    wall_time: float       # publication time of that snapshot


class _Request(NamedTuple):
    x: np.ndarray
    bound: policy.ReadBound | None
    callback: Callable     # called with Prediction or an Exception
    t0: float              # monotonic enqueue time (latency accounting)


_SENTINEL = object()


class PredictionEngine:
    """Deadline/size-capped micro-batcher over a SnapshotRegistry."""

    def __init__(self, task, registry: SnapshotRegistry, *,
                 max_batch: int = 16, deadline_s: float = 0.002,
                 tracer=None, telemetry=None, now=time.time):
        self.task = task
        self.registry = registry
        self.max_batch = max(1, int(max_batch))
        self.deadline_s = max(0.0, float(deadline_s))
        self.tracer = tracer or NULL_TRACER
        self.telemetry = telemetry or NULL_TELEMETRY
        # pre-resolved metric children (null when telemetry is off):
        # observed per micro-batch, never per row, never on device data
        self._m_snapshot_age = self.telemetry.histogram("snapshot_age_ms")
        self._m_requests = self.telemetry.counter("serving_requests_total")
        self._m_rejections = self.telemetry.counter(
            "serving_rejections_total")
        # seq of the last snapshot whose delta.wire flow was closed here:
        # the flow ends once, at the snapshot's FIRST serving read
        self._last_traced_seq = -1
        self._now = now
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self.latency = LatencyRecorder()
        # cumulative counters; status() exposes requests as a *_per_s key
        self.requests = 0
        self.batches = 0          # device dispatches (== jit calls)
        self.batched_rows = 0     # rows that made it into a dispatch
        self.rejections = 0       # staleness rejections
        self.errors = 0
        self._predict = None      # jit'd forward, built on first dispatch
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name="kps-serve-batch", daemon=True)
        self._thread.start()

    # -- request entry points ----------------------------------------------
    def submit(self, x, bound: policy.ReadBound | None = None,
               callback: Callable = lambda result: None) -> None:
        """Async predict: callback fires on the batcher thread with a
        Prediction, or with the StalenessError/Exception that killed the
        request. Never blocks the caller."""
        if self._closed:
            raise RuntimeError("prediction engine is closed")
        # pscheck: disable=PS102 (client boundary: coerces caller-supplied x)
        row = np.asarray(x, dtype=np.float32).reshape(-1)
        self._q.put(_Request(row, bound, callback, time.monotonic()))

    def predict(self, x, bound: policy.ReadBound | None = None, *,
                min_clock: int | None = None, max_age_s: float | None = None,
                timeout: float = 30.0) -> Prediction:
        """Sync predict; raises StalenessError if the bound rejects."""
        if bound is None and (min_clock is not None or max_age_s is not None):
            bound = policy.ReadBound(min_clock=min_clock, max_age_s=max_age_s)
        done = threading.Event()
        box: list = []

        def _cb(result):
            box.append(result)
            done.set()

        self.submit(x, bound, _cb)
        if not done.wait(timeout):
            raise TimeoutError("prediction timed out")
        result = box[0]
        if isinstance(result, BaseException):
            raise result
        return result

    # -- batcher loop -------------------------------------------------------
    def _loop(self) -> None:
        while True:
            first = self._q.get()
            if first is _SENTINEL:
                return
            batch = [first]
            deadline = time.monotonic() + self.deadline_s
            stop = False
            while len(batch) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _SENTINEL:
                    stop = True
                    break
                batch.append(nxt)
            self._serve(batch)
            if stop:
                return

    def _serve(self, batch: list[_Request]) -> None:
        self.requests += len(batch)
        # one snapshot resolution per micro-batch: every row is answered
        # from the same hot-swapped (theta, clock) pair
        snap = self.registry.latest
        now = self._now()
        if self.telemetry.enabled:
            self._m_requests.inc(len(batch))
            if snap is not None:
                # read-side staleness: how old the answering snapshot is
                # at serve time (host floats; one sample per micro-batch)
                self._m_snapshot_age.observe(
                    max(0.0, (now - snap.wall_time) * 1e3))
        live: list[_Request] = []
        for req in batch:
            try:
                policy.check(snap, req.bound, now)
            except policy.StalenessError as err:
                self.rejections += 1
                self.tracer.count("serving.staleness_rejections")
                if self.telemetry.enabled:
                    self._m_rejections.inc()
                self._finish(req, err)
                continue
            live.append(req)
        if not live:
            return
        try:
            labels, confs = self._dispatch(snap, live)
        except Exception as err:  # noqa: BLE001 — fail the rows, not the loop
            self.errors += 1
            for req in live:
                self._finish(req, err)
            return
        self.batches += 1
        self.batched_rows += len(live)
        self.tracer.count("serving.batch_dispatches")
        for i, req in enumerate(live):
            # pscheck: disable=PS102 (labels/confs are host arrays by here)
            self._finish(req, Prediction(int(labels[i]), float(confs[i]),
                                         snap.vector_clock, snap.wall_time))

    def _dispatch(self, snap, live: list[_Request]):
        fn = self._predict_fn()
        xs = np.zeros((self.max_batch, self.task.cfg.num_features),
                      dtype=np.float32)
        for i, req in enumerate(live):
            xs[i, :req.x.size] = req.x[:xs.shape[1]]
        with self.tracer.span("serving.predict", rows=len(live)):
            if snap.trace is not None and snap.seq > self._last_traced_seq:
                # close the delta.wire flow on this snapshot's FIRST
                # serving read: buffer -> solve -> wire -> apply ->
                # publish -> here, one connected arrow chain in Perfetto
                self._last_traced_seq = snap.seq
                self.tracer.flow_end("delta.wire", snap.trace,
                                     clock=snap.vector_clock)
            labels, confs = fn(snap.theta, xs)
            # block so latency samples measure real service time
            labels = np.asarray(labels)  # pscheck: disable=PS102 (deliberate latency-sample sync)
            confs = np.asarray(confs)  # pscheck: disable=PS102 (deliberate latency-sample sync)
        return labels, confs

    def _predict_fn(self):
        if self._predict is None:
            import jax
            import jax.numpy as jnp

            task = self.task

            def _forward(theta, x):
                lg = task.predict_logits(theta, x)
                probs = jax.nn.softmax(lg, axis=-1)
                return jnp.argmax(lg, axis=-1), jnp.max(probs, axis=-1)

            self._predict = jax.jit(_forward)
        return self._predict

    def _finish(self, req: _Request, result) -> None:
        self.latency.record(time.monotonic() - req.t0)
        try:
            req.callback(result)
        except Exception:  # noqa: BLE001 — a bad callback must not stall serving
            self.tracer.count("serving.callback_errors")

    # -- ops surface --------------------------------------------------------
    def stats(self) -> dict:
        occupancy = (round(self.batched_rows / self.batches, 2)
                     if self.batches else 0.0)
        out = {"requests": self.requests, "batches": self.batches,
               "occupancy": occupancy, "rejections": self.rejections,
               "errors": self.errors}
        out.update(self.latency.percentiles_ms(50, 99))
        return out

    def close(self, timeout: float = 30.0) -> None:
        """Stop the batcher thread. Must run before interpreter exit —
        the thread holds jit'd callables (native code)."""
        if self._closed:
            return
        self._closed = True
        self._q.put(_SENTINEL)
        self._thread.join(timeout)
