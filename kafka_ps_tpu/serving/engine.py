"""Micro-batching prediction engine — gang dispatch for the read path.

Requests queue up; a single batcher thread coalesces them until either
`max_batch` rows are waiting or `deadline_s` has elapsed since the first
row arrived, then runs ONE jit'd forward pass over a padded fixed-shape
batch. The amortization argument is identical to training-side gang
dispatch (docs/GANG_DISPATCH.md): dispatch overhead is per-XLA-call, so
k requests per call cost ~1/k of the per-request dispatch tax.

Under load the engine protects itself instead of queueing to death
(docs/SERVING.md, "Operating at load"):

  * admission control — `queue_limit` bounds each tenant's outstanding
    admitted requests; `submit` on a full queue raises a typed
    `policy.OverloadedError` SYNCHRONOUSLY (the transport answers
    OVERLOADED immediately; nothing is parked behind work that cannot
    meet its deadline).  `shed_deadline_s` additionally sheds when the
    predicted queueing delay (backlog / batch capacity x the EWMA batch
    service time) exceeds the budget, even before the queue fills.
  * adaptive micro-batch sizing — dispatch shapes are power-of-two
    buckets of the live row count, capped at `max_batch`: light load
    pays a small batch's compute, heavy load grows the batch toward the
    cap instead of growing the dispatch count.  At most
    log2(max_batch)+1 compiles per model family (`TRACE_COUNTS`
    regression-tests that bound).

Batching itself is a measured decision, not a policy (`auto=True`,
docs/SERVING.md "Dispatch economics"): each tenant carries a
`DispatchCostModel` (serving/costmodel.py) fed by the same per-dispatch
timings that feed `LatencyRecorder`.  Below the learned break-even
occupancy, `submit` bypasses the queue entirely and serves the request
inline on the caller's thread — no window wait, no batcher handoff;
above it, the batcher's collect window is sized from the live arrival
rate instead of always sleeping the full deadline.  A cold or
uncalibrated engine keeps the batching path (the status quo);
`warmup()` calibrates, so warmed engines pick the right mode from the
first request.

Several model families serve from one engine: tenants register via
`add_model(model_id, task, registry)`, requests carry a model id (wire
trailer in runtime/net.py), and each tenant gets its own snapshot
registry and its own admission budget — a hot tenant sheds without
starving the others.

Each per-tenant micro-batch resolves that tenant's registry ONCE — all
its rows are answered from the same (theta, clock) pair, and each row's
read bound is checked against that snapshot (the registry only ever
serves its newest snapshot, so a bound the newest fails no snapshot
passes; see serving/policy.py).

jax imports are deferred to the first dispatch so thin clients can
import this module (for the Prediction type) without a backend.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, NamedTuple

import numpy as np

from kafka_ps_tpu.analysis.lockgraph import OrderedLock
from kafka_ps_tpu.serving import policy
from kafka_ps_tpu.serving.costmodel import DispatchCostModel
from kafka_ps_tpu.serving.snapshot import SnapshotRegistry
from kafka_ps_tpu.telemetry import NULL_TELEMETRY
from kafka_ps_tpu.telemetry.flight import FLIGHT
from kafka_ps_tpu.utils.trace import NULL_TRACER, LatencyRecorder


class Prediction(NamedTuple):
    label: int             # argmax class
    confidence: float      # softmax mass on the argmax class
    vector_clock: int      # clock of the snapshot that answered
    wall_time: float       # publication time of that snapshot


class _Request(NamedTuple):
    x: np.ndarray
    bound: policy.ReadBound | None
    callback: Callable     # called with Prediction or an Exception
    t0: float              # monotonic enqueue time (latency accounting)
    model_id: int          # tenant the request addresses


class _Tenant:
    """One served model family: its task, snapshot ring, compiled
    forward, dispatch cost model, and admission-budget bookkeeping."""

    __slots__ = ("model_id", "task", "registry", "predict", "depth",
                 "last_traced_seq", "cost", "compiled")

    def __init__(self, model_id: int, task, registry: SnapshotRegistry,
                 max_batch: int):
        self.model_id = model_id
        self.task = task
        self.registry = registry
        self.predict = None        # jit'd forward, built on first dispatch
        self.depth = 0             # admitted-but-unserved requests
        # seq of the last snapshot whose delta.wire flow was closed here:
        # the flow ends once, at the snapshot's FIRST serving read
        self.last_traced_seq = -1
        # dispatch economics (serving/costmodel.py): fed by warmup and
        # every live dispatch, read by submit's bypass decision
        self.cost = DispatchCostModel(max_batch)
        # bucket shapes this tenant's jit has seen: first-seen == one
        # XLA compile (jit caches one program per shape)
        self.compiled: set[int] = set()


_SENTINEL = object()

# Compile/dispatch-mode accounting for regression tests (the slab
# TRACE_COUNTS pattern): "compiles" counts first-seen (tenant, bucket)
# dispatch shapes — the test bound is at most one per bucket per model
# family across any batch-size sequence.
TRACE_COUNTS = {"compiles": 0, "batch": 0, "bypass": 0}


def _bucket(n: int, cap: int) -> int:
    """Smallest power of two >= n, capped — the adaptive dispatch shape."""
    b = 1
    while b < n and b < cap:
        b <<= 1
    return min(b, cap)


class PredictionEngine:
    """Deadline/size-capped micro-batcher over per-model snapshot rings
    with bounded admission and explicit load shedding."""

    def __init__(self, task, registry: SnapshotRegistry, *,
                 max_batch: int = 16, deadline_s: float = 0.002,
                 queue_limit: int = 0, shed_deadline_s: float | None = None,
                 adaptive: bool = True, auto: bool = True,
                 tracer=None, telemetry=None, now=time.time):
        self.max_batch = max(1, int(max_batch))
        self.deadline_s = max(0.0, float(deadline_s))
        # 0 = unbounded (the pre-admission-control behavior); > 0 bounds
        # EACH tenant's outstanding admitted requests
        self.queue_limit = max(0, int(queue_limit))
        self.shed_deadline_s = shed_deadline_s
        self.adaptive = adaptive
        # auto dispatch-mode selection: bypass the queue below the cost
        # model's break-even occupancy, size windows from the arrival
        # rate above it.  Decisions only engage once a tenant's model
        # is calibrated (warmup, or live samples covering both ends of
        # the batch-latency curve) — cold engines batch, as before.
        self.auto = bool(auto)
        self.tracer = tracer or NULL_TRACER
        self.telemetry = telemetry or NULL_TELEMETRY
        # pre-resolved metric children (null when telemetry is off):
        # observed per micro-batch, never per row, never on device data
        self._m_snapshot_age = self.telemetry.histogram("snapshot_age_ms")
        self._m_requests = self.telemetry.counter("serving_requests_total")
        self._m_rejections = self.telemetry.counter(
            "serving_rejections_total")
        self._m_queue_depth = self.telemetry.gauge("serving_queue_depth")
        self._m_sheds = self.telemetry.counter("serving_shed_total")
        self._m_batch_size = self.telemetry.histogram("serving_batch_size")
        # per-request wall latency as a bucketed histogram: the serving-
        # latency SLO (telemetry/slo.py) and the rolling critical path
        # need windowed bucket deltas, which the sliding-window
        # LatencyRecorder cannot provide
        self._m_latency = self.telemetry.histogram("serving_latency_ms")
        # dispatch-mode counter family: how often each dispatch path
        # won (the shm transport increments its own child in net.py)
        self._m_mode = {
            "batch": self.telemetry.counter("serving_dispatch_mode",
                                            mode="batch"),
            "bypass": self.telemetry.counter("serving_dispatch_mode",
                                             mode="bypass"),
        }
        self._now = now
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        # admission bookkeeping: depth counters must be exact (they gate
        # sheds), so they move under one leaf lock, never nested
        self._admission = OrderedLock("PredictionEngine.admission")
        # guarded-by: _admission (queue_depth/stats reads are lock-free gauge snapshots)
        self._depth = 0            # total admitted-but-unserved requests
        # inline bypass serves currently running on caller threads:
        # while one is in flight, new arrivals take the queue — that
        # overflow is how sustained concurrency shows up in the cost
        # model's demand signal and flips the engine back to batching
        self._bypassing = 0
        self._ewma_batch_s: float | None = None
        # guarded-by: _admission (add_model writes hold it; steady-state reads are GIL-atomic dict gets)
        self._tenants: dict[int, _Tenant] = {
            0: _Tenant(0, task, registry, self.max_batch)}
        self.latency = LatencyRecorder()
        # cumulative counters; status() exposes requests as a *_per_s key
        # guarded-by: _admission (stats reads are lock-free snapshots)
        self.requests = 0
        # guarded-by: _admission (stats reads are lock-free snapshots)
        self.batches = 0          # device dispatches (== jit calls)
        # guarded-by: _admission (stats reads are lock-free snapshots)
        self.batched_rows = 0     # rows that made it into a dispatch
        # guarded-by: _admission (stats reads are lock-free snapshots)
        self.rejections = 0       # staleness rejections
        self.sheds = 0            # admission-control sheds (typed)
        # guarded-by: _admission (stats reads are lock-free snapshots)
        self.bypasses = 0         # requests served on the fast path
        # guarded-by: _admission (stats reads are lock-free snapshots)
        self.errors = 0
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name="kps-serve-batch", daemon=True)
        self._thread.start()

    # model-0 aliases — the single-tenant surface every existing caller
    # (runtime/app.py, cli/, bench.py, tests) keeps using unchanged
    @property
    def task(self):
        return self._tenants[0].task

    @property
    def registry(self) -> SnapshotRegistry:
        return self._tenants[0].registry

    # -- multi-model surface -------------------------------------------------
    def add_model(self, model_id: int, task,
                  registry: SnapshotRegistry | None = None,
                  capacity: int = 8) -> SnapshotRegistry:
        """Register another served model family.  Returns its registry
        (created fresh when none is passed)."""
        model_id = int(model_id)
        with self._admission:
            if model_id in self._tenants:
                raise ValueError(f"model {model_id} already registered")
            reg = registry if registry is not None \
                else SnapshotRegistry(capacity=capacity)
            self._tenants[model_id] = _Tenant(model_id, task, reg,
                                              self.max_batch)
            return reg

    def model_ids(self) -> tuple[int, ...]:
        return tuple(sorted(self._tenants))

    def registry_for(self, model_id: int) -> SnapshotRegistry:
        return self._tenants[model_id].registry

    # -- request entry points ----------------------------------------------
    def submit(self, x, bound: policy.ReadBound | None = None,
               callback: Callable = lambda result: None, *,
               model_id: int = 0) -> None:
        """Async predict: callback fires on the batcher thread with a
        Prediction, or with the StalenessError/Exception that killed the
        request. Never blocks the caller; raises
        policy.OverloadedError synchronously when admission control
        sheds the request (reject fast — nothing is enqueued)."""
        if self._closed:
            raise RuntimeError("prediction engine is closed")
        tenant = self._tenants.get(model_id)
        if tenant is None:
            raise ValueError(f"unknown model id {model_id}")
        with self._admission:
            if self.queue_limit and tenant.depth >= self.queue_limit:
                self._shed(tenant, f"admission queue full "
                                   f"({tenant.depth}/{self.queue_limit})")
            if self.shed_deadline_s is not None \
                    and self._ewma_batch_s is not None:
                # predicted queueing delay: batches ahead of this row x
                # the EWMA batch service time — when that already blows
                # the deadline budget, queueing is a slower way to fail
                predicted = ((self._depth // self.max_batch + 1)
                             * self._ewma_batch_s)
                if predicted > self.shed_deadline_s:
                    self._shed(tenant,
                               f"predicted queueing delay "
                               f"{predicted * 1e3:.1f}ms > shed deadline "
                               f"{self.shed_deadline_s * 1e3:.1f}ms")
            tenant.depth += 1
            self._depth += 1
            tenant.cost.observe_arrival(time.monotonic())
            # bypass decision, made per request at admission: below the
            # learned engage threshold batching buys nothing — serve on
            # the caller's thread (no window wait, no batcher handoff).
            # Two inline lanes run concurrently with the batcher: the
            # jit'd forward is thread-safe and releases the GIL inside
            # XLA, so a second lane overlaps real compute while the
            # queue keeps the overflow; past two lanes the marginal
            # inline serve just adds scheduler contention, and overflow
            # through the queue is what feeds the demand estimate that
            # re-engages batching under sustained concurrency.
            bypass = (self.auto and self._bypassing < 2
                      and tenant.cost.bypass())
            if bypass:
                self._bypassing += 1
            if self.telemetry.enabled:
                self._m_queue_depth.set(self._depth)
        # pscheck: disable=PS102 (client boundary: coerces caller-supplied x)
        row = np.asarray(x, dtype=np.float32).reshape(-1)
        req = _Request(row, bound, callback, time.monotonic(), model_id)
        if bypass:
            try:
                self._serve([req], mode="bypass")
            finally:
                with self._admission:
                    self._bypassing -= 1
        else:
            self._q.put(req)

    def _shed(self, tenant: _Tenant, why: str):
        """Count + raise the typed rejection (admission lock held)."""
        self.sheds += 1
        self.tracer.count("serving.sheds")
        if self.telemetry.enabled:
            self._m_sheds.inc()
        raise policy.OverloadedError(
            f"request shed: {why}", queue_depth=tenant.depth,
            queue_limit=self.queue_limit or None, model_id=tenant.model_id)

    def predict(self, x, bound: policy.ReadBound | None = None, *,
                min_clock: int | None = None, max_age_s: float | None = None,
                model_id: int = 0, timeout: float = 30.0) -> Prediction:
        """Sync predict; raises StalenessError if the bound rejects and
        OverloadedError if admission control sheds."""
        if bound is None and (min_clock is not None or max_age_s is not None):
            bound = policy.ReadBound(min_clock=min_clock, max_age_s=max_age_s)
        done = threading.Event()
        box: list = []

        def _cb(result):
            box.append(result)
            done.set()

        self.submit(x, bound, _cb, model_id=model_id)
        if not done.wait(timeout):
            raise TimeoutError("prediction timed out")
        result = box[0]
        if isinstance(result, BaseException):
            raise result
        return result

    # -- batcher loop -------------------------------------------------------
    def _loop(self) -> None:
        while True:
            first = self._q.get()
            if first is _SENTINEL:
                return
            batch = [first]
            stop = False
            # instant drain: rows already queued joined while the last
            # window served — batching them costs no wait at all.  A
            # calibrated auto engine sizes the drain by regime: below
            # the engage threshold it serves ONE row per cycle (the
            # serial path — wake-ups stay staggered, the standing
            # backlog keeps the batcher hot, exactly the dynamics that
            # make an unbatched engine fast); once batching engages it
            # drains the backlog but LEAVES ONE ROW BEHIND, so the
            # batcher re-enters get() without parking on the futex and
            # client wake-ups overlap the next dispatch instead of
            # bursting behind a sleeping thread.  The leftover waits
            # exactly one dispatch, never a window.
            limit = self.max_batch
            if self.auto:
                cost = self._tenants[first.model_id].cost
                if cost.calibrated:
                    limit = 1 if cost.bypass() \
                        else min(limit, max(1, self._q.qsize()))
            while len(batch) < limit:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is _SENTINEL:
                    stop = True
                    break
                batch.append(nxt)
            # window sizing: a calibrated auto engine waits only as
            # long as the live arrival rate needs to fill the batch
            # (zero in the bypass regime); otherwise the configured
            # deadline, the pre-cost-model behavior.  The window opens
            # ONLY when the drain ran the queue dry — with a standing
            # backlog the batch already sized itself to the load, and
            # waiting on top of rows in hand just stalls the pipeline.
            if not stop and len(batch) < limit:
                deadline = time.monotonic() + self._window_s(first)
                while len(batch) < self.max_batch:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        nxt = self._q.get(timeout=remaining)
                    except queue.Empty:
                        break
                    if nxt is _SENTINEL:
                        stop = True
                        break
                    batch.append(nxt)
            self._serve(batch)
            if stop:
                return

    def queue_depth(self) -> int:
        """Admitted-but-unserved requests right now (host int; the
        serving watchdog's demand predicate, telemetry/health.py)."""
        return self._depth

    def _window_s(self, first: _Request) -> float:
        tenant = self._tenants[first.model_id]
        if self.auto and tenant.cost.calibrated:
            return tenant.cost.window_s(1, self.deadline_s)
        return self.deadline_s

    def _serve(self, batch: list[_Request], mode: str = "batch") -> None:
        cost = self._tenants[batch[0].model_id].cost
        with self._admission:
            self.requests += len(batch)
            if mode == "bypass":
                self.bypasses += len(batch)
            for req in batch:
                self._tenants[req.model_id].depth -= 1
            self._depth -= len(batch)
            if self.telemetry.enabled:
                self._m_queue_depth.set(self._depth)
        TRACE_COUNTS[mode] += 1
        if FLIGHT.enabled:
            FLIGHT.record("serving.batch", n=len(batch),
                          depth=self._depth, mode=mode,
                          occupancy=round(cost.occupancy, 2),
                          break_even=round(cost.break_even, 2))
            FLIGHT.beat("serving")
        if self.telemetry.enabled:
            self._m_requests.inc(len(batch))
            self._m_mode[mode].inc()
        # what a full drain could have collected right now — the demand
        # sample the cost model sizes future windows against (None for
        # bypass serves, which never see the queue)
        avail = None
        if mode == "batch":
            avail = min(self.max_batch, len(batch) + self._q.qsize())
        # group by tenant, preserving arrival order within each group:
        # one collected window serves every model family present in it
        # (round-robin over model ids — no tenant waits an extra window)
        groups: dict[int, list[_Request]] = {}
        for req in batch:
            groups.setdefault(req.model_id, []).append(req)
        t_start = time.monotonic()
        for model_id in sorted(groups):
            self._serve_tenant(self._tenants[model_id],
                               groups[model_id], mode, avail)
        # EWMA of the window's service time feeds predictive shedding
        dt = time.monotonic() - t_start
        with self._admission:
            self._ewma_batch_s = dt if self._ewma_batch_s is None \
                else 0.2 * dt + 0.8 * self._ewma_batch_s

    def _serve_tenant(self, tenant: _Tenant, batch: list[_Request],
                      mode: str = "batch",
                      avail: int | None = None) -> None:
        # one snapshot resolution per tenant micro-batch: every row is
        # answered from the same hot-swapped (theta, clock) pair
        snap = tenant.registry.latest
        now = self._now()
        if self.telemetry.enabled and snap is not None:
            # read-side staleness: how old the answering snapshot is
            # at serve time (host floats; one sample per micro-batch)
            self._m_snapshot_age.observe(
                max(0.0, (now - snap.wall_time) * 1e3))
        live: list[_Request] = []
        for req in batch:
            try:
                policy.check(snap, req.bound, now)
            except policy.StalenessError as err:
                with self._admission:
                    self.rejections += 1
                self.tracer.count("serving.staleness_rejections")
                if self.telemetry.enabled:
                    self._m_rejections.inc()
                self._finish(req, err)
                continue
            live.append(req)
        if not live:
            return
        try:
            labels, confs = self._dispatch(tenant, snap, live, mode, avail)
        except Exception as err:  # noqa: BLE001 — fail the rows, not the loop
            with self._admission:
                self.errors += 1
            for req in live:
                self._finish(req, err)
            return
        with self._admission:
            # bypass serves run on caller threads, concurrent with the
            # batcher: dispatch counters move under the same leaf lock
            # as the depth bookkeeping
            self.batches += 1
            self.batched_rows += len(live)
        self.tracer.count("serving.batch_dispatches")
        if self.telemetry.enabled:
            self._m_batch_size.observe(len(live))
        for i, req in enumerate(live):
            # labels/confs are host arrays by here
            self._finish(req, Prediction(int(labels[i]), float(confs[i]),
                                         snap.vector_clock, snap.wall_time))

    def _dispatch(self, tenant: _Tenant, snap, live: list[_Request],
                  mode: str = "batch", avail: int | None = None):
        fn = self._predict_fn(tenant)
        # adaptive shape: a power-of-two bucket of the live count means
        # light load dispatches a small batch's compute while heavy load
        # grows toward max_batch — batch size, not dispatch count,
        # absorbs the offered rate (jit caches one program per bucket)
        rows = _bucket(len(live), self.max_batch) if self.adaptive \
            else self.max_batch
        self._note_shape(tenant, rows)
        t0 = time.monotonic()
        xs = np.zeros((rows, tenant.task.cfg.num_features),
                      dtype=np.float32)
        for i, req in enumerate(live):
            xs[i, :req.x.size] = req.x[:xs.shape[1]]
        with self.tracer.span("serving.predict", rows=len(live)):
            if snap.trace is not None and snap.seq > tenant.last_traced_seq:
                # close the delta.wire flow on this snapshot's FIRST
                # serving read: buffer -> solve -> wire -> apply ->
                # publish -> here, one connected arrow chain in Perfetto
                tenant.last_traced_seq = snap.seq
                self.tracer.flow_end("delta.wire", snap.trace,
                                     clock=snap.vector_clock)
            labels, confs = fn(snap.theta, xs)
            # block so latency samples measure real service time
            labels = np.asarray(labels)  # pscheck: disable=PS102 (deliberate latency-sample sync)
            confs = np.asarray(confs)  # pscheck: disable=PS102 (deliberate latency-sample sync)
        # the same sample that feeds LatencyRecorder/tracing calibrates
        # the cost model: assembly + device call + sync, one bucket
        tenant.cost.observe_dispatch(len(live), rows,
                                     time.monotonic() - t0,
                                     batched=(mode == "batch"),
                                     avail=avail)
        return labels, confs

    def _note_shape(self, tenant: _Tenant, rows: int) -> None:
        """First-seen dispatch shapes are XLA compiles (jit caches one
        program per shape) — the TRACE_COUNTS regression surface."""
        fresh = False
        with self._admission:
            if rows not in tenant.compiled:
                tenant.compiled.add(rows)
                fresh = True
        if fresh:
            TRACE_COUNTS["compiles"] += 1

    def _predict_fn(self, tenant: _Tenant):
        if tenant.predict is None:
            import jax
            import jax.numpy as jnp

            task = tenant.task

            def _forward(theta, x):
                lg = task.predict_logits(theta, x)
                probs = jax.nn.softmax(lg, axis=-1)
                return jnp.argmax(lg, axis=-1), jnp.max(probs, axis=-1)

            # double-checked under the admission lock: bypass serves
            # run on caller threads, so two first dispatches can race
            # here — exactly one jit (and its shape cache) must win
            with self._admission:
                if tenant.predict is None:
                    tenant.predict = jax.jit(_forward)  # pscheck: disable=PS101 (built once, cached on the tenant)
        return tenant.predict

    def warmup(self, model_id: int = 0) -> int:
        """Compile every adaptive bucket shape for a tenant against its
        current snapshot (no-op when none is published).  Call before
        measuring latency: a first-request XLA compile is orders of
        magnitude over the deadline and would land in some poor
        client's p99.  Each bucket is then timed with a SECOND,
        compile-free call to seed the dispatch cost model — a warmed
        engine is calibrated before its first request.  Returns the
        number of shapes compiled."""
        tenant = self._tenants[model_id]
        snap = tenant.registry.latest
        if snap is None:
            return 0
        fn = self._predict_fn(tenant)
        shapes = 0
        b = 1 if self.adaptive else self.max_batch
        while True:
            xs = np.zeros((b, tenant.task.cfg.num_features), np.float32)
            labels, _ = fn(snap.theta, xs)
            np.asarray(labels)          # sync: compile finished
            self._note_shape(tenant, b)
            t0 = time.monotonic()
            labels, _ = fn(snap.theta, xs)
            np.asarray(labels)          # sync: steady-state timing
            tenant.cost.seed(b, time.monotonic() - t0)
            shapes += 1
            if b >= self.max_batch:
                return shapes
            b <<= 1

    def _finish(self, req: _Request, result) -> None:
        elapsed = time.monotonic() - req.t0
        self.latency.record(elapsed)
        if self.telemetry.enabled:
            self._m_latency.observe(elapsed * 1e3)
        try:
            req.callback(result)
        except Exception:  # noqa: BLE001 — a bad callback must not stall serving
            self.tracer.count("serving.callback_errors")

    # -- ops surface --------------------------------------------------------
    def stats(self) -> dict:
        occupancy = (round(self.batched_rows / self.batches, 2)
                     if self.batches else 0.0)
        cost = self._tenants[0].cost
        out = {"requests": self.requests, "batches": self.batches,
               "occupancy": occupancy, "rejections": self.rejections,
               "sheds": self.sheds, "queue_depth": self._depth,
               "errors": self.errors, "bypasses": self.bypasses,
               # the regime the next lone request would be served in
               "mode": ("bypass" if self.auto and cost.bypass()
                        else "batch"),
               "break_even": round(cost.break_even, 2),
               "arrival_qps": round(cost.arrival_qps, 1)}
        out.update(self.latency.percentiles_ms(50, 99))
        return out

    def close(self, timeout: float = 30.0) -> None:
        """Stop the batcher thread. Must run before interpreter exit —
        the thread holds jit'd callables (native code)."""
        if self._closed:
            return
        self._closed = True
        self._q.put(_SENTINEL)
        self._thread.join(timeout)
