"""Online dispatch cost model — batching as a measured decision.

The engine's micro-batching default is only the right call when enough
rows share a window to amortize the per-XLA-call dispatch tax.  Below
that occupancy, batching is pure loss: the window wait buys nothing and
the padded dispatch costs the same as a single-row one.  Clipper calls
this out directly (adaptive batching, NSDI'17) and Nexus builds its
whole scheduler on the batch-latency curve (SOSP'19): the break-even
point is a *property of the model family*, so it must be measured, not
configured.

`DispatchCostModel` learns three things per tenant, all from samples
the engine already produces (the same per-dispatch timings that feed
`LatencyRecorder` and the `serving.predict` tracer span — no new
instrumentation on the hot path):

  * `t(bucket)` — EWMA wall seconds of one padded dispatch per
    power-of-two bucket shape.  `PredictionEngine.warmup` seeds every
    bucket with a second, compile-free timed call, so a warmed engine
    is calibrated before the first client request.
  * occupancy — EWMA rows per dispatch, the live estimate of how many
    rows a batching window actually collects under the current load.
  * arrival rate — EWMA inter-arrival seconds of admitted requests
    (the same signal the predictive shed estimator reasons about),
    used to size the batching window instead of always sleeping the
    full configured deadline.

Break-even occupancy falls out of the timings: batching k rows costs
`t(max_bucket) / k` per row against `t(1)` unbatched, so batching wins
iff `k > t(max_bucket) / t(1)`.  The t-ratio is necessary but not
sufficient — a micro-batch also convoys its clients' wake-ups, a cost
the dispatch timings cannot see — so batching only ENGAGES once the
measured backlog clears `max(break_even, max_batch/2)`.  Below that the
engine serves inline on caller threads (up to two lanes) and serves
queued overflow one row per cycle; the overflow's backlog feeds the
demand estimate that re-engages batching the moment sustained
concurrency returns (docs/SERVING.md, "Dispatch economics").
"""

from __future__ import annotations

import math


class DispatchCostModel:
    """Per-model-family dispatch economics, learned online.

    All updates are single float/dict stores (GIL-atomic); callers may
    feed it from the batcher thread and request threads concurrently
    without a lock — a lost EWMA sample is noise, not corruption.
    """

    # demand within this margin of break-even counts as below it: the
    # boundary region is measurement noise, and the EWMA decays toward
    # 1.0 asymptotically from above — ties must not strand the engine
    # in batch mode paying window waits for nothing
    BYPASS_SLACK = 0.25
    # batching must also fill a decisive fraction of capacity before it
    # engages.  The t-ratio break-even only prices the XLA dispatch; a
    # micro-batch additionally convoys its clients' wake-ups (k events
    # set back-to-back, k callers contending for the scheduler at
    # once), a cost the dispatch timings cannot see.  Measured on a
    # contended host, half-full windows trade even at best against
    # serving the backlog one row at a time with staggered wake-ups —
    # so below half capacity the engine keeps the serial queued path
    # and batching waits for demand that decisively amortizes.
    BATCH_FLOOR_FRAC = 0.5

    def __init__(self, max_batch: int, *, alpha: float = 0.2):
        self.max_batch = max(1, int(max_batch))
        self.alpha = alpha
        # EWMA dispatch seconds per bucket shape; seeded by warmup,
        # refined by every live dispatch
        self._t: dict[int, float] = {}
        # EWMA rows per dispatch, over ALL dispatches (reporting)
        self.occupancy = 1.0
        # EWMA rows AVAILABLE per queued-path serve — the decision
        # signal.  Sampled as what a full drain could have collected
        # (rows served + rows still queued, capped at max_batch), NOT
        # what this serve took: in the serial regime every queued serve
        # is one row, so serve size alone could never report demand
        # deep enough to re-engage batching.  Bypass serves are
        # excluded: they never see the queue, so they say nothing about
        # what a batching window would collect.  Sustained concurrency
        # overflows the inline lanes into the queue, shows up here
        # within a few serves, and flips the engine to batching; a
        # lone closed-loop client never does, and stays on the fast
        # path.
        self.demand = 1.0
        # EWMA seconds between admitted requests
        self._interarrival_s: float | None = None
        self._last_arrival: float | None = None
        self.dispatches = 0

    # -- sample intake ------------------------------------------------------

    def observe_arrival(self, t_mono: float) -> None:
        """One admitted request at monotonic time `t_mono`."""
        last, self._last_arrival = self._last_arrival, t_mono
        if last is None:
            return
        gap = t_mono - last
        if gap < 0.0:
            return
        self._interarrival_s = gap if self._interarrival_s is None \
            else self.alpha * gap + (1 - self.alpha) * self._interarrival_s

    def observe_dispatch(self, rows: int, bucket: int, dt_s: float,
                         batched: bool = True,
                         avail: int | None = None) -> None:
        """One completed dispatch: `rows` live rows padded to `bucket`
        took `dt_s` wall seconds (assembly + device call + sync).
        `avail` is the backlog a full drain could have collected at
        serve time (rows + still-queued, engine-capped at max_batch);
        it feeds the demand estimate when given, `rows` otherwise.
        `batched=False` marks a bypass serve — it refines the timing
        curve but not the demand estimate (see `demand`)."""
        self.dispatches += 1
        have = self._t.get(bucket)
        self._t[bucket] = dt_s if have is None \
            else self.alpha * dt_s + (1 - self.alpha) * have
        self.occupancy = (self.alpha * rows
                          + (1 - self.alpha) * self.occupancy)
        if batched:
            sample = min(self.max_batch, avail) if avail is not None \
                else rows
            self.demand = (self.alpha * sample
                           + (1 - self.alpha) * self.demand)

    def seed(self, bucket: int, dt_s: float) -> None:
        """Warmup calibration: a compile-free timed dispatch of this
        bucket shape.  Overwrites any prior estimate — a fresh steady-
        state sample beats a stale one."""
        self._t[bucket] = dt_s

    # -- the learned quantities ---------------------------------------------

    @property
    def calibrated(self) -> bool:
        """Both ends of the batch-latency curve measured: trust the
        break-even estimate only once t(1) and t(max_bucket) exist."""
        return 1 in self._t and self.max_batch in self._t

    @property
    def arrival_qps(self) -> float:
        ia = self._interarrival_s
        return 0.0 if not ia else 1.0 / ia

    @property
    def break_even(self) -> float:
        """Occupancy above which batched dispatch beats per-request
        dispatch: t(max_bucket) / t(1), floored at 1 (batching a single
        row is never cheaper than dispatching it)."""
        t1 = self._t.get(1)
        tb = self._t.get(self.max_batch)
        if not t1 or not tb:
            return 1.0
        return max(1.0, tb / t1)

    # -- the decisions ------------------------------------------------------

    @property
    def engage_threshold(self) -> float:
        """Demand above which the queued path switches from serving
        rows serially to micro-batching them: the dispatch-cost
        break-even OR the half-capacity floor, whichever is higher
        (see BATCH_FLOOR_FRAC for why the t-ratio alone is not
        sufficient)."""
        return max(self.break_even + self.BYPASS_SLACK,
                   self.BATCH_FLOOR_FRAC * self.max_batch)

    def bypass(self) -> bool:
        """Stay off the batching regime?  True while the measured
        backlog sits below the engage threshold — windows would
        collect too few rows to pay for themselves.  In this regime
        the engine serves inline on caller threads when a lane is
        free and serves queued overflow one row per cycle (staggered
        wake-ups); batching engages only on demand that decisively
        amortizes.  Always False uncalibrated: the cold default is
        the batching path (the status quo)."""
        return self.calibrated and self.demand < self.engage_threshold

    def window_s(self, have: int, deadline_s: float) -> float:
        """How long the batcher should wait for more rows, given `have`
        already collected.  Zero in the bypass regime (rows only reach
        the queue there on a concurrent burst — serve them now); else
        the time the live arrival rate needs to fill the batch to the
        MEASURED demand, capped at the configured deadline.  The fill
        target is demand, not capacity: at demand d << max_batch,
        waiting to fill max_batch stalls every collected row for
        (max_batch - d) interarrivals it will never collect — the very
        fixed-window regression adaptive dispatch exists to close."""
        if not self.calibrated:
            return deadline_s
        if self.bypass():
            return 0.0
        target = min(self.max_batch, math.ceil(self.demand))
        if have >= target:
            return 0.0
        ia = self._interarrival_s
        if not ia:
            return deadline_s
        # waiting one interarrival buys one row; dispatching what we
        # have costs t(1)-ish and keeps collecting DURING the dispatch.
        # So a wait only pays when arrivals outpace an unbatched
        # dispatch — otherwise any window re-opens the closed-loop
        # spiral (slow serving -> depressed arrival rate -> longer
        # window -> slower serving) that parks latency at the deadline.
        if ia > self._t.get(1, math.inf):
            return 0.0
        return min(deadline_s, (target - have) * ia)

    def as_dict(self) -> dict:
        """Host-value summary for stats()/flight events."""
        return {"calibrated": self.calibrated,
                "break_even": round(self.break_even, 2),
                "engage_threshold": round(self.engage_threshold, 2),
                "occupancy": round(self.occupancy, 2),
                "demand": round(self.demand, 2),
                "arrival_qps": round(self.arrival_qps, 1),
                "dispatches": self.dispatches}
