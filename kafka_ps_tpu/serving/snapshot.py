"""Immutable model snapshots with lock-free hot swap.

The server publishes a snapshot at every consistency-gate release (see
ServerNode.publish_snapshot): the exact theta the released workers were
sent, stamped with the stable vector clock at that moment. Snapshots
alias the server's device array — safe because ServerNode only ever
*replaces* theta, never mutates it in place.

That replace-never-mutate contract now has a second consumer: the
async eval engine's pending queue (evaluation/engine.py) holds the
same kind of theta aliases, keyed by WORKER-0 CADENCE clocks rather
than the gate-release stable clocks published here — which is why the
engine takes its snapshots directly from the apply path instead of
tapping this registry (the two clock sequences differ, and the eval
CSV's bitwise contract is defined over the cadence sequence).

Readers (the prediction engine, any thread calling `latest`) take no
lock: publication builds the complete Snapshot first and then swaps one
reference, which is atomic under the GIL. A reader therefore always
sees a fully-formed (theta, clock, time) triple — never a torn mix of
two publications. The publisher-side lock only serialises concurrent
publishers (threaded runtime: drive threads + fused loop).

A bounded ring keeps the newest `capacity` snapshots for exact-clock
audit reads (`at_clock`); older ones fall off and become unreachable.
"""

from __future__ import annotations

import collections
import time
from typing import NamedTuple

from kafka_ps_tpu.analysis.lockgraph import OrderedLock
from kafka_ps_tpu.serving import policy


class Snapshot(NamedTuple):
    theta: object          # device or host array; immutable by contract
    vector_clock: int      # stable clock: min active-worker clock at publish
    wall_time: float       # publication time (registry's clock)
    seq: int               # monotonically increasing publication number
    # trace context of the gradient whose gate release published this
    # snapshot (docs/OBSERVABILITY.md); None when tracing is off —
    # defaulted so existing 4-positional constructions stay valid
    trace: object = None


class SnapshotRegistry:
    """Bounded ring of published snapshots with a lock-free `latest`."""

    def __init__(self, capacity: int = 8, now=time.time):
        self._ring: collections.deque[Snapshot] = collections.deque(
            maxlen=max(1, int(capacity)))
        self._latest: Snapshot | None = None
        self._seq = 0
        self._now = now
        self._publish_lock = OrderedLock("SnapshotRegistry.publish")

    def publish(self, theta, vector_clock: int,
                wall_time: float | None = None,
                trace=None) -> Snapshot:
        with self._publish_lock:
            self._seq += 1
            snap = Snapshot(
                theta, int(vector_clock),
                self._now() if wall_time is None else float(wall_time),
                self._seq, trace)
            self._ring.append(snap)
            # single atomic reference swap — this is the hot-swap point;
            # readers of `latest` never block on the publish lock
            self._latest = snap
        return snap

    @property
    def latest(self) -> Snapshot | None:
        return self._latest

    def snapshots(self) -> tuple[Snapshot, ...]:
        """The retained ring, oldest first."""
        return tuple(self._ring)

    def __len__(self) -> int:
        return len(self._ring)

    def get(self, bound: policy.ReadBound | None = None, *,
            min_clock: int | None = None, max_age_s: float | None = None,
            at_clock: int | None = None, now: float | None = None) -> Snapshot:
        """Newest snapshot satisfying the bound, or raise StalenessError.

        Accepts either a ReadBound or the individual fields (not both).
        """
        if bound is None:
            bound = policy.ReadBound(min_clock=min_clock,
                                     max_age_s=max_age_s, at_clock=at_clock)
        elif min_clock is not None or max_age_s is not None \
                or at_clock is not None:
            raise ValueError("pass either a ReadBound or keyword fields")
        now = self._now() if now is None else now
        if bound.at_clock is not None:
            snap = self._find_clock(bound.at_clock)
        else:
            snap = self._latest
        policy.check(snap, bound, now)
        return snap

    def _find_clock(self, clock: int) -> Snapshot | None:
        # newest-first so duplicate clocks (e.g. the cold-start publish
        # followed by the first gate release at the same clock) resolve
        # to the most recent publication
        for snap in reversed(tuple(self._ring)):
            if snap.vector_clock == clock:
                return snap
        raise policy.StalenessError(
            f"no retained snapshot at clock {clock} "
            f"(ring keeps the newest {self._ring.maxlen})",
            min_clock=clock,
            have_clock=None if self._latest is None
            else self._latest.vector_clock)


class MultiModelRegistry:
    """SnapshotRegistry per model id — several model families serving
    from one process (multi-tenant serving, docs/SERVING.md).

    Pure routing: each tenant keeps its own independent snapshot ring
    (its own publisher, its own staleness story); this class only maps
    the wire-level model id to the right ring.  The engine layers
    per-tenant admission budgets on top (serving/engine.py), so one hot
    model family sheds without starving the others.
    """

    def __init__(self):
        self._registries: dict[int, SnapshotRegistry] = {}
        self._lock = OrderedLock("MultiModelRegistry.register")

    def register(self, model_id: int,
                 registry: SnapshotRegistry | None = None,
                 capacity: int = 8) -> SnapshotRegistry:
        """Idempotent: returns the existing ring when `model_id` is
        already registered (and rejects replacing it with a different
        one — a tenant's ring is its serving history)."""
        with self._lock:
            have = self._registries.get(model_id)
            if have is not None:
                if registry is not None and registry is not have:
                    raise ValueError(
                        f"model {model_id} already registered")
                return have
            reg = registry if registry is not None \
                else SnapshotRegistry(capacity=capacity)
            self._registries[int(model_id)] = reg
            return reg

    def get(self, model_id: int) -> SnapshotRegistry | None:
        return self._registries.get(model_id)

    def model_ids(self) -> tuple[int, ...]:
        return tuple(sorted(self._registries))

    def __len__(self) -> int:
        return len(self._registries)


class FrontierCutPublisher:
    """Cross-shard consistent snapshots (range sharding, docs/SHARDING.md).

    A sharded server group cannot publish per-release snapshots the way
    one server does — shard thetas advance independently, and a reader
    must never see a torn mix of shard states at different clocks.  A
    publication here is a CUT: the vector of per-shard
    (theta_slice, stable_clock) pairs read at a drive-loop quiescent
    point, published only when the common clock frontier (the min of
    the per-shard clocks) has ADVANCED past the last published one.
    The concatenated slices become one full-range snapshot stamped with
    the frontier clock, so every serving/policy.py staleness rule —
    min_clock, max_age_s, at_clock audit reads — keeps exactly today's
    meaning: a snapshot at clock c still guarantees every shard has
    incorporated all rounds below c."""

    def __init__(self, registry: SnapshotRegistry):
        self.registry = registry
        self._last_frontier = -1

    def maybe_publish(self, cut, trace=None) -> Snapshot | None:
        """`cut`: [(theta_slice, clock), ...] in shard-id order; a
        slice may be a zero-arg callable evaluated only on publication
        (lazy cuts, ShardedServerGroup.snapshot_cut — a tiered store
        must not assemble pages for a cut that publishes nothing).  The
        frontier is min(clock); publishes and returns the snapshot when
        it advanced, else None (no torn/duplicate publications)."""
        import numpy as np
        frontier = min(clock for _, clock in cut)
        if frontier <= self._last_frontier:
            return None
        theta = np.concatenate([np.asarray(s() if callable(s) else s)
                                for s, _ in cut])
        snap = self.registry.publish(theta, frontier, trace=trace)
        self._last_frontier = frontier
        return snap
