"""Closed- and open-loop load generation for the serving plane
(docs/SERVING.md, "Operating at load").

Two questions a serving stack must answer with numbers, not vibes:

  * where is the knee — the max sustained QPS at which p99 still meets
    the deadline SLO (`find_knee`), and
  * how does it fail past the knee — explicit typed sheds with the
    accepted requests still fast (`OverloadedError` counted separately
    from staleness and transport errors).

Closed loop (`run_closed_loop`) models a fixed fleet of synchronous
callers: N threads each issuing back-to-back requests — throughput
adapts to service time, so it measures capacity, not latency under a
target rate.  Open loop (`run_open_loop`) models independent arrivals:
a Poisson or bursty schedule fixes WHEN each request fires regardless
of how the previous one fared; latency is measured from the scheduled
arrival (not the actual send), so client-side lag counts against the
server — the coordinated-omission-safe convention.

Targets abstract the two paths the engine serves: `EngineTarget` drives
the in-process `PredictionEngine`, `SocketTarget` drives a serving port
through per-thread `PredictClient`s (one outstanding request per
connection, like real thin clients).  Both are jax-free.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

import numpy as np

from kafka_ps_tpu.analysis.lockgraph import OrderedLock
from kafka_ps_tpu.serving import policy
from kafka_ps_tpu.utils.trace import LatencyRecorder


@dataclass
class LoadResult:
    """One load run's ledger.  Latency percentiles cover ACCEPTED
    (OK) requests only — a fast typed rejection must not flatter p99."""

    requests: int
    ok: int
    stale: int
    shed: int
    errors: int
    duration_s: float
    achieved_qps: float
    p50_ms: float | None
    p99_ms: float | None
    offered_qps: float | None = None   # None for closed-loop runs

    @property
    def shed_rate(self) -> float:
        return self.shed / max(self.requests, 1)

    def meets(self, deadline_ms: float) -> bool:
        """Did this run sustain the SLO: every request answered, p99 of
        accepted requests within the deadline, nothing shed?"""
        return (self.ok > 0 and self.shed == 0 and self.errors == 0
                and self.p99_ms is not None
                and self.p99_ms <= deadline_ms)

    def as_dict(self) -> dict:
        out = {"requests": self.requests, "ok": self.ok,
               "stale": self.stale, "shed": self.shed,
               "errors": self.errors,
               "duration_s": round(self.duration_s, 3),
               "achieved_qps": round(self.achieved_qps, 1),
               "p50_ms": self.p50_ms, "p99_ms": self.p99_ms,
               "shed_rate": round(self.shed_rate, 4)}
        if self.offered_qps is not None:
            out["offered_qps"] = round(self.offered_qps, 1)
        return out


class EngineTarget:
    """Drive an in-process serving.engine.PredictionEngine."""

    def __init__(self, engine, bound: policy.ReadBound | None = None,
                 model_id: int = 0, timeout: float = 30.0):
        self.engine = engine
        self.bound = bound
        self.model_id = model_id
        self.timeout = timeout

    def make_issue(self):
        engine, bound = self.engine, self.bound
        model_id, timeout = self.model_id, self.timeout

        def _issue(x):
            return engine.predict(x, bound, model_id=model_id,
                                  timeout=timeout)

        return _issue

    def close(self) -> None:
        pass                        # the engine belongs to the caller


class SocketTarget:
    """Drive a serving socket through per-thread PredictClients.

    One client per driver thread — the PredictClient contract is one
    outstanding request per connection, so concurrency comes from the
    thread count, exactly like a fleet of thin clients."""

    def __init__(self, host: str, port: int, *,
                 min_clock: int | None = None,
                 max_age_s: float | None = None, model_id: int = 0,
                 reconnect: bool = False, timeout: float = 30.0,
                 shm: bool = False):
        self.host, self.port = host, port
        self.min_clock, self.max_age_s = min_clock, max_age_s
        self.model_id = model_id
        self.reconnect = reconnect
        self.timeout = timeout
        self.shm = shm          # per-client shared-memory negotiation
        self._clients: list = []
        self._lock = OrderedLock("loadgen.SocketTarget.clients")

    def make_issue(self):
        from kafka_ps_tpu.runtime import net
        client = net.PredictClient(self.host, self.port,
                                   timeout=self.timeout,
                                   reconnect=self.reconnect,
                                   model_id=self.model_id,
                                   shm=self.shm)
        with self._lock:
            self._clients.append(client)
        min_clock, max_age_s = self.min_clock, self.max_age_s

        def _issue(x):
            return client.predict(x, min_clock, max_age_s)

        return _issue

    def close(self) -> None:
        with self._lock:
            clients, self._clients = self._clients, []
        for c in clients:
            c.close()


class RoundRobinTarget:
    """Spread driver threads across replica targets, round-robin.

    Models a client fleet balanced over N serving endpoints (the k8s
    Service in front of deploy/k8s/replica.yaml): each driver thread is
    pinned to one replica for its lifetime, consecutive threads land on
    consecutive replicas."""

    def __init__(self, targets):
        if not targets:
            raise ValueError("need at least one target")
        self.targets = list(targets)
        self._next = 0
        self._lock = OrderedLock("loadgen.RoundRobinTarget.next")

    def make_issue(self):
        with self._lock:
            target = self.targets[self._next % len(self.targets)]
            self._next += 1
        return target.make_issue()

    def close(self) -> None:
        for t in self.targets:
            t.close()


# -- arrival processes -------------------------------------------------------

def poisson_arrivals(rate_qps: float, duration_s: float,
                     rng: np.random.Generator) -> np.ndarray:
    """Absolute arrival times in [0, duration): exponential
    inter-arrivals at `rate_qps` — independent memoryless clients."""
    n = max(1, int(rate_qps * duration_s * 1.5) + 8)
    gaps = rng.exponential(1.0 / rate_qps, size=n)
    times = np.cumsum(gaps)
    while times[-1] < duration_s:        # tail shortfall: extend
        more = np.cumsum(rng.exponential(1.0 / rate_qps, size=n))
        times = np.concatenate([times, times[-1] + more])
    return times[times < duration_s]


def bursty_arrivals(rate_qps: float, duration_s: float,
                    rng: np.random.Generator, *, period_s: float = 0.5,
                    duty: float = 0.25) -> np.ndarray:
    """On/off arrivals averaging `rate_qps`: each `period_s` window
    front-loads all traffic into its first `duty` fraction at rate
    rate/duty — the flash-crowd shape that stresses the admission queue
    harder than Poisson at the same mean rate."""
    if not 0 < duty <= 1:
        raise ValueError(f"duty {duty} must be in (0, 1]")
    base = poisson_arrivals(rate_qps, duration_s, rng)
    # compress each period's arrivals into its first `duty` fraction:
    # the count (mean rate) is unchanged, the instantaneous on-rate is
    # rate/duty
    period_idx = np.floor(base / period_s)
    within = base - period_idx * period_s
    times = np.sort(period_idx * period_s + within * duty)
    return times[times < duration_s]


# -- load loops --------------------------------------------------------------

class _Ledger:
    """Shared counters for one run; one leaf lock, no nesting."""

    def __init__(self):
        self.lock = OrderedLock("loadgen.ledger")
        self.ok = 0
        self.stale = 0
        self.shed = 0
        self.errors = 0
        self.latency = LatencyRecorder(window=65536)

    def settle(self, err: BaseException | None, t0: float) -> None:
        """Account one finished request (latency from `t0`, recorded
        for accepted requests only)."""
        dt = time.monotonic() - t0
        with self.lock:
            if err is None:
                self.ok += 1
                self.latency.record(dt)
            elif isinstance(err, policy.OverloadedError):
                self.shed += 1
            elif isinstance(err, policy.StalenessError):
                self.stale += 1
            else:
                self.errors += 1

    def result(self, requests: int, duration_s: float,
               offered_qps: float | None = None) -> LoadResult:
        pct = self.latency.percentiles_ms(50, 99)
        return LoadResult(requests=requests, ok=self.ok, stale=self.stale,
                          shed=self.shed, errors=self.errors,
                          duration_s=duration_s,
                          achieved_qps=self.ok / max(duration_s, 1e-9),
                          p50_ms=pct["p50_ms"], p99_ms=pct["p99_ms"],
                          offered_qps=offered_qps)


def _rows(features, rng: np.random.Generator, n: int = 64) -> np.ndarray:
    """Pre-built request rows: either the caller's matrix or synthetic
    standard-normal rows at `features` width."""
    if isinstance(features, int):
        return rng.standard_normal((n, features)).astype(np.float32)
    rows = np.asarray(features, dtype=np.float32)
    return rows.reshape(1, -1) if rows.ndim == 1 else rows


def run_closed_loop(target, features, *, concurrency: int = 4,
                    duration_s: float = 2.0, seed: int = 0) -> LoadResult:
    """`concurrency` synchronous callers, back-to-back for
    `duration_s`.  Measures capacity: achieved QPS at this fleet size."""
    rng = np.random.default_rng(seed)
    rows = _rows(features, rng)
    ledger = _Ledger()
    counts = [0] * concurrency
    start = time.monotonic()
    stop_at = start + duration_s

    def _drive(tid: int) -> None:
        issue = target.make_issue()
        i = tid
        while time.monotonic() < stop_at:
            t0 = time.monotonic()
            err = None
            try:
                issue(rows[i % len(rows)])
            except Exception as e:  # noqa: BLE001 — the ledger classifies
                err = e
            ledger.settle(err, t0)
            counts[tid] += 1
            i += concurrency

    threads = [threading.Thread(target=_drive, args=(t,), daemon=True,
                                name=f"kps-loadgen-{t}")
               for t in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return ledger.result(sum(counts), time.monotonic() - start)


def run_open_loop(target, features, *, rate_qps: float,
                  duration_s: float = 2.0, concurrency: int = 8,
                  arrivals: str = "poisson", seed: int = 0) -> LoadResult:
    """Offered-rate run: a Poisson or bursty schedule fixes every
    arrival time up front; `concurrency` driver threads fire them on
    schedule (round-robin).  Latency counts from the SCHEDULED arrival,
    so a lagging driver inflates p99 instead of hiding queueing —
    coordinated omission never flatters the result."""
    rng = np.random.default_rng(seed)
    rows = _rows(features, rng)
    if arrivals == "poisson":
        sched = poisson_arrivals(rate_qps, duration_s, rng)
    elif arrivals == "bursty":
        sched = bursty_arrivals(rate_qps, duration_s, rng)
    else:
        raise ValueError(f"unknown arrival process {arrivals!r}")
    ledger = _Ledger()
    start = time.monotonic()

    def _drive(tid: int) -> None:
        issue = target.make_issue()
        for i in range(tid, len(sched), concurrency):
            at = start + float(sched[i])  # pscheck: disable=PS102 (host-side schedule arithmetic; keeps np.float64 out of the recorder)
            delay = at - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            err = None
            try:
                issue(rows[i % len(rows)])
            except Exception as e:  # noqa: BLE001 — the ledger classifies
                err = e
            ledger.settle(err, at)
    threads = [threading.Thread(target=_drive, args=(t,), daemon=True,
                                name=f"kps-loadgen-{t}")
               for t in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return ledger.result(len(sched), time.monotonic() - start,
                         offered_qps=rate_qps)


def find_knee(run_at, deadline_ms: float, *, lo_qps: float = 50.0,
              hi_qps: float = 100000.0, bisect_steps: int = 4) -> dict:
    """Max sustained QPS with p99 <= deadline and zero sheds/errors.

    `run_at(rate_qps) -> LoadResult` is the probe (an open-loop run at
    that offered rate).  Geometric ramp doubles from `lo_qps` until the
    SLO breaks (or `hi_qps`), then bisects the last good/first bad
    bracket.  Returns {knee_qps, probes: [LoadResult.as_dict()...]}."""
    probes: list[LoadResult] = []

    def probe(rate: float) -> LoadResult:
        r = run_at(rate)
        probes.append(r)
        return r

    good, bad = None, None
    rate = lo_qps
    while rate <= hi_qps:
        r = probe(rate)
        if r.meets(deadline_ms):
            good = rate
            rate *= 2
        else:
            bad = rate
            break
    if good is None:                    # SLO broken at the floor rate
        return {"knee_qps": 0.0,
                "probes": [p.as_dict() for p in probes]}
    if bad is not None:
        for _ in range(bisect_steps):
            mid = (good + bad) / 2
            if probe(mid).meets(deadline_ms):
                good = mid
            else:
                bad = mid
    return {"knee_qps": round(good, 1),
            "probes": [p.as_dict() for p in probes]}
