"""Same-host shared-memory fast path for predict traffic.

A co-located `PredictClient` pays the TCP stack twice per prediction —
frame out, frame back — for bytes that never leave the machine.  This
module replaces that round trip with a depth-1 RPC slot in a
`multiprocessing.shared_memory` segment: the client memcpys its request
payload in and bumps a sequence number; the server's poll thread
decodes it with the SAME codec helpers the socket path uses
(`runtime/net.py` encode/decode_predict_request / encode_prediction),
submits to the `PredictionEngine`, and memcpys the reply back.  No
syscalls on the hot path beyond the client's bounded spin-sleep.

The channel is negotiated, never assumed (docs/SERVING.md, "Dispatch
economics"): the client asks via a trailer on its HELLO, the server
offers `(segment name, nonce)` via a trailer on its CONFIG — the same
append-and-length-check pattern as the codec/trace trailers, so legacy
peers on either side silently degrade to sockets.  A remote client's
attach fails (the segment name does not exist on its host), nonce
mismatch catches name collisions, and any failure at any point falls
back to the still-open socket.  The socket stays the control plane;
shared memory only ever carries predict payloads.

Layout (little-endian, one segment per connection)::

    [0:16)    nonce — random bytes the CONFIG offer carries; the
              client verifies them after attach
    [16:24)   req_seq  (u64) — client increments after writing request
    [24:32)   resp_seq (u64) — server sets to req_seq after writing
              the matching response
    [32:36)   req_len  (u32)
    [36:40)   resp_len (u32)
    [40:41)   closed   (u8) — either side marks teardown
    [64:64+C) request payload buffer
    [64+C:..) response payload buffer

Depth-1 on purpose: a prediction round trip is tens of microseconds,
so one in-flight request per connection keeps the protocol two seq
words and zero locks shared across processes.  Clients serialize their
own threads on a local lock.
"""

from __future__ import annotations

import os
import struct
import time

from kafka_ps_tpu.analysis.lockgraph import OrderedLock

_NONCE = struct.Struct("<16s")
_SEQ = struct.Struct("<Q")
_LEN = struct.Struct("<I")
_REQ_SEQ_OFF = 16
_RESP_SEQ_OFF = 24
_REQ_LEN_OFF = 32
_RESP_LEN_OFF = 36
_CLOSED_OFF = 40
_DATA_OFF = 64

DEFAULT_CAPACITY = 1 << 18      # per-direction payload buffer (256 KiB)

# client spin policy: a short pure spin catches the common
# tens-of-microseconds reply without ever sleeping; after that, sleep
# in sub-millisecond slices so a slow batched reply costs ~one
# scheduler quantum of extra latency, not a busy core
_SPIN_ITERS = 2000
_POLL_SLEEP_S = 0.0002


class ShmError(RuntimeError):
    """Channel setup or transport failure — callers fall back to the
    socket path, never to the user."""


class ShmChannel:
    """One depth-1 request/response slot in a shared-memory segment.

    The server side `create()`s (and later unlinks) the segment; the
    client side `attach()`es by the negotiated name and verifies the
    nonce.  `rpc()` is the client hot path, `serve_once()`/`respond()`
    the server's.
    """

    def __init__(self, seg, nonce: bytes, capacity: int, owner: bool):
        self._seg = seg
        self.nonce = nonce
        self.capacity = capacity
        self.owner = owner
        self._buf = seg.buf
        self._seq = 0           # client: last request sequence issued
        self._seen = 0          # server: last request sequence popped
        self._lock = OrderedLock("ShmChannel.rpc")

    # -- lifecycle ----------------------------------------------------------

    @classmethod
    def create(cls, capacity: int = DEFAULT_CAPACITY) -> "ShmChannel":
        """Server side: allocate the segment and stamp the nonce."""
        from multiprocessing import shared_memory
        size = _DATA_OFF + 2 * capacity
        seg = shared_memory.SharedMemory(create=True, size=size)
        nonce = os.urandom(16)
        seg.buf[:_DATA_OFF] = b"\0" * _DATA_OFF
        seg.buf[0:16] = nonce
        return cls(seg, nonce, capacity, owner=True)

    @classmethod
    def attach(cls, name: str, nonce: bytes) -> "ShmChannel":
        """Client side: map the offered segment and verify the nonce.
        Raises (FileNotFoundError for a remote peer, ShmError for a
        stale or foreign segment) — callers catch and fall back."""
        from multiprocessing import shared_memory
        seg = shared_memory.SharedMemory(name=name)
        try:
            # the resource tracker assumes whoever maps a segment owns
            # its lifetime; this side explicitly does not (the server
            # unlinks), so unregister to avoid a spurious unlink +
            # KeyError warning at interpreter exit
            from multiprocessing import resource_tracker
            resource_tracker.unregister(seg._name, "shared_memory")
        except Exception:  # noqa: BLE001 — tracker API is CPython-internal
            pass
        if bytes(seg.buf[0:16]) != nonce:
            seg.close()
            raise ShmError(f"segment {name} nonce mismatch")
        capacity = (seg.size - _DATA_OFF) // 2
        return cls(seg, nonce, capacity, owner=False)

    @property
    def name(self) -> str:
        return self._seg.name

    @property
    def closed(self) -> bool:
        return self._buf is None or self._buf[_CLOSED_OFF] != 0

    def mark_closed(self) -> None:
        if self._buf is not None:
            self._buf[_CLOSED_OFF] = 1

    def close(self) -> None:
        """Unmap (and unlink, when owner).  Idempotent."""
        if self._buf is None:
            return
        try:
            self._buf[_CLOSED_OFF] = 1
        except (TypeError, ValueError):
            pass
        self._buf = None
        try:
            self._seg.close()
            if self.owner:
                try:
                    # in-process tests attach the client end in the SAME
                    # process: its unregister (see attach) also removed
                    # OUR registration, and unlink's implicit unregister
                    # would then KeyError inside the tracker process —
                    # re-register first (a set add: no-op cross-process)
                    from multiprocessing import resource_tracker
                    resource_tracker.register(self._seg._name,
                                              "shared_memory")
                except Exception:  # noqa: BLE001 — CPython-internal API
                    pass
                self._seg.unlink()
        except (FileNotFoundError, OSError):
            pass

    # -- client hot path ----------------------------------------------------

    def rpc(self, payload: bytes, timeout: float = 30.0) -> bytes:
        """One predict round trip: write `payload`, spin for the reply.
        Raises ShmError on overflow/teardown/timeout — the caller falls
        back to its socket."""
        if len(payload) > self.capacity:
            raise ShmError(f"payload {len(payload)}B > channel capacity "
                           f"{self.capacity}B")
        with self._lock:
            buf = self._buf
            if buf is None or buf[_CLOSED_OFF]:
                raise ShmError("channel closed")
            self._seq += 1
            seq = self._seq
            buf[_DATA_OFF:_DATA_OFF + len(payload)] = payload
            _LEN.pack_into(buf, _REQ_LEN_OFF, len(payload))
            # request becomes visible to the server at the seq store —
            # payload and length writes are sequenced before it
            _SEQ.pack_into(buf, _REQ_SEQ_OFF, seq)
            deadline = time.monotonic() + timeout
            spins = 0
            while True:
                (resp,) = _SEQ.unpack_from(buf, _RESP_SEQ_OFF)
                if resp == seq:
                    (n,) = _LEN.unpack_from(buf, _RESP_LEN_OFF)
                    off = _DATA_OFF + self.capacity
                    return bytes(buf[off:off + n])
                if buf[_CLOSED_OFF]:
                    raise ShmError("server closed channel")
                if time.monotonic() > deadline:
                    raise ShmError("shm rpc timed out")
                spins += 1
                if spins > _SPIN_ITERS:
                    # pscheck: disable=PS105 (the lock IS the depth-1 request slot; bounded sub-ms poll)
                    time.sleep(_POLL_SLEEP_S)

    # -- server hot path ----------------------------------------------------

    def serve_once(self) -> tuple[int, bytes] | None:
        """Pop the pending request, if any: (seq, payload) once per
        request — the reply is owed via respond(seq, ...)."""
        buf = self._buf
        if buf is None:
            return None
        (req,) = _SEQ.unpack_from(buf, _REQ_SEQ_OFF)
        if req <= self._seen:
            return None
        self._seen = req
        (n,) = _LEN.unpack_from(buf, _REQ_LEN_OFF)
        return req, bytes(buf[_DATA_OFF:_DATA_OFF + n])

    def respond(self, seq: int, payload: bytes) -> None:
        """Publish the reply for `seq` (server side)."""
        buf = self._buf
        if buf is None:
            return
        n = min(len(payload), self.capacity)
        off = _DATA_OFF + self.capacity
        buf[off:off + n] = payload[:n]
        _LEN.pack_into(buf, _RESP_LEN_OFF, n)
        _SEQ.pack_into(buf, _RESP_SEQ_OFF, seq)
