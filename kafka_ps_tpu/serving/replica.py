"""Log-following read replicas (docs/SERVING.md, "Operating at load").

A replica is a serving process that never joins the training fabric: it
tails the durable commit log's WEIGHTS partitions (log/tail.py — strict
read-only, never truncating a live writer's torn tail) and republishes
what it reads into a local `SnapshotRegistry`, which a stock
`PredictionEngine` then serves from.  Read traffic scales by adding
replica processes; the training deployment never sees a single extra
syscall — the only coupling is the filesystem the log lives on.

Two deployment shapes, auto-detected from the log directory layout:

  * single server: `DIR/weights/<worker>/…` — every weights message
    carries the full theta, so the replica publishes the newest message
    by vector clock (the same rule as `DurableFabric.
    latest_logged_weights`, incrementally).
  * range-sharded (`--shards N`): `DIR/shard<i>of<N>/weights/…` — each
    shard logs only its own key-range slice.  The replica keeps the
    newest slice per shard and publishes through
    `FrontierCutPublisher`, so a served snapshot is always a consistent
    CUT stamped with the frontier clock (min per-shard clock), never a
    torn mix of shard states.  This is exactly the assembled-theta
    serving path that the live sharded runtime cannot offer
    (socket_mode.run_server_shard rejects --serve); the replica closes
    that gap.

Snapshots published here enter the frontier-aware staleness policies of
serving/policy.py unchanged: `min_clock` bounds below the frontier are
satisfiable, `max_age_s` runs off the replica's publication time, and
`at_clock` audit reads hit the replica's own retained ring.
"""

from __future__ import annotations

import os
import re
import threading

from kafka_ps_tpu.log.tail import TopicTailer
from kafka_ps_tpu.runtime import serde
from kafka_ps_tpu.serving.snapshot import (FrontierCutPublisher,
                                           SnapshotRegistry)
from kafka_ps_tpu.telemetry.flight import FLIGHT

_SHARD_DIR = re.compile(r"^shard(\d+)of(\d+)$")


def discover_shards(root: str) -> list[tuple[int, str]]:
    """[(shard_id, shard_log_dir)…] for a SPLIT deployment's log root,
    or [] when `root` is an unsharded (single-server) log."""
    try:
        names = os.listdir(root)
    except FileNotFoundError:
        return []
    out = []
    for name in names:
        m = _SHARD_DIR.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(root, name)))
    return sorted(out)


class ReplicaFollower:
    """Follow a durable log's weights partitions into a registry.

    `catch_up()` is the synchronous unit of work (poll every tailer
    once, publish whatever advanced) — tests and cold starts call it
    directly; `start()` runs it on a background thread at
    `poll_interval_s` until `stop()`.
    """

    def __init__(self, root: str, registry: SnapshotRegistry | None = None,
                 *, poll_interval_s: float = 0.05, tracer=None):
        self.root = root
        self.registry = registry if registry is not None \
            else SnapshotRegistry()
        self.poll_interval_s = poll_interval_s
        self.tracer = tracer
        # pscheck: disable=PS201 (exactly one driver - the tail thread or a manual catch_up loop - advances the follower)
        self.records_read = 0
        # pscheck: disable=PS201 (exactly one driver - the tail thread or a manual catch_up loop - advances the follower)
        self.publications = 0
        shards = discover_shards(root)
        self.num_shards = len(shards)
        if shards:
            self._tailers = {sid: TopicTailer(path) for sid, path in shards}
            # newest (values, clock, range_start) seen per shard; a cut
            # is publishable once every shard has reported at least once
            # pscheck: disable=PS201 (exactly one driver - the tail thread or a manual catch_up loop - advances the follower)
            self._newest: dict[int, tuple] = {}
            self._cut = FrontierCutPublisher(self.registry)
        else:
            self._tailers = {0: TopicTailer(root)}
            self._newest = {}
            self._cut = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Callable[[int], None], fired (from the calling thread — the
        # tail thread once start()ed) with the new clock after every
        # publish.  run_replica uses it to warm the serving engine the
        # moment a replica that started against an EMPTY log first sees
        # theta: warmup is a no-op without a snapshot, and an unwarmed
        # engine never calibrates its dispatch cost model.
        self.on_publish = None

    # -- synchronous follow ---------------------------------------------------

    def catch_up(self) -> int:
        """Poll every partition once; publish if the log advanced.
        Returns the number of snapshots published."""
        published = 0
        advanced = False
        for sid, tailer in self._tailers.items():
            for _key, _offset, payload in tailer.poll():
                self.records_read += 1
                msg = serde.from_bytes(payload)
                have = self._newest.get(sid)
                if have is None or msg.vector_clock > have[1]:
                    self._newest[sid] = (msg.values, msg.vector_clock,
                                         msg.key_range.start)
                    advanced = True
        if not advanced:
            return 0
        if self._cut is not None:
            if len(self._newest) == self.num_shards:
                # shard-id order == key_range.start order for range
                # sharding, but sort by range start explicitly — the
                # concatenation must tile the key space in order
                cut = [(values, clock) for values, clock, _start
                       in sorted(self._newest.values(),
                                 key=lambda t: t[2])]
                if self._cut.maybe_publish(cut) is not None:
                    published = 1
        else:
            values, clock, _start = self._newest[0]
            latest = self.registry.latest
            if latest is None or clock > latest.vector_clock:
                self.registry.publish(values, clock)
                published = 1
        if published:
            self.publications += 1
            if self.tracer is not None:
                self.tracer.count("replica.publications")
            if FLIGHT.enabled:
                latest = self.registry.latest
                FLIGHT.record("replica.publish",
                              clock=(latest.vector_clock
                                     if latest is not None else -1))
            if self.on_publish is not None:
                latest = self.registry.latest
                self.on_publish(latest.vector_clock
                                if latest is not None else -1)
        return published

    @property
    def clock(self) -> int | None:
        latest = self.registry.latest
        return None if latest is None else latest.vector_clock

    # -- background follow ----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("replica follower already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._follow, daemon=True,
                                        name="kps-replica-tail")
        self._thread.start()

    def _follow(self) -> None:
        while not self._stop.is_set():
            self.catch_up()
            # beat every poll, data or not: the replica watchdog's
            # question is "is the tail loop turning?", not "is the
            # trainer producing?" (telemetry/health.py)
            FLIGHT.beat("replica")
            self._stop.wait(self.poll_interval_s)

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=timeout)
