"""Staleness-bounded reads for the serving plane.

Training offers three consistency models (utils/config.py): SEQUENTIAL
(BSP), bounded delay k (SSP), and EVENTUAL (ASP). A prediction request
picks the read-side mirror of the same trade-off:

    read bound                      training analogue
    ------------------------------  --------------------------------
    no bound (EVENTUAL_READ)        EVENTUAL — newest snapshot, any age
    max_age_s=T                     bounded delay — tolerate staleness
                                    up to a wall-clock budget
    min_clock=c                     SEQUENTIAL-ish — refuse weights
                                    older than a known training clock

The registry always serves its *newest* snapshot; a bound can only
reject it, never select an older one (an older snapshot satisfies
strictly weaker bounds, so if the newest fails nothing else can pass).
The one exception is `at_clock`, a debugging/audit mode that pins an
exact historical clock from the snapshot ring.

This module is dependency-free on purpose: transport code
(runtime/net.py) and thin clients raise/catch `StalenessError` without
importing jax.
"""

from __future__ import annotations

from dataclasses import dataclass


class StalenessError(RuntimeError):
    """No snapshot satisfies the request's read bound.

    Carries the bound that failed and what was actually available so
    callers (and the wire protocol) can report *how* stale the read was.
    """

    def __init__(self, message: str, *, min_clock=None, max_age_s=None,
                 have_clock=None, have_age_s=None):
        super().__init__(message)
        self.min_clock = min_clock
        self.max_age_s = max_age_s
        self.have_clock = have_clock
        self.have_age_s = have_age_s


class OverloadedError(RuntimeError):
    """The engine shed this request at admission instead of queueing it
    past any chance of meeting its deadline (docs/SERVING.md,
    "Operating at load").

    Typed — not a timeout, not a StalenessError — so transports map it
    to an explicit OVERLOADED wire status and clients can distinguish
    "back off and retry elsewhere" from a staleness rejection or a real
    failure.  Carries the admission-queue state at shed time.
    """

    def __init__(self, message: str, *, queue_depth=None, queue_limit=None,
                 model_id=None):
        super().__init__(message)
        self.queue_depth = queue_depth
        self.queue_limit = queue_limit
        self.model_id = model_id


@dataclass(frozen=True)
class ReadBound:
    """What a prediction request demands of the snapshot it reads.

    min_clock  — snapshot's vector clock must be >= this (None: any)
    max_age_s  — snapshot's wall-clock age must be <= this (None: any)
    at_clock   — exact-clock audit read from the snapshot ring; when
                 set the other two fields still apply to the pinned
                 snapshot
    """

    min_clock: int | None = None
    max_age_s: float | None = None
    at_clock: int | None = None

    @property
    def unbounded(self) -> bool:
        return (self.min_clock is None and self.max_age_s is None
                and self.at_clock is None)


# the ASP-flavoured default: serve whatever is newest
EVENTUAL_READ = ReadBound()


def fresh(min_clock: int) -> ReadBound:
    """Refuse anything older than a known training clock."""
    return ReadBound(min_clock=min_clock)


def bounded(max_age_s: float) -> ReadBound:
    """Tolerate staleness up to a wall-clock budget."""
    return ReadBound(max_age_s=max_age_s)


def check(snapshot, bound: ReadBound | None, now: float,
          telemetry=None) -> None:
    """Raise StalenessError unless `snapshot` satisfies `bound`.

    `snapshot` is a serving.snapshot.Snapshot or None (nothing published
    yet — every bound, including the empty one, rejects that).
    `telemetry` (a kafka_ps_tpu.telemetry.Telemetry, optional to keep
    this module dependency-free for thin clients) records the observed
    snapshot age so BSP/bounded/async read-staleness distributions are
    benchable — host floats only, never touching snapshot.theta.
    """
    if snapshot is None:
        raise StalenessError(
            "no snapshot published yet",
            min_clock=None if bound is None else bound.min_clock,
            max_age_s=None if bound is None else bound.max_age_s)
    if telemetry is not None and telemetry.enabled:
        telemetry.histogram("snapshot_age_ms").observe(
            max(0.0, (now - snapshot.wall_time) * 1e3))
    b = bound or EVENTUAL_READ
    if b.min_clock is not None and snapshot.vector_clock < b.min_clock:
        raise StalenessError(
            f"snapshot clock {snapshot.vector_clock} < required "
            f"min_clock {b.min_clock}",
            min_clock=b.min_clock, have_clock=snapshot.vector_clock)
    if b.max_age_s is not None:
        age = now - snapshot.wall_time
        if age > b.max_age_s:
            raise StalenessError(
                f"snapshot age {age:.3f}s > allowed max_age_s "
                f"{b.max_age_s:.3f}s",
                max_age_s=b.max_age_s, have_age_s=age,
                have_clock=snapshot.vector_clock)
