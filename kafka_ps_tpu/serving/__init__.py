"""Online serving plane — train/serve split for the streaming PS
(docs/SERVING.md).

The training loop keeps aggregating deltas while this subsystem answers
live prediction requests against recent weights:

  * `snapshot.SnapshotRegistry` — immutable (theta, vector_clock,
    wall_time) snapshots published by the server at every
    consistency-gate release, hot-swapped lock-free for readers;
  * `engine.PredictionEngine` — micro-batched, jit'd prediction under a
    deadline/size cap (the serving-side analogue of gang dispatch);
  * `policy` — staleness-bounded reads (`min_clock` / `max_age_s`),
    mirroring the three training consistency models on the read path.

Import discipline: `policy` and `snapshot` are dependency-free (no jax)
so transport/client code can use them without pulling a backend;
`engine` defers its jax imports to first prediction.
"""

from kafka_ps_tpu.serving.policy import (EVENTUAL_READ, OverloadedError,
                                         ReadBound, StalenessError)
from kafka_ps_tpu.serving.snapshot import (FrontierCutPublisher,
                                           MultiModelRegistry, Snapshot,
                                           SnapshotRegistry)

__all__ = ["EVENTUAL_READ", "OverloadedError", "ReadBound",
           "StalenessError", "Snapshot", "SnapshotRegistry",
           "MultiModelRegistry", "FrontierCutPublisher",
           "PredictionEngine", "Prediction", "ReplicaFollower"]


def __getattr__(name):
    # engine/replica pull in numpy/jax-adjacent machinery; load them
    # only when a caller actually serves predictions
    if name in ("PredictionEngine", "Prediction"):
        from kafka_ps_tpu.serving import engine
        return getattr(engine, name)
    if name == "ReplicaFollower":
        from kafka_ps_tpu.serving.replica import ReplicaFollower
        return ReplicaFollower
    raise AttributeError(name)
