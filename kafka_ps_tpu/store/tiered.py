"""TieredParamStore — hot/warm/cold residency for one theta slice.

The server's parameter slice is split into fixed-size PAGES (contiguous
key ranges).  Each page lives in exactly one tier at a time:

  hot   device-resident f32 array (compress/slab.ParamPageSlab — the
        PR 6 device slab, per-page instead of full-slice);
  warm  pinned host-RAM f32 array;
  cold  one CRC-framed record in the durable commit log, addressed by
        offset (store/cold.ColdStore over CommitLog.read_at).

Per-page heat (reads via `pin`, delta writes via `update_page`) drives
promotion/demotion on a background policy thread; heat is exported as
the `param_range_heat` telemetry family.  The capacity story: the hot
(and optionally warm) byte budgets cap what is resident, everything
else is a log record — models outgrow HBM, then host RAM
(docs/TIERING.md, ROADMAP item 5).

Correctness contract — residency NEVER changes values:

  * pages are replaced wholesale, never mutated in place (the theta
    replacement contract, runtime/server.py docstring), so any thread
    may keep using a value reference it obtained earlier;
  * a migration moves bits verbatim between tiers (device_put / host
    fetch / log append+read of the same f32 bytes), so which tier a
    page occupies is invisible to every computation — the bitwise-
    equality bar (capped run == fully resident run, scripts/tier1.sh
    --tier) holds no matter when the policy thread runs;
  * residency decisions themselves are deterministic pure functions of
    the heat counters (sort by (-heat, page index)); only their TIMING
    depends on the thread scheduler, and timing cannot reach replay
    because of the point above.

Locking discipline (analysis/lockgraph, PS105): one leaf
`store.residency` OrderedLock guards the residency table.  Blocking
work — log appends/point reads, device transfers, host fetches — runs
OUTSIDE the lock: a migration snapshots (value, version) under the
lock, does its I/O unlocked, then re-acquires and commits only if the
page's version is unchanged (a racing write wins; the abandoned cold
record is benign append-only garbage).  Writes land hot or warm only,
so `update_page` never touches the log.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from kafka_ps_tpu.analysis.lockgraph import OrderedLock
from kafka_ps_tpu.runtime.messages import KeyRange
from kafka_ps_tpu.telemetry.flight import FLIGHT

TIER_HOT, TIER_WARM, TIER_COLD = 0, 1, 2
TIER_NAMES = ("hot", "warm", "cold")


class _Page:
    """Residency record for one key range.  `value` is a device array
    (hot), a host f32 array (warm), or None (cold — `cold_offset` then
    addresses the log record).  `version` counts value replacements;
    migrations commit only against an unchanged version."""

    __slots__ = ("index", "start", "end", "tier", "value", "cold_offset",
                 "version", "reads", "writes")

    def __init__(self, index: int, start: int, end: int,
                 value: np.ndarray):
        self.index = index
        self.start = start
        self.end = end
        self.tier = TIER_WARM
        self.value = value
        self.cold_offset = -1
        self.version = 0
        self.reads = 0
        self.writes = 0

    @property
    def nbytes(self) -> int:
        return (self.end - self.start) * 4

    @property
    def heat(self) -> int:
        return self.reads + self.writes


class TieredParamStore:
    """Paged hot/warm/cold store for one server's theta slice."""

    def __init__(self, values: np.ndarray, key_range: KeyRange, *,
                 hot_bytes: int = 0, warm_bytes: int = 0,
                 page_params: int = 1024, cold=None, telemetry=None,
                 rebalance_interval_s: float = 0.05):
        from kafka_ps_tpu.compress.slab import ParamPageSlab
        if telemetry is None:
            from kafka_ps_tpu.telemetry import NULL_TELEMETRY
            telemetry = NULL_TELEMETRY
        if page_params <= 0:
            raise ValueError("page_params must be positive")
        if warm_bytes > 0 and cold is None:
            raise ValueError(
                "a warm-tier cap needs a cold store to overflow into "
                "(pass cold=ColdStore.open(...) or run under "
                "--durable-log)")
        self.key_range = key_range
        self.page_params = page_params
        # 0 = unbounded (the "today's behavior" default, ISSUE 13)
        self.hot_budget = hot_bytes if hot_bytes > 0 else None
        self.warm_budget = warm_bytes if warm_bytes > 0 else None
        self.cold = cold
        self.telemetry = telemetry
        self.rebalance_interval_s = rebalance_interval_s
        self._slab = ParamPageSlab()
        self._lock = OrderedLock("store.residency")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

        vals = np.ascontiguousarray(np.asarray(values), dtype=np.float32)
        if vals.shape != (key_range.end - key_range.start,):
            raise ValueError(
                f"values shape {vals.shape} != key range "
                f"[{key_range.start}, {key_range.end})")
        self._pages: list[_Page] = []
        for i, lo in enumerate(range(key_range.start, key_range.end,
                                     page_params)):
            hi = min(lo + page_params, key_range.end)
            self._pages.append(_Page(
                i, lo, hi,
                vals[lo - key_range.start:hi - key_range.start].copy()))

        # measured counters the bench/stats read (host ints, no device
        # sync anywhere near them)
        self.pins = {"hot": 0, "warm": 0, "cold": 0}
        # guarded-by: _lock (rebalance writes hold the residency lock; stats reads are snapshots)
        self.promotions = 0
        # guarded-by: _lock (rebalance writes hold the residency lock; stats reads are snapshots)
        self.demotions = 0
        self.faults = 0          # cold pages materialized on demand
        # guarded-by: _lock (rebalance writes hold the residency lock; stats reads are snapshots)
        self.rebalances = 0
        self._m_pins = {t: telemetry.counter("param_tier_pins_total",
                                             tier=t)
                        for t in TIER_NAMES}
        self._m_migrations = {
            d: telemetry.counter("param_tier_migrations_total",
                                 direction=d)
            for d in ("promote", "demote")}
        self._m_migration_ms = {
            d: telemetry.histogram("param_tier_migration_ms", direction=d)
            for d in ("promote", "demote")}
        self.rebalance()         # settle the initial residency

    # -- page geometry -------------------------------------------------------

    @property
    def num_pages(self) -> int:
        return len(self._pages)

    def pages_overlapping(self, key_range: KeyRange) -> range:
        """Indices of pages intersecting [start, end)."""
        start = max(key_range.start, self.key_range.start)
        end = min(key_range.end, self.key_range.end)
        if end <= start:
            return range(0)
        first = (start - self.key_range.start) // self.page_params
        last = (end - 1 - self.key_range.start) // self.page_params
        return range(first, last + 1)

    def page_range(self, index: int) -> KeyRange:
        p = self._pages[index]
        return KeyRange(p.start, p.end)

    # -- reads ---------------------------------------------------------------

    def pin_pages(self, key_range: KeyRange, count_heat: bool = True):
        """Materialize every page overlapping `key_range`:
        [(page index, KeyRange, value)] with value a device array for
        hot pages and a host f32 array for warm/cold (cold pages are
        faulted in from the log and installed warm).  Counts read heat
        and per-tier pin hits unless `count_heat` is False."""
        touched = self.pages_overlapping(key_range)
        out = []
        faults = []              # (page, offset, version)
        with self._lock:
            for i in touched:
                p = self._pages[i]
                if count_heat:
                    p.reads += 1
                    tier = TIER_NAMES[p.tier]
                    self.pins[tier] += 1
                    if self.telemetry.enabled:
                        self._m_pins[tier].inc()
                if p.tier == TIER_COLD:
                    faults.append((p, p.cold_offset, p.version))
                    out.append([i, KeyRange(p.start, p.end), None])
                else:
                    out.append([i, KeyRange(p.start, p.end), p.value])
        if faults:
            # log point reads happen OUTSIDE the residency lock
            t0 = time.perf_counter()
            fetched = [(p, ver,
                        self.cold.get(off, p.index, p.start, p.end))
                       for p, off, ver in faults]
            dt_ms = (time.perf_counter() - t0) * 1e3
            by_index = {}
            with self._lock:
                for p, ver, vals in fetched:
                    if p.tier == TIER_COLD and p.version == ver:
                        # install warm: the VALUE is unchanged, so the
                        # version is not bumped — a concurrent migration
                        # of this page would be a no-op anyway
                        p.tier = TIER_WARM
                        p.value = vals
                        p.cold_offset = -1
                        self.faults += 1
                        self.promotions += 1
                        by_index[p.index] = p.value
                    else:
                        # a racing write already landed the page warm/
                        # hot with a NEWER value; use that
                        by_index[p.index] = p.value
            if self.telemetry.enabled:
                self._m_migrations["promote"].inc(len(fetched))
                self._m_migration_ms["promote"].observe(dt_ms)
            if FLIGHT.enabled:
                # demand faults are the tail-latency event a postmortem
                # wants on the timeline: which pages, how long
                FLIGHT.record("store.fault", pages=len(fetched),
                              ms=round(dt_ms, 3))
            for entry in out:
                if entry[2] is None:
                    entry[2] = by_index[entry[0]]
        return [tuple(e) for e in out]

    def pin(self, key_range: KeyRange, count_heat: bool = True
            ) -> np.ndarray:
        """Host f32 vector for exactly [start, end) — the on-demand
        range pull ShardRouter/WeightsAssembler and the serving
        snapshot path use (docs/TIERING.md)."""
        pages = self.pin_pages(key_range, count_heat=count_heat)
        start = max(key_range.start, self.key_range.start)
        end = min(key_range.end, self.key_range.end)
        out = np.empty(end - start, dtype=np.float32)
        for _, kr, value in pages:
            host = value if isinstance(value, np.ndarray) \
                else np.asarray(value, dtype=np.float32)
            lo, hi = max(kr.start, start), min(kr.end, end)
            out[lo - start:hi - start] = host[lo - kr.start:hi - kr.start]
        return out

    def assembled(self) -> np.ndarray:
        """Full-slice host vector WITHOUT heat accounting — the eval/
        checkpoint/snapshot peek (reading the whole slice must not
        convince the policy everything is equally hot)."""
        return self.pin(self.key_range, count_heat=False)

    # -- writes --------------------------------------------------------------

    def update_page(self, index: int, values) -> None:
        """Replace one page's value (a delta apply's output).  Device
        arrays stay device-resident when the page is hot; writes to a
        warm or cold page land warm (never a log append — blocking log
        I/O is the policy thread's job, outside this hot path)."""
        p = self._pages[index]
        prepared = values
        while True:
            if isinstance(prepared, np.ndarray):
                prepared = np.ascontiguousarray(prepared,
                                                dtype=np.float32)
            with self._lock:
                is_host = isinstance(prepared, np.ndarray)
                if p.tier == TIER_HOT:
                    p.value = self._slab.put(index, prepared)
                elif is_host:
                    if p.tier == TIER_COLD:
                        p.tier = TIER_WARM
                        p.cold_offset = -1
                    p.value = prepared
                else:
                    # device value but the page is not hot (the policy
                    # thread demoted it mid-flight): fetch to host
                    # OUTSIDE the lock and retry
                    pass
                if p.tier == TIER_HOT or is_host:
                    p.version += 1
                    p.writes += 1
                    return
            prepared = np.asarray(prepared, dtype=np.float32)

    def replace_all(self, values) -> None:
        """Scatter a full slice into the pages, preserving residency
        where possible (cold pages land warm; the policy re-demotes) —
        the theta-setter path: checkpoint restore, fused loops."""
        vals = np.ascontiguousarray(np.asarray(values), dtype=np.float32)
        if vals.shape != (self.key_range.end - self.key_range.start,):
            raise ValueError(f"replace_all shape {vals.shape}")
        base = self.key_range.start
        with self._lock:
            for p in self._pages:
                chunk = vals[p.start - base:p.end - base].copy()
                p.version += 1
                p.writes += 1
                if p.tier == TIER_HOT:
                    p.value = self._slab.put(p.index, chunk)
                else:
                    if p.tier == TIER_COLD:
                        p.tier = TIER_WARM
                        p.cold_offset = -1
                    p.value = chunk

    # -- the policy ----------------------------------------------------------

    def _plan_locked(self) -> dict[int, int]:
        """Deterministic target residency from the heat counters: pages
        ordered by (-heat, index), greedily assigned hot until the hot
        budget, then warm until the warm budget, then cold.  Pure
        function of the counters — no clocks, no randomness (PS104)."""
        order = sorted(self._pages, key=lambda p: (-p.heat, p.index))
        targets: dict[int, int] = {}
        hot_left = self.hot_budget
        warm_left = self.warm_budget
        for p in order:
            if hot_left is None or p.nbytes <= hot_left:
                targets[p.index] = TIER_HOT
                if hot_left is not None:
                    hot_left -= p.nbytes
            elif self.cold is None or warm_left is None \
                    or p.nbytes <= warm_left:
                targets[p.index] = TIER_WARM
                if warm_left is not None:
                    warm_left = max(warm_left - p.nbytes, 0)
            else:
                targets[p.index] = TIER_COLD
        return targets

    def rebalance(self) -> dict:
        """One policy pass: compute the deterministic target residency,
        migrate the diff (I/O outside the lock, version-checked
        commit), decay the heat counters, export heat gauges."""
        with self._lock:
            targets = self._plan_locked()
            moves = [(p, targets[p.index], p.value, p.cold_offset,
                      p.version)
                     for p in self._pages if p.tier != targets[p.index]]
        applied = self._migrate(moves)
        with self._lock:
            self.rebalances += 1
            for p in self._pages:
                # exponential heat decay so the policy tracks access
                # SHIFTS, not lifetime totals; integer halving keeps
                # the counters (and the plan) deterministic
                p.reads //= 2
                p.writes //= 2
            if self.telemetry.enabled:
                for p in self._pages:
                    rng = f"{p.start}:{p.end}"
                    self.telemetry.gauge("param_range_heat", kind="read",
                                         range=rng).set(p.reads)
                    self.telemetry.gauge("param_range_heat", kind="write",
                                         range=rng).set(p.writes)
                counts = [0, 0, 0]
                for p in self._pages:
                    counts[p.tier] += 1
                for t, n in zip(TIER_NAMES, counts):
                    self.telemetry.gauge("param_tier_pages",
                                         tier=t).set(n)
        return {"moved": applied, "targets": len(moves)}

    def _migrate(self, moves) -> int:
        """Apply (page, target tier) moves: blocking work (host fetch,
        log append, log read, device upload) runs with the lock
        RELEASED; each commit re-checks the page's version so a racing
        `update_page` always wins."""
        applied = 0
        for p, target, value, cold_offset, version in moves:
            promote = target < p.tier
            t0 = time.perf_counter()
            # --- unlocked I/O: produce the target-tier value form ----
            if target == TIER_COLD:
                host = value if isinstance(value, np.ndarray) \
                    else np.asarray(value, dtype=np.float32)
                new_offset = self.cold.put(p.index, p.start, p.end, host)
                new_value = None
            elif target == TIER_WARM:
                if value is None:       # cold -> warm: point read
                    new_value = self.cold.get(cold_offset, p.index,
                                              p.start, p.end)
                else:
                    new_value = value if isinstance(value, np.ndarray) \
                        else np.asarray(value, dtype=np.float32)
                new_offset = -1
            else:                       # -> hot: device upload
                if value is None:
                    value = self.cold.get(cold_offset, p.index,
                                          p.start, p.end)
                new_value = self._slab.put(p.index, value)
                new_offset = -1
            # --- locked commit, version-checked ----------------------
            with self._lock:
                if p.version != version:
                    # a write replaced the value mid-migration: abandon
                    # (an appended cold record becomes benign garbage)
                    if target == TIER_HOT and p.tier != TIER_HOT:
                        self._slab.drop(p.index)
                    continue
                if p.tier == TIER_HOT and target != TIER_HOT:
                    self._slab.drop(p.index)
                p.tier = target
                p.value = new_value
                p.cold_offset = new_offset
                applied += 1
                if promote:
                    self.promotions += 1
                else:
                    self.demotions += 1
            dt_ms = (time.perf_counter() - t0) * 1e3
            d = "promote" if promote else "demote"
            if self.telemetry.enabled:
                self._m_migrations[d].inc()
                self._m_migration_ms[d].observe(dt_ms)
            if FLIGHT.enabled:
                FLIGHT.record(f"store.{d}", page=p.index,
                              tier=TIER_NAMES[target],
                              ms=round(dt_ms, 3))
        return applied

    # -- the background policy thread ---------------------------------------

    def start_policy_thread(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.rebalance_interval_s):
                self.rebalance()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="kps-tier-policy")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=10.0)
        self._thread = None
        if self.cold is not None:
            self.cold.close()

    # -- checkpoint surface --------------------------------------------------

    def residency_vector(self) -> np.ndarray:
        with self._lock:
            return np.array([p.tier for p in self._pages], dtype=np.int8)

    def heat_vectors(self) -> tuple[np.ndarray, np.ndarray]:
        with self._lock:
            return (np.array([p.reads for p in self._pages], np.int64),
                    np.array([p.writes for p in self._pages], np.int64))

    def set_residency(self, tiers, reads=None, writes=None) -> None:
        """Restore recorded residency + heat (utils/checkpoint.py),
        AFTER `replace_all` put the restored values in place.  Recorded-
        cold pages are RE-demoted with fresh log appends — the
        checkpoint stays self-contained and never references records a
        crash may have torn off the log tail."""
        tiers = np.asarray(tiers)
        if len(tiers) != len(self._pages):
            raise ValueError(
                f"residency vector has {len(tiers)} pages, store has "
                f"{len(self._pages)} — page_params changed across "
                "restore?")
        with self._lock:
            if reads is not None:
                for p, r in zip(self._pages, np.asarray(reads)):
                    p.reads = int(r)
            if writes is not None:
                for p, w in zip(self._pages, np.asarray(writes)):
                    p.writes = int(w)
            moves = [(p, int(t), p.value, p.cold_offset, p.version)
                     for p, t in zip(self._pages, tiers)
                     if p.tier != int(t)]
        self._migrate(moves)

    # -- accounting ----------------------------------------------------------

    def resident_bytes(self) -> dict:
        with self._lock:
            hot = sum(p.nbytes for p in self._pages
                      if p.tier == TIER_HOT)
            warm = sum(p.nbytes for p in self._pages
                       if p.tier == TIER_WARM)
            cold = sum(p.nbytes for p in self._pages
                       if p.tier == TIER_COLD)
        return {"hot": hot, "warm": warm, "cold_logged": cold,
                "resident": hot + warm,
                "total": sum(p.nbytes for p in self._pages)}

    def tier_counts(self) -> dict:
        with self._lock:
            counts = [0, 0, 0]
            for p in self._pages:
                counts[p.tier] += 1
        return dict(zip(TIER_NAMES, counts))

    def stats(self) -> dict:
        total_pins = sum(self.pins.values()) or 1
        return {
            "pages": self.num_pages,
            "page_params": self.page_params,
            "tiers": self.tier_counts(),
            "pins": dict(self.pins),
            "hit_rate": {t: round(self.pins[t] / total_pins, 4)
                         for t in TIER_NAMES},
            "promotions": self.promotions,
            "demotions": self.demotions,
            "faults": self.faults,
            "rebalances": self.rebalances,
            "resident_bytes": self.resident_bytes(),
            "device_bytes": self._slab.device_bytes(),
            "upload_bytes": self._slab.bytes_uploaded,
        }
