"""Cold tier — parameter pages as CRC-framed records in a commit log.

The durable commit log (kafka_ps_tpu/log/) is already an
offset-indexed key-value store: `CommitLog.append` hands back a stable
offset and `CommitLog.read_at` (log/segment.py) is a CRC-verified
positioned point read through the sparse index.  The cold tier uses it
as exactly that — a demoted page is one appended record, a fault-in is
one point read — so cold parameters inherit the log's whole durability
story for free: torn tails are truncated on recovery, corruption is
detected (KeyError, never garbage floats), and retention never reaps a
partition no consumer group commits (log/manager.py), which is why a
`param-cold` topic under the durable-log root is safe.

Record payload: `<qqq>` header (page index, key start, key end) + raw
little-endian f32 bytes.  The header is verified on read — an offset
bookkeeping bug surfaces as a loud KeyError, not as silently wrong
parameters.

Append-only means demotions of the same page accumulate records; only
the offset the residency table holds is live, older records are
garbage the log's segment retention can reap once nothing references
them.  Checkpoint restore RE-demotes recorded-cold pages with fresh
appends (store/tiered.py `set_residency`), so a checkpoint never
depends on pre-checkpoint cold records.
"""

from __future__ import annotations

import struct

import numpy as np

from kafka_ps_tpu.log.log import CommitLog, LogConfig

_HDR = struct.Struct("<qqq")        # page index, key start, key end


class ColdStore:
    """Offset-addressed page storage over one CommitLog partition."""

    def __init__(self, log: CommitLog):
        self.log = log
        self._owned = False
        self.appends = 0
        self.reads = 0

    @classmethod
    def open(cls, directory: str, config: LogConfig | None = None
             ) -> "ColdStore":
        """Standalone cold partition (tests, bench, runs without a
        durable fabric); `close()` then closes the log too."""
        store = cls(CommitLog(directory, config or LogConfig(fsync="none"),
                              name="param-cold"))
        store._owned = True
        return store

    def put(self, page: int, start: int, end: int,
            values: np.ndarray) -> int:
        """Append one page record; returns its log offset — the only
        handle the residency table needs to keep."""
        vals = np.ascontiguousarray(values, dtype=np.float32)
        if vals.shape != (end - start,):
            raise ValueError(
                f"page {page} [{start}, {end}) expects {end - start} "
                f"values, got shape {vals.shape}")
        self.appends += 1
        return self.log.append(_HDR.pack(page, start, end)
                               + vals.tobytes())

    def get(self, offset: int, page: int, start: int, end: int
            ) -> np.ndarray:
        """CRC-verified point read of the page record at `offset`;
        the stored header must match what the caller expects."""
        payload = self.log.read_at(offset)
        p, s, e = _HDR.unpack_from(payload, 0)
        if (p, s, e) != (page, start, end):
            raise KeyError(
                f"cold record at offset {offset} is page {p} "
                f"[{s}, {e}), wanted page {page} [{start}, {end})")
        self.reads += 1
        return np.frombuffer(payload, np.float32, count=e - s,
                             offset=_HDR.size).copy()

    def close(self) -> None:
        if self._owned:
            self.log.close()
