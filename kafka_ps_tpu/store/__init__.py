"""Tiered parameter store: hot (device slab) / warm (host RAM) /
cold (commit-log records) residency for per-server theta slices, so
parameter spaces outgrow HBM without changing a single computed bit
(docs/TIERING.md)."""

from kafka_ps_tpu.store.cold import ColdStore
from kafka_ps_tpu.store.tiered import (TIER_COLD, TIER_HOT, TIER_NAMES,
                                       TIER_WARM, TieredParamStore)

__all__ = ["ColdStore", "TieredParamStore", "TIER_HOT", "TIER_WARM",
           "TIER_COLD", "TIER_NAMES"]
