"""Durable commit log — the Kafka primitive the fabric stood in for.

The reference inherits its entire fault-tolerance story from Kafka's
durable, offset-addressed log (Kreps et al., NetDB'11): producers
append, the broker assigns monotonic offsets, consumers own a committed
offset and replay from it after a crash.  `runtime/fabric.py` preserved
the *delivery* semantics of the three topics in volatile deques; this
package restores the *durability* semantics so "the Kafka fabric
disappears; its semantics stay" (README) holds across process death:

  * `records`   — CRC32-framed, length-prefixed record codec (the
                  framing; payloads are `runtime/serde.py` binary);
  * `segment`   — one append-only segment file + sparse offset index;
  * `log`       — `CommitLog`: segmented partition log with monotonic
                  offsets, configurable roll/retention and fsync policy;
  * `manager`   — `LogManager`: (topic, key) partition registry +
                  consumer groups with durable committed offsets;
  * `durable_fabric` — `DurableFabric`: the fabric API
                  (send/poll/poll_blocking) layered over the log, with
                  crash recovery by replay from committed offsets.

Recovery protocol (docs/DURABILITY.md): a checkpoint records the log
offsets it covers; resume = load checkpoint + replay the log tail.
Replayed gradient deltas are deduplicated against the tracker's vector
clocks (`parallel/tracker.py`) so each delta is applied exactly once.
"""

from kafka_ps_tpu.log.durable_fabric import DurableFabric
from kafka_ps_tpu.log.log import CommitLog, LogConfig
from kafka_ps_tpu.log.manager import LogManager
from kafka_ps_tpu.log.tail import PartitionTailer, TopicTailer

__all__ = ["CommitLog", "DurableFabric", "LogConfig", "LogManager",
           "PartitionTailer", "TopicTailer"]
