"""One commit-log segment: an append-only .log file plus a sparse
offset index, named by base offset like Kafka's on-disk layout:

    00000000000000000042.log      records 42, 43, ... (records.py framing)
    00000000000000000042.index    sparse (offset, file_position) pairs

The index holds one entry per ~`index_interval_bytes` of log, so a seek
to offset N is: binary-search the index for the floor entry, then scan
forward at most one interval.  The index is a derived structure — on
open it is validated against the recovered .log and rebuilt from it if
stale or missing, so index corruption can never lose records.
"""

from __future__ import annotations

import bisect
import os
import struct

from kafka_ps_tpu.log import records

_INDEX_ENTRY = struct.Struct("<qq")        # offset, file position


def segment_basename(base_offset: int) -> str:
    return f"{base_offset:020d}"


class LogSegment:
    """Append + offset-addressed read over one segment file."""

    def __init__(self, directory: str, base_offset: int,
                 index_interval_bytes: int = 4096):
        self.directory = directory
        self.base_offset = base_offset
        self.index_interval_bytes = index_interval_bytes
        os.makedirs(directory, exist_ok=True)
        base = os.path.join(directory, segment_basename(base_offset))
        self.log_path = base + ".log"
        self.index_path = base + ".index"
        # sparse index, kept in memory and mirrored to the .index file
        self._index: list[tuple[int, int]] = []
        self._bytes_since_index = 0
        self.next_offset = base_offset
        self.size = 0
        self.truncated_bytes = 0      # corrupt tail discarded on recovery
        self._recover()
        self._fh = open(self.log_path, "ab")
        self._index_fh = open(self.index_path, "ab")

    # -- recovery ----------------------------------------------------------

    def _recover(self) -> None:
        """Scan the .log, truncate a corrupt/torn tail (records.py scan
        rule), and rebuild the sparse index from the surviving records."""
        if not os.path.exists(self.log_path):
            open(self.log_path, "wb").close()
            open(self.index_path, "wb").close()
            return
        with open(self.log_path, "rb") as fh:
            buf = fh.read()
        valid = records.valid_length(buf)
        self.truncated_bytes = len(buf) - valid
        if valid < len(buf):
            with open(self.log_path, "r+b") as fh:
                fh.truncate(valid)
            buf = buf[:valid]
        self.size = valid
        since = 0
        for offset, payload, pos in records.scan(buf):
            if pos == 0 or since >= self.index_interval_bytes:
                self._index.append((offset, pos))
                since = 0
            since += records.HEADER_SIZE + len(payload)
            self.next_offset = offset + 1
        self._bytes_since_index = since
        # the .index is derived: rewrite it to match the recovered log
        with open(self.index_path, "wb") as fh:
            for entry in self._index:
                fh.write(_INDEX_ENTRY.pack(*entry))

    # -- append ------------------------------------------------------------

    def append(self, payload: bytes) -> int:
        offset = self.next_offset
        rec = records.pack_record(offset, payload)
        if self._bytes_since_index >= self.index_interval_bytes \
                or self.size == 0:
            self._index.append((offset, self.size))
            self._index_fh.write(_INDEX_ENTRY.pack(offset, self.size))
            self._bytes_since_index = 0
        self._fh.write(rec)
        self.size += len(rec)
        self._bytes_since_index += len(rec)
        self.next_offset = offset + 1
        return offset

    def flush(self, sync: bool = False) -> None:
        self._fh.flush()
        if sync:
            os.fsync(self._fh.fileno())

    def close(self) -> None:
        self._fh.close()
        self._index_fh.close()

    # -- read --------------------------------------------------------------

    def seek_position(self, offset: int) -> int:
        """File position of the index floor entry for `offset` — the
        sparse seek: at most one index interval of records is scanned
        past this position."""
        if not self._index:
            return 0
        i = bisect.bisect_right([o for o, _ in self._index], offset) - 1
        return self._index[max(i, 0)][1]

    def read_from(self, offset: int):
        """Yield (offset, payload) for records with offset >= `offset`.
        Reads through a fresh handle so concurrent appends (from the
        owning writer thread) can't interleave with the scan."""
        self._fh.flush()
        with open(self.log_path, "rb") as fh:
            fh.seek(self.seek_position(offset))
            buf = fh.read()
        for rec_offset, payload, _ in records.scan(buf):
            if rec_offset >= offset:
                yield rec_offset, payload

    def read_at(self, offset: int) -> bytes:
        """CRC-verified point read of the single record at `offset`.

        The positioned-read primitive the tiered store's cold tier is
        built on (docs/TIERING.md): binary-search the sparse index for
        the floor position, then hop header-to-header (records.py
        `peek_header` — 16 bytes per hop, no payload reads) until the
        target record, and CRC-verify only that one.  At most one
        `index_interval_bytes` of headers is walked.

        Raises KeyError if `offset` is outside the segment's recovered
        range or the record at it fails CRC — a torn tail past the
        recovery point is "not present", never garbage bytes.
        """
        if not self.base_offset <= offset < self.next_offset:
            raise KeyError(offset)
        self._fh.flush()
        with open(self.log_path, "rb") as fh:
            pos = self.seek_position(offset)
            while True:
                fh.seek(pos)
                header = fh.read(records.HEADER_SIZE)
                peeked = records.peek_header(header, 0)
                if peeked is None:
                    raise KeyError(offset)        # torn/corrupt tail
                rec_offset, length = peeked
                if rec_offset > offset:
                    raise KeyError(offset)        # hole: offset skipped
                if rec_offset == offset:
                    rec = records.unpack_record(
                        header + fh.read(length), 0)
                    if rec is None:
                        raise KeyError(offset)    # CRC mismatch
                    return rec[1]
                pos += records.HEADER_SIZE + length

    def delete(self) -> None:
        self.close()
        for p in (self.log_path, self.index_path):
            if os.path.exists(p):
                os.remove(p)
