"""DurableFabric — the in-process fabric backed by the commit log.

Same API as `runtime/fabric.Fabric` (send / poll / poll_blocking /
purge / contains / pending), so every drive loop and node runs
unchanged; each send additionally appends the message's binary serde
frame (`runtime/serde.py`) to the partition's CommitLog before it is
enqueued, and each poll records the delivered offset.

Consumer groups (one per consuming role, mirroring the reference's
Kafka consumer groups, BaseKafkaApp.java:27-33):

    gradients  -> "server"   (the aggregator)
    weights    -> "workers"  (one offset entry per worker key)
    input-data -> "ingest"   (rows are consumed into buffers at
                              persist time; the offset marks ingestion)

Offsets are committed at checkpoint boundaries (`snapshot_offsets` →
checkpoint → `commit`), NOT per message: the checkpoint and the
committed offsets then describe the same instant, and recovery is
"load checkpoint, replay the tail past its offsets".  Replay is
at-least-once — the exactly-once guarantee comes from the consumer
side deduplicating by (worker_id, vector_clock) against the restored
tracker (runtime/server.ServerNode.process).
"""

from __future__ import annotations

import os

from kafka_ps_tpu.log.log import LogConfig
from kafka_ps_tpu.log.manager import LogManager, partition_key
from kafka_ps_tpu.runtime import serde
from kafka_ps_tpu.runtime.fabric import (Fabric, GRADIENTS_TOPIC,
                                         INPUT_DATA_TOPIC, WEIGHTS_TOPIC)

# consuming role per topic (the consumer-group ids on disk)
GROUP_OF_TOPIC = {
    GRADIENTS_TOPIC: "server",
    WEIGHTS_TOPIC: "workers",
    INPUT_DATA_TOPIC: "ingest",
}

# Directory name reserved under the durable root for the tiered store's
# cold partition (kafka_ps_tpu/store/cold.py, docs/TIERING.md).  It is
# NOT a fabric topic: its records are raw page bytes, not serde frames;
# no consumer group ever commits offsets for it (so retention can never
# reap a record a live page or checkpoint still references); and
# recovery must never replay it into the message queues.  LogManager
# discovery already ignores it — its segment files sit directly in the
# directory, not under digit-named key subdirs — but the name is
# reserved here so no future topic claims it.
COLD_PARTITION_DIR = "param-cold"


class DurableFabric(Fabric):
    """Keyed FIFO fabric whose every message is also a durable,
    offset-addressed log record."""

    durable = True

    def __init__(self, root: str, config: LogConfig | None = None,
                 tracer=None, telemetry=None):
        super().__init__(tracer)
        if telemetry is None:
            from kafka_ps_tpu.telemetry import NULL_TELEMETRY
            telemetry = NULL_TELEMETRY
        self._telemetry = telemetry
        self._m_replays = {
            t: telemetry.counter("log_replays_total", topic=t)
            for t in (WEIGHTS_TOPIC, GRADIENTS_TOPIC)}
        self.manager = LogManager(root, config, tracer=self._tracer,
                                  telemetry=telemetry)
        # next undelivered offset per partition; starts at the replay
        # position set by recover() and advances on every poll
        self._delivered: dict[tuple[str, int], int] = {}
        self._recovered = False

    def cold_dir(self) -> str:
        """The reserved cold-partition directory under this fabric's
        root — co-located so one `--durable-log DIR` carries both the
        message log and the tiered store's cold pages."""
        return os.path.join(self.manager.root, COLD_PARTITION_DIR)

    # -- producer side -----------------------------------------------------

    def send(self, topic: str, key: int, message) -> None:
        offset = self.manager.get(topic, key).append(
            serde.to_bytes(message))
        self._tracer.count(f"send.{topic}")
        with self._cond:
            self._q(topic, key).append((offset, message))
            self._cond.notify_all()

    def send_transient(self, topic: str, key: int, message) -> None:
        """Enqueue WITHOUT logging: advisory in-process traffic (gang
        notices) that has no serde frame and must not survive a restart
        — a replayed notice would promise weights messages whose
        delivery already happened.  Queued as (None, message); polls
        skip the offset bookkeeping for such entries."""
        self._tracer.count(f"send.{topic}")
        with self._cond:
            self._q(topic, key).append((None, message))
            self._cond.notify_all()

    def persist(self, topic: str, key: int, message) -> int:
        """Append to the log WITHOUT enqueueing — for traffic consumed
        by the caller at send time (the INPUT_DATA hop: the producer
        sinks the row straight into a buffer).  The caller marks the
        offset consumed with `mark_consumed` once the row is applied."""
        offset = self.manager.get(topic, key).append(
            serde.to_bytes(message))
        self._tracer.count(f"send.{topic}")
        return offset

    def mark_consumed(self, topic: str, key: int, offset: int) -> None:
        with self._cond:
            self._delivered[(topic, key)] = offset + 1

    # -- consumer side -----------------------------------------------------

    def poll(self, topic: str, key: int = 0):
        with self._cond:
            q = self._q(topic, key)
            if not q:
                return None
            offset, msg = q.popleft()
            if offset is not None:       # transient entries have no offset
                self._delivered[(topic, key)] = offset + 1
            return msg

    def poll_blocking(self, topic: str, key: int = 0,
                      timeout: float | None = None):
        with self._cond:
            q = self._q(topic, key)
            if not q:
                self._cond.wait_for(lambda: bool(q), timeout=timeout)
            if not q:
                return None
            offset, msg = q.popleft()
            if offset is not None:       # transient entries have no offset
                self._delivered[(topic, key)] = offset + 1
            return msg

    def purge(self, topic: str, key: int, pred) -> int:
        return super().purge(topic, key, lambda e: pred(e[1]))

    def contains(self, topic: str, key: int, pred) -> bool:
        return super().contains(topic, key, lambda e: pred(e[1]))

    # -- offsets / recovery ------------------------------------------------

    def snapshot_offsets(self) -> dict[str, int]:
        """{"topic/key": next undelivered offset} — the instant a
        checkpoint covers.  Taken under the fabric lock so it is
        consistent with the queues."""
        with self._cond:
            return {partition_key(t, k): off
                    for (t, k), off in sorted(self._delivered.items())}

    def commit(self, offsets: dict[str, int] | None = None) -> None:
        """Durably commit consumer offsets (defaults to the current
        snapshot), fsync the logs up to them, and reap fully-consumed
        segments."""
        offsets = offsets if offsets is not None else self.snapshot_offsets()
        self.manager.flush()
        by_group: dict[str, dict[str, int]] = {}
        for pk, off in offsets.items():
            topic = pk.split("/", 1)[0]
            group = GROUP_OF_TOPIC.get(topic, topic)
            by_group.setdefault(group, {})[pk] = off
        for group, offs in by_group.items():
            self.manager.commit(group, offs)

    def start_offset(self, topic: str, key: int,
                     checkpoint_offsets: dict[str, int] | None) -> int:
        """Where replay starts for a partition: the checkpoint's
        recorded offset when one is given (authoritative — it matches
        the restored server/worker state), else the group's durably
        committed offset, else 0 (full replay)."""
        pk = partition_key(topic, key)
        if checkpoint_offsets is not None and pk in checkpoint_offsets:
            return int(checkpoint_offsets[pk])
        return self.manager.committed(GROUP_OF_TOPIC.get(topic, topic),
                                      topic, key)

    def replay(self, topic: str, key: int,
               checkpoint_offsets: dict[str, int] | None = None):
        """Yield (offset, message) for the unconsumed tail of a
        partition (decoded through serde.from_bytes)."""
        start = self.start_offset(topic, key, checkpoint_offsets)
        for offset, payload in self.manager.get(topic, key).read_from(start):
            yield offset, serde.from_bytes(payload)

    def latest_logged_weights(self):
        """The newest logged WeightsMessage (by vector clock) across all
        WEIGHTS partitions, or None when none was ever logged.

        Serve-from-cold-start freshness (docs/SERVING.md): a restarting
        `--serve` process publishes the restored checkpoint theta as its
        first snapshot, then — when the log's newest released weights
        are strictly ahead of the restored stable clock — publishes that
        record too, so readers immediately see everything the dead
        process had already RELEASED (a released message is a promise:
        some worker may have observed it pre-crash)."""
        best = None
        for topic, key in self.manager.partitions(WEIGHTS_TOPIC):
            last_payload = None
            for _offset, payload in self.manager.get(topic,
                                                     key).read_from(0):
                last_payload = payload   # per-partition clocks ascend
            if last_payload is None:
                continue
            msg = serde.from_bytes(last_payload)
            if best is None or msg.vector_clock > best.vector_clock:
                best = msg
        return best

    def recover(self, checkpoint_offsets: dict[str, int] | None = None
                ) -> dict[str, int]:
        """Re-enqueue the unconsumed WEIGHTS / GRADIENTS tail into the
        in-memory queues (crash recovery: a restarted process sees
        exactly the in-flight messages the dead one had).  INPUT_DATA
        is not enqueued — the app replays it into buffers itself
        (runtime/app.StreamingPSApp.recover_durable).  Returns replay
        counts per topic."""
        if self._recovered:
            raise RuntimeError("recover() must run once, before the "
                               "drive loop")
        self._recovered = True
        counts = {WEIGHTS_TOPIC: 0, GRADIENTS_TOPIC: 0}
        # A live gate release aliases ONE message object into every
        # worker's partition; the gang dispatcher keys its broadcast-vs-
        # stacked program choice on that identity (runtime/gang.py).
        # Deserializing each partition's copy separately would replay
        # the same release through a DIFFERENT XLA program (1-ULP delta
        # drift, poisonous under error-feedback compression) — so byte-
        # identical weights payloads re-share one deserialized object.
        weights_cache: dict[bytes, object] = {}
        with self._cond:
            for topic, key in self.manager.partitions():
                if topic == COLD_PARTITION_DIR:   # raw page bytes, not
                    continue                      # serde frames
                start = self.start_offset(topic, key, checkpoint_offsets)
                self._delivered[(topic, key)] = start
                if topic == INPUT_DATA_TOPIC:
                    continue
                q = self._q(topic, key)
                for offset, payload in \
                        self.manager.get(topic, key).read_from(start):
                    if topic == WEIGHTS_TOPIC:
                        blob = bytes(payload)
                        msg = weights_cache.get(blob)
                        if msg is None:
                            msg = serde.from_bytes(payload)
                            weights_cache[blob] = msg
                    else:
                        msg = serde.from_bytes(payload)
                    q.append((offset, msg))
                    counts[topic] = counts.get(topic, 0) + 1
                    self._tracer.count(f"log.replays.{topic}")
                    if self._telemetry.enabled:
                        self._m_replays[topic].inc()
            self._cond.notify_all()
        return counts

    def close(self) -> None:
        self.manager.close()
