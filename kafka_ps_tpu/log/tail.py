"""Read-only commit-log tailing for log-following read replicas
(docs/SERVING.md, "Operating at load").

A replica process follows a training deployment's durable log without
ever attaching to the live fabric — and, critically, without ever
OPENING the log for writing.  `CommitLog`/`LogSegment` are the writer's
view: `LogSegment._recover()` truncates a torn tail on open, which is
correct crash recovery for the owner but data loss if a *reader* does
it to a live writer's file.  This module therefore never constructs
any of those classes; it opens segment files read-only and walks them
with `records.scan`, which stops cleanly at the first invalid record.
A torn tail (the writer mid-append) is simply re-read on the next
poll once the writer finishes the record.

Byte positions are tracked per segment file, so a poll does O(new
bytes) work: sealed segments are skipped by size, and the active
segment is read from the last consumed record boundary.  Segment roll
needs no special case — a new `*.log` file shows up in the directory
listing and starts at position 0.
"""

from __future__ import annotations

import os

from kafka_ps_tpu.log import records


class PartitionTailer:
    """Incremental reader over one partition directory's segment files.

    `poll()` returns every record appended since the previous poll as
    `(offset, payload)` pairs, in log order.  Single-threaded by
    contract (one tailer per follower thread); holds no file handles
    between polls so the writer's retention/rename activity can never
    deadlock against us.
    """

    def __init__(self, path: str):
        self.path = path
        # segment basename -> next unread byte position (always a
        # record boundary: scan() only yields whole valid records)
        self._positions: dict[str, int] = {}

    def poll(self) -> list[tuple[int, bytes]]:
        out: list[tuple[int, bytes]] = []
        try:
            names = sorted(n for n in os.listdir(self.path)
                           if n.endswith(".log"))
        except FileNotFoundError:
            return out                  # partition not created yet
        for name in names:
            pos = self._positions.get(name, 0)
            full = os.path.join(self.path, name)
            try:
                if os.path.getsize(full) <= pos:
                    continue            # sealed or idle segment
                with open(full, "rb") as fh:
                    if pos:
                        fh.seek(pos)
                    buf = fh.read()
            except OSError:
                continue                # raced retention; retry next poll
            consumed = 0
            for offset, payload, rec_pos in records.scan(buf):
                out.append((offset, payload))
                consumed = rec_pos + records.HEADER_SIZE + len(payload)
            # anything past `consumed` is a torn tail (writer
            # mid-append) — leave the position at the record boundary
            # and re-read it next poll
            self._positions[name] = pos + consumed
        return out


class TopicTailer:
    """Tail every partition of one topic under a durable-log root.

    The layout is `root/<topic>/<key>/<segment>.log` (log/manager.py);
    partitions appear as workers join, so the directory is re-listed on
    every poll.  Records come back as `(key, offset, payload)`.
    """

    def __init__(self, root: str, topic: str = "weights"):
        self.root = root
        self.topic = topic
        self._partitions: dict[int, PartitionTailer] = {}

    def keys(self) -> tuple[int, ...]:
        return tuple(sorted(self._partitions))

    def poll(self) -> list[tuple[int, int, bytes]]:
        topic_dir = os.path.join(self.root, self.topic)
        try:
            names = os.listdir(topic_dir)
        except FileNotFoundError:
            return []
        for name in names:
            if name.isdigit() and int(name) not in self._partitions:
                self._partitions[int(name)] = PartitionTailer(
                    os.path.join(topic_dir, name))
        out: list[tuple[int, int, bytes]] = []
        for key in sorted(self._partitions):
            for offset, payload in self._partitions[key].poll():
                out.append((key, offset, payload))
        return out
