"""LogManager — the broker-side registry: (topic, key) partitions on
disk plus consumer groups with durable committed offsets.

Directory layout under the root (`--durable-log DIR`):

    DIR/
      weights/0/00000000000000000000.log       one CommitLog per
      weights/0/00000000000000000000.index       (topic, key) partition
      gradients/0/...
      input-data/3/...
      offsets/server.json                       committed offsets per
      offsets/workers.json                        consumer group

A group's offset file maps "topic/key" -> next offset to consume
(Kafka's __consumer_offsets, as an atomically-replaced JSON file).
Committing also drives retention: segments below the minimum committed
offset across ALL groups that track a partition become deletable;
partitions no group has committed for are never reaped.
"""

from __future__ import annotations

import json
import os

from kafka_ps_tpu.log.log import CommitLog, LogConfig
from kafka_ps_tpu.utils.trace import NULL_TRACER


def partition_key(topic: str, key: int) -> str:
    return f"{topic}/{key}"


class LogManager:
    """Partition registry + consumer-group offset store over one root
    directory.  Single-writer per partition (the in-process fabric), so
    no cross-process locking."""

    def __init__(self, root: str, config: LogConfig | None = None,
                 tracer=None, telemetry=None):
        self.root = root
        self.config = config or LogConfig()
        self.tracer = tracer or NULL_TRACER
        if telemetry is None:
            from kafka_ps_tpu.telemetry import NULL_TELEMETRY
            telemetry = NULL_TELEMETRY
        self.telemetry = telemetry
        self._logs: dict[tuple[str, int], CommitLog] = {}
        self._offsets_dir = os.path.join(root, "offsets")
        os.makedirs(self._offsets_dir, exist_ok=True)
        self._groups: dict[str, dict[str, int]] = {}
        for f in os.listdir(self._offsets_dir):
            if f.endswith(".json"):
                with open(os.path.join(self._offsets_dir, f)) as fh:
                    self._groups[f[:-5]] = {k: int(v) for k, v
                                            in json.load(fh).items()}
        # open every partition already on disk (recovery scans tails)
        for topic, key in self._discover():
            self.get(topic, key)

    def _discover(self):
        for topic in sorted(os.listdir(self.root)):
            tdir = os.path.join(self.root, topic)
            if topic == "offsets" or not os.path.isdir(tdir):
                continue
            for key in sorted(os.listdir(tdir)):
                if key.isdigit() and os.path.isdir(os.path.join(tdir, key)):
                    yield topic, int(key)

    # -- partitions --------------------------------------------------------

    def get(self, topic: str, key: int) -> CommitLog:
        log = self._logs.get((topic, key))
        if log is None:
            log = CommitLog(os.path.join(self.root, topic, str(key)),
                            self.config, tracer=self.tracer,
                            name=partition_key(topic, key),
                            telemetry=self.telemetry)
            self._logs[(topic, key)] = log
        return log

    def partitions(self, topic: str | None = None):
        """Known (topic, key) pairs, optionally filtered by topic."""
        return sorted(tk for tk in self._logs
                      if topic is None or tk[0] == topic)

    @property
    def truncated_bytes(self) -> int:
        """Corrupt tail bytes discarded across all partitions on open."""
        return sum(log.truncated_bytes for log in self._logs.values())

    # -- consumer groups ---------------------------------------------------

    def committed(self, group: str, topic: str, key: int) -> int:
        """Next offset `group` should consume for the partition (0 when
        the group never committed)."""
        return self._groups.get(group, {}).get(partition_key(topic, key), 0)

    def commit(self, group: str, offsets: dict[str, int]) -> None:
        """Durably record {"topic/key": next_offset} for `group`
        (atomic tmp+rename, like utils/checkpoint.py), then reap
        fully-consumed segments."""
        merged = self._groups.setdefault(group, {})
        merged.update({k: int(v) for k, v in offsets.items()})
        path = os.path.join(self._offsets_dir, f"{group}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(merged, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        self.tracer.count("log.offset_commits")
        self.apply_retention()

    def apply_retention(self) -> int:
        """Delete segments every tracking group has fully consumed.
        Returns total segments deleted."""
        deleted = 0
        for (topic, key), log in self._logs.items():
            pk = partition_key(topic, key)
            tracked = [g[pk] for g in self._groups.values() if pk in g]
            if tracked:
                deleted += log.apply_retention(min(tracked))
        return deleted

    def flush(self) -> None:
        for log in self._logs.values():
            log.flush()

    def close(self) -> None:
        for log in self._logs.values():
            log.close()
