"""CommitLog — one (topic, key) partition: a directory of segments
with monotonic offsets, configurable segment roll + retention, and an
fsync policy.

Append path: write to the active segment, roll to a new segment once it
reaches `segment_bytes`, fsync per policy.  Read path: pick the segment
whose base offset floors the target (segments are sorted by base
offset), sparse-index seek inside it, scan forward.

Fsync policy (the Kafka `flush.messages`/OS-page-cache trade-off,
docs/DURABILITY.md):
  * "none"     — leave durability to the OS page cache (fastest; a
                 *machine* crash can lose recent records, a process
                 crash cannot — the kernel already has the bytes);
  * "interval" — fsync at most once per `fsync_interval_s` seconds,
                 checked on append (bounded loss window, default);
  * "always"   — fsync every append (slowest, zero loss window).

Retention deletes only segments that are BOTH rolled (not the active
segment) AND fully consumed — every record's offset is below the
minimum committed offset the caller passes in.  Nothing is ever deleted
by age or size alone: an unconsumed record is never dropped.
"""

from __future__ import annotations

import bisect
import dataclasses
import os
import time

from kafka_ps_tpu.log.segment import LogSegment, segment_basename
from kafka_ps_tpu.telemetry.flight import FLIGHT
from kafka_ps_tpu.utils.trace import NULL_TRACER


@dataclasses.dataclass(frozen=True)
class LogConfig:
    """Knobs of one partition log (shared by every partition under a
    LogManager)."""

    segment_bytes: int = 16 * 1024 * 1024   # roll threshold
    index_interval_bytes: int = 4096        # sparse-index granularity
    fsync: str = "interval"                 # none | interval | always
    fsync_interval_s: float = 1.0

    def __post_init__(self):
        if self.fsync not in ("none", "interval", "always"):
            raise ValueError(f"unknown fsync policy {self.fsync!r}")
        if self.segment_bytes <= 0:
            raise ValueError("segment_bytes must be positive")


class CommitLog:
    """Segmented append-only log for one partition."""

    def __init__(self, directory: str, config: LogConfig | None = None,
                 tracer=None, name: str = "", telemetry=None):
        self.directory = directory
        self.config = config or LogConfig()
        self.tracer = tracer or NULL_TRACER
        if telemetry is None:
            from kafka_ps_tpu.telemetry import NULL_TELEMETRY
            telemetry = NULL_TELEMETRY
        self.telemetry = telemetry
        self._m_appends = telemetry.counter("log_appends_total")
        self._m_fsync_ms = telemetry.histogram("log_fsync_ms")
        self.name = name or directory
        os.makedirs(directory, exist_ok=True)
        self.segments: list[LogSegment] = []
        self.truncated_bytes = 0
        self._last_fsync = time.monotonic()
        self._open_existing()

    def _open_existing(self) -> None:
        bases = sorted(int(f[:-4]) for f in os.listdir(self.directory)
                       if f.endswith(".log"))
        if not bases:
            bases = [0]
        # only the LAST segment can have a torn tail (earlier ones were
        # completed by a roll), but recovering each is cheap and also
        # rebuilds any stale index
        for base in bases:
            seg = LogSegment(self.directory, base,
                             self.config.index_interval_bytes)
            self.truncated_bytes += seg.truncated_bytes
            self.segments.append(seg)
        if self.truncated_bytes:
            self.tracer.count("log.truncated_bytes", self.truncated_bytes)

    # -- append ------------------------------------------------------------

    @property
    def active(self) -> LogSegment:
        return self.segments[-1]

    @property
    def next_offset(self) -> int:
        return self.active.next_offset

    @property
    def start_offset(self) -> int:
        """Oldest retained offset (retention may have deleted earlier
        segments)."""
        return self.segments[0].base_offset

    def append(self, payload: bytes) -> int:
        if self.active.size >= self.config.segment_bytes:
            self._roll()
        offset = self.active.append(payload)
        self.tracer.count("log.appends")
        if self.telemetry.enabled:
            self._m_appends.inc()
        if FLIGHT.enabled:
            FLIGHT.record("log.append", log=self.name, offset=offset,
                          bytes=len(payload))
        self._maybe_fsync()
        return offset

    def _roll(self) -> None:
        self.active.flush(sync=self.config.fsync != "none")
        seg = LogSegment(self.directory, self.next_offset,
                         self.config.index_interval_bytes)
        self.segments.append(seg)
        self.tracer.count("log.segment_rolls")

    def _maybe_fsync(self) -> None:
        policy = self.config.fsync
        if policy == "none":
            self.active.flush(sync=False)
            return
        now = time.monotonic()
        if policy == "always" or \
                now - self._last_fsync >= self.config.fsync_interval_s:
            self._timed_fsync()
            self._last_fsync = now
        else:
            self.active.flush(sync=False)

    def _timed_fsync(self) -> None:
        """The single sync-flush site: the fsync stall IS the durability
        tax --log-fsync buys, so its latency distribution is a first-
        class metric (docs/DURABILITY.md trade-off table)."""
        FLIGHT.enter("log.fsync")      # watchdog sees a wedged syscall
        t0 = time.perf_counter()
        self.active.flush(sync=True)
        dt_ms = (time.perf_counter() - t0) * 1e3
        FLIGHT.exit("log.fsync")
        self.tracer.count("log.fsyncs")
        if self.telemetry.enabled:
            self._m_fsync_ms.observe(dt_ms)
        if FLIGHT.enabled:
            FLIGHT.record("log.fsync", log=self.name, ms=round(dt_ms, 3))

    def flush(self) -> None:
        """Force an fsync of the active segment regardless of policy —
        called at clean shutdown and at commit points."""
        self._timed_fsync()
        self._last_fsync = time.monotonic()

    # -- read --------------------------------------------------------------

    def read_from(self, offset: int):
        """Yield (offset, payload) for every retained record with
        offset >= `offset`, across segments, in order."""
        for i, seg in enumerate(self.segments):
            nxt = self.segments[i + 1].base_offset \
                if i + 1 < len(self.segments) else None
            if nxt is not None and nxt <= offset:
                continue               # fully below the target
            yield from seg.read_from(offset)

    def read_at(self, offset: int) -> bytes:
        """CRC-verified point read of the single record at `offset` —
        bisect the owning segment by base offset, sparse-index seek
        inside it (LogSegment.read_at).  Raises KeyError for offsets
        below retention, past the tail, or failing CRC."""
        bases = [seg.base_offset for seg in self.segments]
        i = bisect.bisect_right(bases, offset) - 1
        if i < 0:
            raise KeyError(offset)     # below the retained start offset
        return self.segments[i].read_at(offset)

    # -- retention ---------------------------------------------------------

    def apply_retention(self, min_committed_offset: int) -> int:
        """Delete segments that are rolled AND fully consumed (every
        offset < `min_committed_offset`).  Returns segments deleted."""
        deleted = 0
        while len(self.segments) > 1 and \
                self.segments[1].base_offset <= min_committed_offset:
            self.segments.pop(0).delete()
            deleted += 1
        if deleted:
            self.tracer.count("log.segments_deleted", deleted)
        return deleted

    def close(self) -> None:
        self.active.flush(sync=self.config.fsync != "none")
        for seg in self.segments:
            seg.close()


def partition_dirname(topic: str, key: int) -> str:
    return os.path.join(topic, str(key))


__all__ = ["CommitLog", "LogConfig", "partition_dirname",
           "segment_basename"]
