"""Record framing for the commit log — CRC32-framed, length-prefixed.

On-disk layout of one record (little-endian, like `runtime/serde.py`):

    +--------+---------+--------+----------------+
    | offset | length  | crc32  | payload bytes  |
    |  i64   |  u32    |  u32   | `length` bytes |
    +--------+---------+--------+----------------+

The offset is the record's logical position in its partition (stored
redundantly so a segment is self-describing — an index file can be
rebuilt from the .log alone).  The CRC covers offset + length + payload,
so a corrupted header is detected too, not just a corrupted body.
Kafka's v0 message set used the same shape (offset, size, crc, payload).

`scan` implements the recovery rule every restart runs on the last
segment: the longest valid prefix is the log; the first truncated or
CRC-corrupt record and everything after it is discarded (the bytes a
crash left half-written were never acknowledged, so dropping them is
correct, not lossy).
"""

from __future__ import annotations

import struct
import zlib

_PREFIX = struct.Struct("<qI")        # offset, payload length
_CRC = struct.Struct("<I")
HEADER_SIZE = _PREFIX.size + _CRC.size

# backstop against reading an absurd length out of a corrupt header and
# allocating it: no control-plane message is remotely this large
MAX_RECORD_BYTES = 64 * 1024 * 1024


def pack_record(offset: int, payload: bytes) -> bytes:
    prefix = _PREFIX.pack(offset, len(payload))
    crc = zlib.crc32(payload, zlib.crc32(prefix))
    return prefix + _CRC.pack(crc) + payload


def unpack_record(buf: bytes, pos: int) -> tuple[int, bytes, int] | None:
    """(offset, payload, next_pos) for the record at `pos`, or None if
    the bytes from `pos` are not a complete, CRC-valid record (the
    truncated/corrupt tail case — callers discard from `pos` on)."""
    if pos + HEADER_SIZE > len(buf):
        return None
    offset, length = _PREFIX.unpack_from(buf, pos)
    if length > MAX_RECORD_BYTES or offset < 0:
        return None
    end = pos + HEADER_SIZE + length
    if end > len(buf):
        return None
    (stored_crc,) = _CRC.unpack_from(buf, pos + _PREFIX.size)
    payload = buf[pos + HEADER_SIZE:end]
    crc = zlib.crc32(payload, zlib.crc32(buf[pos:pos + _PREFIX.size]))
    if crc != stored_crc:
        return None
    return offset, bytes(payload), end


def peek_header(buf: bytes, pos: int) -> tuple[int, int] | None:
    """(offset, payload_length) from the 16-byte header at `pos`, or
    None if the header is truncated or obviously corrupt.  Does NOT
    verify the CRC — this is the cheap skip-scan primitive positioned
    point reads (`LogSegment.read_at`) use to hop record-to-record from
    an index floor without touching payload bytes; the target record
    itself is always CRC-verified via `unpack_record`."""
    if pos + HEADER_SIZE > len(buf):
        return None
    offset, length = _PREFIX.unpack_from(buf, pos)
    if length > MAX_RECORD_BYTES or offset < 0:
        return None
    return offset, length


def scan(buf: bytes, pos: int = 0):
    """Yield (offset, payload, record_pos) for the valid record prefix
    of `buf` starting at `pos`; stops at the first invalid record."""
    while True:
        rec = unpack_record(buf, pos)
        if rec is None:
            return
        offset, payload, next_pos = rec
        yield offset, payload, pos
        pos = next_pos


def valid_length(buf: bytes, pos: int = 0) -> int:
    """Byte length of the valid record prefix — the truncation point
    recovery resets a crashed segment file to."""
    for _, payload, rec_pos in scan(buf, pos):
        pos = rec_pos + HEADER_SIZE + len(payload)
    return pos
