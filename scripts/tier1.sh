#!/bin/bash
# Tier-1 verify: the exact command the driver runs (ROADMAP.md).
# Passes iff the suite exits 0 within the timeout; DOTS_PASSED echoes
# the progress-dot count so regressions against the recorded floor are
# visible at a glance.
#
# `scripts/tier1.sh --gang` runs the gang-dispatch smoke leg instead: a
# tiny serial run with coalescing on vs off, asserting identical final
# theta (bitwise) and a strictly lower device-dispatch count
# (docs/GANG_DISPATCH.md).
#
# `scripts/tier1.sh --serve` runs the serving-plane smoke leg: train a
# tiny model with serving enabled, predict in-process AND over the
# socket (PredictClient), and assert the staleness rejection path fires
# (docs/SERVING.md).
#
# `scripts/tier1.sh --compress` runs the compressed-transport smoke leg:
# socket mode end-to-end under --compress int8 — HELLO codec
# negotiation, batched T_DATA_BATCH ingest, error-feedback training to
# completion, and strictly fewer bytes on the wire than the
# uncompressed arm (docs/COMPRESSION.md).
#
# `scripts/tier1.sh --perf` runs the incremental-slab smoke leg: tiny
# serial runs asserting the incremental device slab trains to a
# BITWISE-identical theta vs whole-slab re-upload (f32, all three
# consistency models) and that bf16 slab storage trains end-to-end
# (docs/PERFORMANCE.md).
#
# `scripts/tier1.sh --shard` runs the range-sharding smoke leg: a
# socket-bridged fleet of 2 shard-server processes + 1 worker process
# (2 logical workers), SIGKILL one shard mid-run, restart it, and prove
# bitwise recovery by replaying each shard's per-shard durable-log
# gradients partition through a fresh ServerNode and comparing against
# the shard's final checkpoint theta bytes (docs/SHARDING.md).
#
# `scripts/tier1.sh --agg` runs the aggregation-tier smoke leg
# (docs/AGGREGATION.md): a socket fleet of 1 server (--bsp-order) + 2
# aggregator relays x 2 worker processes (4 logical workers), SIGKILL
# one relay mid-run, restart it (workers resend their caches through
# it), and assert final theta AND the server eval CSV (timestamps
# stripped) bitwise-equal to a direct no-relay fleet with the same
# flags (AGG_SMOKE_OK).
#
# `scripts/tier1.sh --wire` runs the wire-engine smoke leg
# (docs/WIRE.md): a socket fleet of 1 server (--bsp-order) + 1
# aggregator relay + 2 member worker processes (4 logical workers) runs
# twice — frame coalescing on (default) vs --no-wire-coalesce.  In EACH
# arm one member worker process is SIGKILL'd mid-run and restarted
# (durable worker state + relay weights stash + the server's READY
# liveness reissue recover the stalled round), and final theta AND the
# server eval CSV (timestamps stripped) must be bitwise-equal across
# the coalescing lever (WIRE_SMOKE_OK).
#
# `scripts/tier1.sh --eval` runs the async-eval smoke leg
# (docs/EVALUATION.md "Async evaluation"): a socket fleet of 1 server
# (--bsp-order) + 1 aggregator relay + 2 member worker processes (4
# logical workers) at eval_every=1 runs twice — the async coalescing
# eval engine on (default) vs --no-eval-async (fused apply+eval).  In
# EACH arm one member worker process is SIGKILL'd mid-run and
# restarted (pending evals in the engine queue hold no durable state —
# recovery is entirely the existing worker-state + relay-stash + READY
# reissue machinery), and final theta AND the server eval CSV
# (timestamps stripped) must be bitwise-equal across the eval lever
# (EVAL_SMOKE_OK).
#
# `scripts/tier1.sh --load` runs the serving-load smoke leg: a child
# training process serving over a socket (--serve --serve_port
# --serve-queue) driven by THIS process's load generator — zero
# deadline violations at low rate, >=1 explicit typed shed under a
# flash crowd, an offered-rate Poisson arm (open loop, latency from
# scheduled arrival) answering within the smoke SLO with zero errors,
# and the trained theta bitwise-identical to a no-load run
# (docs/SERVING.md, "Operating at load").  A final in-process arm
# proves the adaptive dispatcher settles on the batching BYPASS at
# low concurrency with p99 no worse than a hand-tuned unbatched
# engine (docs/SERVING.md, "Dispatch economics").
#
# `scripts/tier1.sh --tier` runs the tiered-parameter-store smoke leg
# (docs/TIERING.md): train through the public CLI with the hot tier
# capped at ~1/13 of the parameter bytes (+ a warm cap, so most pages
# live as commit-log records), for all three consistency models,
# asserting final theta AND the eval CSV (timestamp column stripped)
# bitwise-equal to the uncapped run; then SIGKILL a capped durable run
# mid-training, restart it, and prove bitwise recovery by replaying the
# gradients partition through a fresh fully-resident ServerNode against
# the restarted run's final checkpoint — whose recorded residency must
# still hold cold pages (faulted in on demand, never pre-materialized).
#
# `scripts/tier1.sh --analyze` runs the static-analysis leg: pscheck
# (docs/ANALYSIS.md) over the package — fails on ANY unsuppressed
# finding — plus ruff (pyproject.toml, rule sets E/F/B/PLE) when the
# binary is installed.
#
# `scripts/tier1.sh --obs` runs the observability smoke leg in two
# phases (docs/OBSERVABILITY.md): (1) one short socket-bridged run PER
# consistency model with tracing and metrics on (tracer pid pairs
# standing in for the `--listen --trace` / `--connect --trace`
# processes), asserting the six-trace merge contains >= 1 cross-process
# flow, the Prometheus dump parses with the staleness histogram
# families populated, and `python -m kafka_ps_tpu.telemetry critpath`
# exits 0 over the merged trace naming a dominant segment per model —
# BSP's must be gate_wait (OBS_CRITPATH_OK); (2) a subprocess fleet
# (2 shard servers + 1 worker, all with --flight-dir) where shard 1 is
# SIGKILLed mid-run — the survivors' flight dumps must exist, the
# killed shard's must not, and `python -m kafka_ps_tpu.telemetry
# postmortem` must exit 0 naming the dead shard and its last
# acknowledged weights send (POSTMORTEM_OK).
#
# `scripts/tier1.sh --drift` runs the model-health smoke leg
# (docs/OBSERVABILITY.md, "Model health & drift"): a socket-bridged
# server + worker pair (2 logical workers) trains with --model-health
# on a stream whose second half is label-flipped and feature-shifted —
# the server's drift plane must latch DRIFT (observed live over
# /modelz), the armed drift watchdog must ship a flight dump carrying
# the drift.trip event, and the wall-clock-stamped drift CSV must
# record the trip; a clean serial control run with the same flags must
# finish with ZERO trip rows (DRIFT_SMOKE_OK).
#
# `scripts/tier1.sh --bench-gate` runs the bench regression gate
# (scripts/bench_gate.py): the committed bench_out.json must pass
# against the committed BENCH_r*.json baselines, and a synthetic 20%
# worker-throughput regression must FAIL the gate naming the metric
# (BENCH_GATE_OK).  Waivers: scripts/bench_waivers.txt.
set -o pipefail

if [[ "${1:-}" == "--analyze" ]]; then
    # 1) drive the real threaded subsystems under an isolated recorder
    #    and dump the runtime lock-order edges the static graph is
    #    diffed against (the test_migrated_production_locks driver)
    EDGES=$(mktemp /tmp/kps_lock_edges.XXXXXX.json)
    trap 'rm -f "$EDGES"' EXIT
    timeout -k 10 120 env JAX_PLATFORMS=cpu python - "$EDGES" <<'EOF' || exit 1
import json
import sys
import tempfile
import threading

from kafka_ps_tpu.analysis import lockgraph
from kafka_ps_tpu.data.buffer import SlidingBuffer
from kafka_ps_tpu.runtime import fabric as fabric_mod
from kafka_ps_tpu.serving.snapshot import SnapshotRegistry
from kafka_ps_tpu.utils.asynclog import DeferredSink
from kafka_ps_tpu.utils.config import BufferConfig
from kafka_ps_tpu.utils.csvlog import CsvLogSink

with tempfile.TemporaryDirectory() as td:
    with lockgraph.isolated() as g:
        fab = fabric_mod.Fabric()
        buf = SlidingBuffer(4, BufferConfig(min_size=16, max_size=64))
        reg = SnapshotRegistry()
        csv = CsvLogSink(td + "/t.csv", header="a;b")
        sink = DeferredSink(csv, drain_interval=0.01)

        def producer():
            for i in range(50):
                fab.send(fabric_mod.WEIGHTS_TOPIC, 0, i)
                buf.add([float(i)] * 4, i % 2)
                reg.publish([float(i)], vector_clock=i)
                sink(f"{i};x")

        def consumer():
            for _ in range(50):
                fab.poll_blocking(fabric_mod.WEIGHTS_TOPIC, 0, timeout=2)
                buf.snapshot()
                _ = reg.latest

        ts = [threading.Thread(target=f) for f in (producer, consumer)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        sink.close()
        csv.close()
        cycles = g.cycles()
        edges = g.export_edges()
if cycles:
    print(f"runtime lock-order cycle: {cycles}", file=sys.stderr)
    sys.exit(1)
with open(sys.argv[1], "w", encoding="utf-8") as f:
    json.dump({"edges": edges}, f)
print(f"runtime lock edges recorded: {len(edges)}")
EOF
    # 2) psverify: pscheck + threadck + lockflow + wireck + PS107 over
    #    the package, diffed against the runtime edges; hard-fails on
    #    ANY unsuppressed finding
    REPORT=$(mktemp /tmp/kps_psverify.XXXXXX.json)
    trap 'rm -f "$EDGES" "$REPORT"' EXIT
    python -m kafka_ps_tpu.analysis kafka_ps_tpu/ --json \
        --lock-coverage "$EDGES" > "$REPORT"
    STATUS=$?
    python - "$REPORT" "$STATUS" <<'EOF' || exit 1
import json
import sys

from kafka_ps_tpu.analysis import psverify

data = json.load(open(sys.argv[1], encoding="utf-8"))
uns = data["counts"]["unsuppressed"]
sup = data["counts"]["suppressed"]
if uns or int(sys.argv[2]) != 0:
    for f in data["findings"]:
        if not f["suppressed"]:
            print(f"{f['path']}:{f['line']}: {f['rule']} {f['message']}")
    print(f"psverify: {uns} unsuppressed findings", file=sys.stderr)
    sys.exit(1)
cov = data.get("lock_coverage") or {}
print(f"lock coverage: {cov.get('common', 0)} edges exercised at "
      f"runtime, {len(cov.get('static_only', []))} static-only, "
      f"{len(cov.get('runtime_only', []))} runtime-only")
for e in cov.get("runtime_only", []):
    print(f"  runtime-only {e['src']} -> {e['dst']} @ {e.get('site', '?')}")
print(f"ANALYZE_OK rules={len(psverify.RULES)} findings={uns} "
      f"suppressed={sup}")
EOF
    if command -v ruff >/dev/null 2>&1; then
        ruff check . || exit 1
    else
        echo "ruff not installed; skipped (psverify gate ran)"
    fi
    exit 0
fi

if [[ "${1:-}" == "--load" ]]; then
    timeout -k 10 300 env JAX_PLATFORMS=cpu python - <<'EOF'
import os
import re
import subprocess
import sys
import tempfile
import time

import numpy as np

# two processes: a child training run serving over a socket, and THIS
# process driving it with the load generator.  The quiet arm repeats
# the identical (serial, deterministic) training run with serving off:
# read load must never perturb training — theta bitwise-identical.
root = tempfile.mkdtemp(prefix="kps-load-")
repo = os.getcwd()
rng = np.random.default_rng(0)
x = rng.normal(size=(256, 8)).astype(np.float32)
y = (x[:, 0] > 0).astype(np.int32) + 1
train, test = os.path.join(root, "train.csv"), os.path.join(root, "test.csv")
for path, (xx, yy) in ((train, (x[:200], y[:200])),
                       (test, (x[200:], y[200:]))):
    with open(path, "w") as fh:
        fh.write(",".join(f"f{i}" for i in range(8)) + ",Score\n")
        for r, lab in zip(xx, yy):
            fh.write(",".join(f"{v:.6f}" for v in r) + f",{lab}\n")

env = dict(os.environ, JAX_PLATFORMS="cpu",
           PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
# sized so training ALWAYS outlasts the ~10 s load window (warmup +
# low + flash crowd + poisson): ~1500 unloaded iters/s on a fast box
# -> ~11 s floor even before the load slows the trainer down (~450
# iters/s on the reference 1-core box -> ~36 s); liveness asserts
# below turn a too-fast trainer into a clear failure instead of an
# error storm
MAX_IT = 16000
common = ["-training", train, "-test", test, "--num_workers", "2",
          "--num_features", "8", "--num_classes", "2", "-min", "8",
          "-max", "32", "-p", "2", "-c", "0", "--mode", "serial",
          "--eval_every", "1000000", "--max_iterations", str(MAX_IT),
          "--checkpoint_every", "50"]

def arm(serve):
    ckpt = os.path.join(root, ("serve" if serve else "quiet") + ".npz")
    cmd = [sys.executable, "-m", "kafka_ps_tpu.cli.run", *common,
           "--checkpoint", ckpt]
    if serve:
        cmd += ["--serve", "--serve_port", "0", "--serve-queue", "4"]
    proc = subprocess.Popen(cmd, env=env, cwd=root, text=True,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.PIPE)
    port = None
    if serve:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stderr.readline()
            if not line:
                break
            m = re.search(r"serving on port (\d+)", line)
            if m:
                port = int(m.group(1))
                break
        if not port:
            proc.kill()
            raise SystemExit("child never announced its serving port")
    return proc, port, ckpt

from kafka_ps_tpu.serving import loadgen

proc, port, serve_ckpt = arm(serve=True)
target = loadgen.SocketTarget("127.0.0.1", port)
try:
    # one connection to pay the jit warmup before anything is measured
    loadgen.run_closed_loop(target, 8, concurrency=1, duration_s=1.0)
    # low rate: every request answered within the smoke SLO (500 ms is
    # generous on purpose — one core shared with training; observed
    # p99 is 30-100 ms), nothing shed, nothing errored
    low = loadgen.run_closed_loop(target, 8, concurrency=2,
                                  duration_s=3.0)
    # flash crowd: 32 in-flight against a 4-deep admission queue must
    # shed EXPLICITLY (typed PREDICT_OVERLOADED), never time out
    over = loadgen.run_closed_loop(target, 8, concurrency=32,
                                   duration_s=3.0)
    # offered-rate arm: memoryless Poisson arrivals at a modest rate —
    # the steady-state traffic model (bench.py serving_load quotes its
    # SLO against this shape).  Latency counts from the SCHEDULED
    # arrival (no coordinated omission), so the smoke SLO here also
    # covers queueing behind the shared training core.  Sheds are
    # legal (bursts can momentarily fill the 4-deep queue); errors are
    # not — every rejection must be typed.
    pois = loadgen.run_open_loop(target, 8, rate_qps=40.0,
                                 duration_s=2.5, concurrency=8,
                                 arrivals="poisson")
    # the whole point is load DURING training: if the trainer already
    # exited, the run above measured a dead socket, not admission
    assert proc.poll() is None, \
        "trainer finished before the load window (raise MAX_IT)"
finally:
    target.close()
rc = proc.wait(timeout=240)
err = proc.stderr.read()
assert rc == 0, f"serving arm rc={rc}\n{err[-4000:]}"
assert low.meets(500.0), f"low-rate SLO violated: {low.as_dict()}"
assert over.shed >= 1, f"flash crowd never shed: {over.as_dict()}"
assert over.errors == 0, f"sheds must be typed: {over.as_dict()}"
assert pois.errors == 0, f"poisson arm errored: {pois.as_dict()}"
assert pois.ok > 0, f"poisson arm answered nothing: {pois.as_dict()}"
assert pois.p99_ms <= 500.0, f"poisson SLO violated: {pois.as_dict()}"

quiet, _, quiet_ckpt = arm(serve=False)
rc = quiet.wait(timeout=240)
assert rc == 0, f"quiet arm rc={rc}\n{quiet.stderr.read()[-4000:]}"
zs, zq = np.load(serve_ckpt), np.load(quiet_ckpt)
assert int(zs["iterations"]) >= MAX_IT <= int(zq["iterations"])
ts = np.asarray(zs["theta"], np.float32)
tq = np.asarray(zq["theta"], np.float32)
assert ts.tobytes() == tq.tobytes(), \
    "read load perturbed training theta"

# -- adaptive-dispatch arm (ROADMAP item 4): at low concurrency the
# auto engine must SETTLE ON THE BYPASS PATH — no queue, no window
# wait — and its accepted p99 must be no worse than a hand-tuned
# unbatched engine (max_batch=1, deadline 0), modulo scheduler noise.
# Runs after both training children exit so the box is quiet.
from kafka_ps_tpu.models.task import get_task
from kafka_ps_tpu.serving.engine import PredictionEngine
from kafka_ps_tpu.serving.snapshot import SnapshotRegistry
from kafka_ps_tpu.utils.config import ModelConfig

def _engine(**kw):
    cfg = ModelConfig(num_features=8, num_classes=2)
    task = get_task("logreg", cfg)
    reg = SnapshotRegistry()
    reg.publish(np.full(task.num_params, 0.5, np.float32), vector_clock=1)
    eng = PredictionEngine(task, reg, **kw)
    eng.warmup()
    return eng

auto_eng = _engine()                               # adaptive (default)
plain_eng = _engine(max_batch=1, deadline_s=0.0)   # hand-tuned unbatched
try:
    auto_res = loadgen.run_closed_loop(loadgen.EngineTarget(auto_eng), 8,
                                       concurrency=1, duration_s=2.0)
    auto_stats = auto_eng.stats()
    plain_res = loadgen.run_closed_loop(loadgen.EngineTarget(plain_eng), 8,
                                        concurrency=1, duration_s=2.0)
finally:
    auto_eng.close()
    plain_eng.close()
assert auto_stats["mode"] == "bypass", \
    f"auto engine never settled on bypass at conc 1: {auto_stats}"
assert auto_stats["bypasses"] > 0, auto_stats
assert auto_res.errors == auto_res.shed == 0, auto_res.as_dict()
# the whole point of adaptive dispatch: an idle-occupancy caller must
# not pay the micro-batching tax.  Same box, same inline path length —
# 1.5x multiplicative + 0.3 ms additive slack absorbs scheduler noise.
assert auto_res.p99_ms <= 1.5 * plain_res.p99_ms + 0.3, (
    f"bypass p99 {auto_res.p99_ms:.3f} ms worse than unbatched "
    f"{plain_res.p99_ms:.3f} ms")

print(f"LOAD_SMOKE_OK low_p99_ms={low.p99_ms} low_ok={low.ok} "
      f"sheds={over.shed} shed_rate={over.shed_rate:.3f} "
      f"poisson_p99_ms={pois.p99_ms} poisson_ok={pois.ok} "
      f"poisson_shed={pois.shed} "
      f"bypass_p99_ms={auto_res.p99_ms:.3f} "
      f"unbatched_p99_ms={plain_res.p99_ms:.3f} "
      f"dispatch_mode={auto_stats['mode']} "
      f"theta=bitwise-identical iters={MAX_IT}")
EOF
    exit $?
fi

if [[ "${1:-}" == "--shard" ]]; then
    timeout -k 10 540 env JAX_PLATFORMS=cpu python - <<'EOF'
import glob
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

import numpy as np

# a real split-deployment fleet: 2 shard-server subprocesses + 1 worker
# subprocess hosting 2 logical workers, driven through the public CLI
root = tempfile.mkdtemp(prefix="kps-shard-")
repo = os.getcwd()
rng = np.random.default_rng(0)
x = rng.normal(size=(256, 8)).astype(np.float32)
y = (x[:, 0] > 0).astype(np.int32) + 1
train, test = os.path.join(root, "train.csv"), os.path.join(root, "test.csv")
for path, (xx, yy) in ((train, (x[:200], y[:200])),
                       (test, (x[200:], y[200:]))):
    with open(path, "w") as fh:
        fh.write(",".join(f"f{i}" for i in range(8)) + ",Score\n")
        for r, lab in zip(xx, yy):
            fh.write(",".join(f"{v:.6f}" for v in r) + f",{lab}\n")

def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port

p0, p1 = free_port(), free_port()
env = dict(os.environ, JAX_PLATFORMS="cpu",
           PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
MAX_IT = 400
common = ["--num_workers", "2", "--num_features", "8",
          "--num_classes", "2", "--max_iterations", str(MAX_IT)]
logdir, ckpt = os.path.join(root, "log"), os.path.join(root, "ckpt.npz")

def shard(i, port):
    return subprocess.Popen(
        [sys.executable, "-m", "kafka_ps_tpu.cli.server_runner",
         "--listen", str(port), "--shards", "2", "--shard-id", str(i),
         "-training", train, "-test", test, "-p", "5", "-c", "0",
         "--durable-log", logdir, "--checkpoint", ckpt,
         "--checkpoint_every", "50", *common],
        env=env, cwd=root, stderr=subprocess.PIPE,
        stdout=subprocess.DEVNULL, text=True)

s0, s1 = shard(0, p0), shard(1, p1)
w = subprocess.Popen(
    [sys.executable, "-m", "kafka_ps_tpu.cli.worker_runner",
     "--connect", f"127.0.0.1:{p0},127.0.0.1:{p1}",
     "--worker_ids", "0,1", "-test", test,
     "-min", "8", "-max", "32", *common],
    env=env, cwd=root, stderr=subprocess.PIPE,
    stdout=subprocess.DEVNULL, text=True)

# wait until shard 1 has logged a prefix of gradient slices, then
# SIGKILL it mid-run
grad_glob = os.path.join(logdir, "shard1of2", "gradients", "*", "*.log")
deadline = time.monotonic() + 120
while time.monotonic() < deadline:
    segs = glob.glob(grad_glob)
    if segs and sum(os.path.getsize(s) for s in segs) > 8000:
        break
    if s1.poll() is not None:
        print(s1.stderr.read(), file=sys.stderr)
        raise SystemExit("shard1 exited before the kill point")
    time.sleep(0.1)
else:
    raise SystemExit("shard1 gradient log never grew")
os.kill(s1.pid, signal.SIGKILL)
s1.wait()
time.sleep(0.5)
s1b = shard(1, p1)       # workers + shard0 kept running throughout

procs = {"shard0": s0, "shard1-restarted": s1b, "worker": w}
deadline = time.monotonic() + 300
while time.monotonic() < deadline:
    if all(p.poll() is not None for p in procs.values()):
        break
    time.sleep(0.5)
else:
    for p in procs.values():
        if p.poll() is None:
            p.kill()
    for name, p in procs.items():
        print(f"== {name} rc={p.poll()}\n{p.stderr.read()[-4000:]}",
              file=sys.stderr)
    raise SystemExit("fleet did not finish in time")
bad = []
for name, p in procs.items():
    err = p.stderr.read()
    if p.returncode != 0:
        print(f"== {name} rc={p.returncode}\n{err[-4000:]}",
              file=sys.stderr)
        bad.append(name)
assert not bad, f"{bad} failed"

# bitwise proof: replay each shard's FULL gradients partition (offset 0
# up to the final checkpoint's committed offset) through a fresh
# ServerNode — log order is processing order across both incarnations,
# and the tracker dedups redelivered slices identically — then compare
# against the shard's final checkpoint theta bytes.
from kafka_ps_tpu.log import LogConfig
from kafka_ps_tpu.log.manager import LogManager
from kafka_ps_tpu.models.task import get_task
from kafka_ps_tpu.runtime import fabric as fabric_mod
from kafka_ps_tpu.runtime import serde
from kafka_ps_tpu.runtime.server import ServerNode
from kafka_ps_tpu.runtime.sharding import ShardPlan
from kafka_ps_tpu.utils.config import (BufferConfig, ModelConfig, PSConfig,
                                       StreamConfig)

cfg = PSConfig(num_workers=2, consistency_model=0, task="logreg",
               model=ModelConfig(num_features=8, num_classes=2),
               buffer=BufferConfig(min_size=8, max_size=32),
               stream=StreamConfig(time_per_event_ms=5),
               use_gang=False)
plan = ShardPlan(get_task(cfg.task, cfg.model).num_params, 2)
replayed = []
for i in range(2):
    z = np.load(os.path.join(root, f"ckpt.npz.shard{i}of2.npz"))
    end = json.loads(str(z["log_offsets"]))["gradients/0"]
    srv = ServerNode(cfg, fabric_mod.Fabric(), None, None, None,
                     key_range=plan.ranges[i], shard_id=i, num_shards=2)
    srv.start_training_loop()
    mgr = LogManager(os.path.join(logdir, f"shard{i}of2"), LogConfig())
    n = 0
    for off, payload in mgr.get("gradients", 0).read_from(0):
        if off >= end:
            break
        srv.process(serde.from_bytes(payload))
        n += 1
    mgr.close()
    replay = np.asarray(srv.theta, dtype=np.float32)
    want = np.asarray(z["theta"], dtype=np.float32)
    assert srv.iterations >= MAX_IT, (i, srv.iterations)
    assert replay.tobytes() == want.tobytes(), \
        f"shard {i}: replayed theta diverged from final checkpoint"
    replayed.append(n)
print(f"SHARD_SMOKE_OK shards=2 replayed={replayed} "
      f"iters={MAX_IT} bitwise=recovered")
EOF
    exit $?
fi

if [[ "${1:-}" == "--agg" ]]; then
    timeout -k 10 540 env JAX_PLATFORMS=cpu python - <<'EOF'
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

import numpy as np

# the aggregation-tier A/B (docs/AGGREGATION.md): the SAME training
# run through two topologies —
#   direct:      server <-- 2 worker processes (4 logical workers)
#   aggregated:  server <-- 2 relay processes <-- 2 worker processes
# with deterministic knobs (--bsp-order on the server so BSP rounds
# apply in worker-id order; --ready-rows so training starts only after
# each worker ingested its FULL stream partition), final theta and the
# server eval CSV must match bitwise.  One relay is SIGKILL'd mid-run
# and restarted: the workers' redelivery caches resend through it and
# the server gate deduplicates, so the kill must not show up in either
# artifact.
root = tempfile.mkdtemp(prefix="kps-agg-")
repo = os.getcwd()
rng = np.random.default_rng(0)
x = rng.normal(size=(192, 8)).astype(np.float32)
y = (x[:, 0] > 0).astype(np.int32) + 1
train, test = os.path.join(root, "train.csv"), os.path.join(root, "test.csv")
for path, (xx, yy) in ((train, (x[:128], y[:128])),
                       (test, (x[128:], y[128:]))):
    with open(path, "w") as fh:
        fh.write(",".join(f"f{i}" for i in range(8)) + ",Score\n")
        for r, lab in zip(xx, yy):
            fh.write(",".join(f"{v:.6f}" for v in r) + f",{lab}\n")

def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port

env = dict(os.environ, JAX_PLATFORMS="cpu",
           PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
# 2000 rounds keep the training window open for seconds (the eval CSV
# is drained asynchronously, so a 200-round run can be over before any
# on-disk row count triggers the mid-run kill — the restarted relay
# then dials a server that already exited)
MAX_IT = 2000
# 128 rows / 4 workers = 32 per partition = the buffer cap, so
# --ready-rows 32 means "my whole partition arrived" — ingestion fully
# precedes training in both arms, which removes stream timing from the
# comparison
READY = 32
common = ["--num_workers", "4", "--num_features", "8",
          "--num_classes", "2", "--max_iterations", str(MAX_IT)]

def server_proc(tag, port):
    cwd = os.path.join(root, tag)
    os.makedirs(cwd, exist_ok=True)
    p = subprocess.Popen(
        [sys.executable, "-m", "kafka_ps_tpu.cli.server_runner",
         "--listen", str(port), "--bsp-order", "-c", "0",
         "-training", train, "-test", test, "-p", "1", "--logging",
         "--checkpoint", os.path.join(cwd, "ckpt.npz"), *common],
        env=env, cwd=cwd, stderr=subprocess.PIPE,
        stdout=subprocess.DEVNULL, text=True)
    return p, cwd

def worker_proc(cwd, wids, flag, addr):
    return subprocess.Popen(
        [sys.executable, "-m", "kafka_ps_tpu.cli.worker_runner",
         flag, addr, "--worker_ids", wids, "-test", test,
         "-min", "8", "-max", "32", "--ready-rows", str(READY),
         *common],
        env=env, cwd=cwd, stderr=subprocess.PIPE,
        stdout=subprocess.DEVNULL, text=True)

def agg_proc(cwd, agg_id, wids, sport, aport):
    return subprocess.Popen(
        [sys.executable, "-m", "kafka_ps_tpu.cli.agg_runner",
         "--connect", f"127.0.0.1:{sport}", "--listen", str(aport),
         "--agg-id", str(agg_id), "--worker_ids", wids, *common],
        env=env, cwd=cwd, stderr=subprocess.PIPE,
        stdout=subprocess.DEVNULL, text=True)

def finish(procs, deadline_s=240):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if all(p.poll() is not None for p in procs.values()):
            break
        time.sleep(0.25)
    else:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        for name, p in procs.items():
            print(f"== {name} rc={p.poll()}\n{p.stderr.read()[-4000:]}",
                  file=sys.stderr)
        raise SystemExit("fleet did not finish in time")
    bad = []
    for name, p in procs.items():
        err = p.stderr.read()
        if p.returncode != 0:
            print(f"== {name} rc={p.returncode}\n{err[-4000:]}",
                  file=sys.stderr)
            bad.append(name)
    assert not bad, f"{bad} failed"

def csv_rows(cwd):
    # column 0 is the wall-clock timestamp — the only legal difference
    with open(os.path.join(cwd, "logs-server.csv")) as fh:
        return [";".join(ln.split(";")[1:]) for ln in fh.read().splitlines()]

# -- arm 1: direct (no relays) --------------------------------------------
pd = free_port()
sd, dcwd = server_proc("direct", pd)
finish({"server": sd,
        "worker01": worker_proc(dcwd, "0,1", "--connect",
                                f"127.0.0.1:{pd}"),
        "worker23": worker_proc(dcwd, "2,3", "--connect",
                                f"127.0.0.1:{pd}")})

# -- arm 2: aggregated, with a relay SIGKILL + restart mid-run ------------
pa, a0, a1 = free_port(), free_port(), free_port()
sa, acwd = server_proc("agg", pa)
r0 = agg_proc(acwd, 0, "0,1", pa, a0)
r1 = agg_proc(acwd, 1, "2,3", pa, a1)
w01 = worker_proc(acwd, "0,1", "--aggregate", f"127.0.0.1:{a0}")
w23 = worker_proc(acwd, "2,3", "--aggregate", f"127.0.0.1:{a1}")

# kill relay 0 once the server's eval CSV shows real training progress
csv_path = os.path.join(acwd, "logs-server.csv")
deadline = time.monotonic() + 120
while time.monotonic() < deadline:
    try:
        with open(csv_path) as fh:
            n = sum(1 for _ in fh) - 1
    except OSError:
        n = 0
    if n >= 16:
        break
    for name, p in (("server", sa), ("relay0", r0)):
        if p.poll() is not None:
            print(p.stderr.read(), file=sys.stderr)
            raise SystemExit(f"{name} exited before the kill point")
    time.sleep(0.05)
else:
    raise SystemExit("aggregated server never made progress")
os.kill(r0.pid, signal.SIGKILL)
r0.wait()
time.sleep(0.5)
# same listen port: the members' supervisor reconnects there and
# resends the whole redelivery cache (the relay itself held no state)
r0b = agg_proc(acwd, 0, "0,1", pa, a0)
finish({"server": sa, "relay0-restarted": r0b, "relay1": r1,
        "worker01": w01, "worker23": w23})

# -- the bitwise pin -------------------------------------------------------
zd = np.load(os.path.join(dcwd, "ckpt.npz"))
za = np.load(os.path.join(acwd, "ckpt.npz"))
assert int(zd["iterations"]) >= MAX_IT <= int(za["iterations"])
assert za["theta"].tobytes() == zd["theta"].tobytes(), \
    "aggregated theta diverged from the direct run"
assert csv_rows(acwd) == csv_rows(dcwd) != [], \
    "aggregated eval CSV diverged from the direct run"
print(f"AGG_SMOKE_OK relays=2 workers=4 iters={MAX_IT} "
      f"kill=relay0+restart theta=bitwise csv=bitwise")
EOF
    exit $?
fi

if [[ "${1:-}" == "--wire" ]]; then
    timeout -k 10 540 env JAX_PLATFORMS=cpu python - <<'EOF'
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

import numpy as np

# the wire-engine A/B (docs/WIRE.md): the SAME training run — server
# <-- 1 relay <-- 2 member worker processes (4 logical workers) — once
# with frame coalescing on (the default) and once with
# --no-wire-coalesce, deterministic knobs as in the --agg leg
# (--bsp-order, --ready-rows = full partition).  In EACH arm one member
# worker process is SIGKILL'd mid-run and restarted: its durable state
# (--checkpoint/--state_every, cli/socket_mode._run_worker_sharded)
# restores the frozen ingestion window, the relay redelivers its
# stashed weights on the re-HELLO, and the server's READY liveness
# reissue re-sends the in-flight round assignment — so the stalled BSP
# gate completes with a bit-identical applied-gradient sequence no
# matter when the kill landed.  Final theta and the server eval CSV
# must match bitwise across the coalescing lever.
root = tempfile.mkdtemp(prefix="kps-wire-")
repo = os.getcwd()
rng = np.random.default_rng(0)
x = rng.normal(size=(192, 8)).astype(np.float32)
y = (x[:, 0] > 0).astype(np.int32) + 1
train, test = os.path.join(root, "train.csv"), os.path.join(root, "test.csv")
for path, (xx, yy) in ((train, (x[:128], y[:128])),
                       (test, (x[128:], y[128:]))):
    with open(path, "w") as fh:
        fh.write(",".join(f"f{i}" for i in range(8)) + ",Score\n")
        for r, lab in zip(xx, yy):
            fh.write(",".join(f"{v:.6f}" for v in r) + f",{lab}\n")

def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port

env = dict(os.environ, JAX_PLATFORMS="cpu",
           PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
# 2000 rounds keep the training window open for seconds (the eval CSV
# is drained asynchronously, so a 200-round run is over before any
# on-disk row count can trigger a mid-run kill)
MAX_IT = 2000
READY = 32          # 128 rows / 4 workers: full-partition gating
common = ["--num_workers", "4", "--num_features", "8",
          "--num_classes", "2", "--max_iterations", str(MAX_IT)]

def server_proc(cwd, port, wire):
    return subprocess.Popen(
        [sys.executable, "-m", "kafka_ps_tpu.cli.server_runner",
         "--listen", str(port), "--bsp-order", "-c", "0",
         "-training", train, "-test", test, "-p", "1", "--logging",
         "--checkpoint", os.path.join(cwd, "ckpt.npz"), wire, *common],
        env=env, cwd=cwd, stderr=subprocess.PIPE,
        stdout=subprocess.DEVNULL, text=True)

def worker_proc(cwd, wids, aport, wire):
    return subprocess.Popen(
        [sys.executable, "-m", "kafka_ps_tpu.cli.worker_runner",
         "--aggregate", f"127.0.0.1:{aport}", "--worker_ids", wids,
         "-test", test, "-min", "8", "-max", "32",
         "--ready-rows", str(READY),
         "--checkpoint", os.path.join(cwd, "job.npz"),
         "--state_every", "0.2", wire, *common],
        env=env, cwd=cwd, stderr=subprocess.PIPE,
        stdout=subprocess.DEVNULL, text=True)

def agg_proc(cwd, sport, aport, wire):
    return subprocess.Popen(
        [sys.executable, "-m", "kafka_ps_tpu.cli.agg_runner",
         "--connect", f"127.0.0.1:{sport}", "--listen", str(aport),
         "--agg-id", "0", "--worker_ids", "0,1,2,3", wire, *common],
        env=env, cwd=cwd, stderr=subprocess.PIPE,
        stdout=subprocess.DEVNULL, text=True)

def finish(procs, deadline_s=240):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if all(p.poll() is not None for p in procs.values()):
            break
        time.sleep(0.25)
    else:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        for name, p in procs.items():
            print(f"== {name} rc={p.poll()}\n{p.stderr.read()[-4000:]}",
                  file=sys.stderr)
        raise SystemExit("fleet did not finish in time")
    bad = []
    for name, p in procs.items():
        err = p.stderr.read()
        if p.returncode != 0:
            print(f"== {name} rc={p.returncode}\n{err[-4000:]}",
                  file=sys.stderr)
            bad.append(name)
    assert not bad, f"{bad} failed"

def csv_rows(cwd):
    # column 0 is the wall-clock timestamp — the only legal difference
    with open(os.path.join(cwd, "logs-server.csv")) as fh:
        return [";".join(ln.split(";")[1:]) for ln in fh.read().splitlines()]

def run_arm(tag, wire):
    cwd = os.path.join(root, tag)
    os.makedirs(cwd, exist_ok=True)
    sport, aport = free_port(), free_port()
    sp = server_proc(cwd, sport, wire)
    rp = agg_proc(cwd, sport, aport, wire)
    w01 = worker_proc(cwd, "0,1", aport, wire)
    w23 = worker_proc(cwd, "2,3", aport, wire)
    # SIGKILL member process 2,3 once the server shows real progress
    csv_path = os.path.join(cwd, "logs-server.csv")
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            with open(csv_path) as fh:
                n = sum(1 for _ in fh) - 1
        except OSError:
            n = 0
        if n >= 16:
            break
        for name, p in (("server", sp), ("relay", rp), ("w23", w23)):
            if p.poll() is not None:
                print(p.stderr.read(), file=sys.stderr)
                raise SystemExit(f"{tag}: {name} exited before the kill")
        time.sleep(0.05)
    else:
        raise SystemExit(f"{tag}: server never made progress")
    os.kill(w23.pid, signal.SIGKILL)
    w23.wait()
    time.sleep(0.5)
    # restart: durable state restores the 32-row windows, READY fires
    # immediately, the stalled round completes
    w23b = worker_proc(cwd, "2,3", aport, wire)
    finish({"server": sp, "relay": rp, "worker01": w01,
            "worker23-restarted": w23b})
    return cwd

cwd_on = run_arm("coalesce-on", "--wire-coalesce")
cwd_off = run_arm("coalesce-off", "--no-wire-coalesce")

zon = np.load(os.path.join(cwd_on, "ckpt.npz"))
zoff = np.load(os.path.join(cwd_off, "ckpt.npz"))
assert int(zon["iterations"]) >= MAX_IT <= int(zoff["iterations"])
assert zon["theta"].tobytes() == zoff["theta"].tobytes(), \
    "coalesced theta diverged from the --no-wire-coalesce arm"
assert csv_rows(cwd_on) == csv_rows(cwd_off) != [], \
    "coalesced eval CSV diverged from the --no-wire-coalesce arm"
print(f"WIRE_SMOKE_OK workers=4 relay=1 iters={MAX_IT} "
      f"kill=worker23+restart theta=bitwise csv=bitwise")
EOF
    exit $?
fi

if [[ "${1:-}" == "--eval" ]]; then
    timeout -k 10 540 env JAX_PLATFORMS=cpu python - <<'EOF'
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

import numpy as np

# the async-eval A/B (docs/EVALUATION.md "Async evaluation"): the SAME
# training run — server <-- 1 relay <-- 2 member worker processes (4
# logical workers), eval_every=1, deterministic knobs as in the --wire
# leg (--bsp-order, --ready-rows = full partition) — once with the
# async coalescing eval engine (the default) and once with
# --no-eval-async (the fused _apply_full_eval programs).  In EACH arm
# one member worker process is SIGKILL'd mid-run and restarted: the
# engine holds no durable state (pending (theta, clock) snapshots die
# with the process and the worker-state + relay-stash + READY-reissue
# machinery re-derives the applied sequence), so final theta AND the
# server eval CSV must match bitwise across the eval lever no matter
# when the kill landed.
root = tempfile.mkdtemp(prefix="kps-eval-")
repo = os.getcwd()
rng = np.random.default_rng(0)
x = rng.normal(size=(192, 8)).astype(np.float32)
y = (x[:, 0] > 0).astype(np.int32) + 1
train, test = os.path.join(root, "train.csv"), os.path.join(root, "test.csv")
for path, (xx, yy) in ((train, (x[:128], y[:128])),
                       (test, (x[128:], y[128:]))):
    with open(path, "w") as fh:
        fh.write(",".join(f"f{i}" for i in range(8)) + ",Score\n")
        for r, lab in zip(xx, yy):
            fh.write(",".join(f"{v:.6f}" for v in r) + f",{lab}\n")

def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port

env = dict(os.environ, JAX_PLATFORMS="cpu",
           PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
# 2000 rounds keep the training window open for seconds so the mid-run
# kill lands while the gate is still cycling (the eval CSV is drained
# asynchronously in BOTH arms — the on-disk row count lags the clock)
MAX_IT = 2000
READY = 32          # 128 rows / 4 workers: full-partition gating
common = ["--num_workers", "4", "--num_features", "8",
          "--num_classes", "2", "--max_iterations", str(MAX_IT),
          "--eval_every", "1"]

def server_proc(cwd, port, evalflag):
    return subprocess.Popen(
        [sys.executable, "-m", "kafka_ps_tpu.cli.server_runner",
         "--listen", str(port), "--bsp-order", "-c", "0",
         "-training", train, "-test", test, "-p", "1", "--logging",
         "--checkpoint", os.path.join(cwd, "ckpt.npz"),
         *evalflag, *common],
        env=env, cwd=cwd, stderr=subprocess.PIPE,
        stdout=subprocess.DEVNULL, text=True)

def worker_proc(cwd, wids, aport):
    return subprocess.Popen(
        [sys.executable, "-m", "kafka_ps_tpu.cli.worker_runner",
         "--aggregate", f"127.0.0.1:{aport}", "--worker_ids", wids,
         "-test", test, "-min", "8", "-max", "32",
         "--ready-rows", str(READY),
         "--checkpoint", os.path.join(cwd, "job.npz"),
         "--state_every", "0.2", *common],
        env=env, cwd=cwd, stderr=subprocess.PIPE,
        stdout=subprocess.DEVNULL, text=True)

def agg_proc(cwd, sport, aport):
    return subprocess.Popen(
        [sys.executable, "-m", "kafka_ps_tpu.cli.agg_runner",
         "--connect", f"127.0.0.1:{sport}", "--listen", str(aport),
         "--agg-id", "0", "--worker_ids", "0,1,2,3", *common],
        env=env, cwd=cwd, stderr=subprocess.PIPE,
        stdout=subprocess.DEVNULL, text=True)

def finish(procs, deadline_s=240):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if all(p.poll() is not None for p in procs.values()):
            break
        time.sleep(0.25)
    else:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        for name, p in procs.items():
            print(f"== {name} rc={p.poll()}\n{p.stderr.read()[-4000:]}",
                  file=sys.stderr)
        raise SystemExit("fleet did not finish in time")
    bad = []
    for name, p in procs.items():
        err = p.stderr.read()
        if p.returncode != 0:
            print(f"== {name} rc={p.returncode}\n{err[-4000:]}",
                  file=sys.stderr)
            bad.append(name)
    assert not bad, f"{bad} failed"

def csv_rows(cwd):
    # column 0 is the wall-clock timestamp — the only legal difference
    with open(os.path.join(cwd, "logs-server.csv")) as fh:
        return [";".join(ln.split(";")[1:]) for ln in fh.read().splitlines()]

def run_arm(tag, evalflag):
    cwd = os.path.join(root, tag)
    os.makedirs(cwd, exist_ok=True)
    sport, aport = free_port(), free_port()
    sp = server_proc(cwd, sport, evalflag)
    rp = agg_proc(cwd, sport, aport)
    w01 = worker_proc(cwd, "0,1", aport)
    w23 = worker_proc(cwd, "2,3", aport)
    # SIGKILL member process 2,3 once the server shows real progress
    csv_path = os.path.join(cwd, "logs-server.csv")
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            with open(csv_path) as fh:
                n = sum(1 for _ in fh) - 1
        except OSError:
            n = 0
        if n >= 16:
            break
        for name, p in (("server", sp), ("relay", rp), ("w23", w23)):
            if p.poll() is not None:
                print(p.stderr.read(), file=sys.stderr)
                raise SystemExit(f"{tag}: {name} exited before the kill")
        time.sleep(0.05)
    else:
        raise SystemExit(f"{tag}: server never made progress")
    os.kill(w23.pid, signal.SIGKILL)
    w23.wait()
    time.sleep(0.5)
    # restart: durable state restores the 32-row windows, READY fires
    # immediately, the stalled round completes; any evals the async
    # engine still held at kill time were never durable — the engine
    # re-derives them from the re-applied clock sequence
    w23b = worker_proc(cwd, "2,3", aport)
    finish({"server": sp, "relay": rp, "worker01": w01,
            "worker23-restarted": w23b})
    return cwd

cwd_async = run_arm("eval-async", [])
cwd_fused = run_arm("eval-fused", ["--no-eval-async"])

za = np.load(os.path.join(cwd_async, "ckpt.npz"))
zf = np.load(os.path.join(cwd_fused, "ckpt.npz"))
assert int(za["iterations"]) >= MAX_IT <= int(zf["iterations"])
assert za["theta"].tobytes() == zf["theta"].tobytes(), \
    "async-eval theta diverged from the --no-eval-async arm"
assert csv_rows(cwd_async) == csv_rows(cwd_fused) != [], \
    "async-eval CSV diverged from the --no-eval-async arm"
print(f"EVAL_SMOKE_OK workers=4 relay=1 iters={MAX_IT} eval_every=1 "
      f"kill=worker23+restart theta=bitwise csv=bitwise")
EOF
    exit $?
fi

if [[ "${1:-}" == "--obs" ]]; then
    timeout -k 10 540 env JAX_PLATFORMS=cpu python - <<'EOF'
import re
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

from kafka_ps_tpu.data.buffer import SlidingBuffer
from kafka_ps_tpu.data.synth import generate_hard
from kafka_ps_tpu.runtime import fabric as fabric_mod, net
from kafka_ps_tpu.runtime.server import ServerNode
from kafka_ps_tpu.runtime.worker import WorkerNode
from kafka_ps_tpu.telemetry import Telemetry
from kafka_ps_tpu.telemetry.merge import merge_traces
from kafka_ps_tpu.utils.config import BufferConfig, ModelConfig, PSConfig
from kafka_ps_tpu.utils.csvlog import NullLogSink
from kafka_ps_tpu.utils.trace import Tracer

model = ModelConfig(num_features=64, num_classes=2)
x, y = generate_hard(512 + 500, num_features=64, num_classes=2, seed=9)
test_x, test_y = x[-500:], y[-500:]
# three workers, one straggler: worker 2 lags STRAGGLER_LAG_S before
# each local step.  Under BSP the gate then withholds the round's
# weights from BOTH fast workers until the straggler reports, so
# gate_wait accrues 2x the lag per round while buffer_wait (charged to
# the straggler's own flows) accrues 1x — the decomposition must
# convict the gate, not the wire, and with a 2x margin it does so
# robustly.  This is the scenario critical-path analysis exists for.
ids = [0, 1, 2]
STRAGGLER, STRAGGLER_LAG_S = 2, 0.012
out = Path(tempfile.mkdtemp(prefix="kps-obs-"))


def run_traced(c, pid_s, pid_w):
    """One short socket-bridged run under consistency model `c`; two
    tracers with distinct pids stand in for the two PROCESSES the
    socket deployment runs (`--listen --trace` / `--connect --trace`).
    Returns the worker/server trace paths and the server telemetry."""
    cfg = PSConfig(num_workers=3, consistency_model=c, model=model,
                   buffer=BufferConfig(min_size=32, max_size=256),
                   eval_every=10**9, use_gang=False)
    tr_s, tr_w = Tracer(pid=pid_s), Tracer(pid=pid_w)
    tel_s, tel_w = Telemetry(tracer=tr_s), Telemetry(tracer=tr_w)
    sbridge = net.ServerBridge(port=0, run_id=1, tracer=tr_s,
                               telemetry=tel_s)
    sfabric = sbridge.wrap(fabric_mod.Fabric())
    server = ServerNode(cfg, sfabric, test_x, test_y, NullLogSink(),
                        tracer=tr_s, telemetry=tel_s)
    wbridge = net.WorkerBridge("127.0.0.1", sbridge.port, ids,
                               tracer=tr_w, telemetry=tel_w)
    assert wbridge.trace_negotiated, "trace context did not negotiate on"
    wfabric = wbridge.make_fabric()
    buffers = {w: SlidingBuffer(64, cfg.buffer, telemetry=tel_w, worker=w)
               for w in ids}
    nodes = {w: WorkerNode(w, cfg, wfabric, buffers[w], test_x, test_y,
                           NullLogSink(), tracer=tr_w, telemetry=tel_w)
             for w in ids}
    for w in ids:
        for i in range(w, 512, len(ids)):
            buffers[w].add(dict(enumerate(x[i])), int(y[i]))
    reader = threading.Thread(target=wbridge.run_reader, args=(buffers,),
                              daemon=True)
    reader.start()
    for w in ids:
        wbridge.mark_ready(w)
    sbridge.wait_for_connected(ids, timeout=30)
    sbridge.wait_for_workers(ids, timeout=30)
    stop = threading.Event()

    def worker_loop(node, lag_s):
        try:
            while not stop.is_set():
                m = wfabric.poll_blocking(fabric_mod.WEIGHTS_TOPIC,
                                          node.worker_id, timeout=0.05)
                if m is not None:
                    if lag_s:
                        time.sleep(lag_s)   # the straggler's lag
                    node.on_weights(m)
        except (ConnectionError, OSError):
            pass
    ts = [threading.Thread(
              target=worker_loop,
              args=(nodes[w],
                    STRAGGLER_LAG_S if w == STRAGGLER else 0.0),
              daemon=True) for w in ids]
    for t in ts:
        t.start()
    server.start_training_loop()
    # warmup: run until the jit compiles (worker local_update, server
    # apply) have all fired, then clear both tracers — the critical
    # path must reflect steady state, not one-time compilation stalls
    while server.iterations < 8:
        g = sfabric.poll_blocking(fabric_mod.GRADIENTS_TOPIC, 0,
                                  timeout=0.2)
        if g is not None:
            server.process(g)
    tr_s.clear()
    tr_w.clear()
    while server.iterations < 32:
        g = sfabric.poll_blocking(fabric_mod.GRADIENTS_TOPIC, 0,
                                  timeout=0.2)
        if g is not None:
            server.process(g)
    stop.set()
    sbridge.close()
    for t in ts:
        t.join(timeout=120)
    wbridge.close()
    reader.join(timeout=10)
    server.log.close()
    pw = str(out / f"worker.{pid_w}.trace.json")
    ps = str(out / f"server.{pid_s}.trace.json")
    tr_w.dump(pw)
    tr_s.dump(ps)
    return pw, ps, tel_s


# one run per consistency model, distinct pid pairs, so all six traces
# merge onto ONE timeline and the critical-path CLI sees every model
runs = {0: run_traced(0, 1001, 2002),
        2: run_traced(2, 1003, 2004),
        -1: run_traced(-1, 1005, 2006)}
traces = [p for pw, ps, _ in runs.values() for p in (pw, ps)]
stats = merge_traces(traces, str(out / "merged.json"))
assert stats["cross_process_flows"] >= 1, stats
assert sorted(stats["pids"]) == [1001, 1003, 1005,
                                 2002, 2004, 2006], stats

tel_s = runs[2][2]
metrics = str(out / "metrics.prom")
tel_s.write_prometheus(metrics)
text = Path(metrics).read_text()
for line in text.splitlines():          # every sample line must parse
    if line and not line.startswith("#"):
        float(line.rsplit(" ", 1)[1])
for family in ("gate_wait_ms_bucket", "clock_lag_bucket",
               "gradients_applied_total", "frames_received"):
    assert family in text, f"{family} missing from metrics dump"
assert 'model="bounded"' in text, "staleness histograms unlabeled"
snap = tel_s.snapshot()
assert snap["gate_wait_ms"]["model=bounded"]["count"] > 0, snap
print(f"OBS_SMOKE_OK flows={stats['cross_process_flows']} "
      f"events={stats['events']} pids={sorted(stats['pids'])} "
      f"metric_families={len(snap)}")

# ---- critical-path decomposition over the merged trace ---------------
# the CLI must exit 0, decompose flows for EVERY consistency model, and
# convict gate_wait as BSP's dominant segment (the sequential gate
# holds weights until the whole round arrives — that wait IS the
# model's defining cost, docs/OBSERVABILITY.md "Critical-path analysis")
cp = subprocess.run(
    [sys.executable, "-m", "kafka_ps_tpu.telemetry", "critpath",
     str(out / "merged.json")], capture_output=True, text=True,
    timeout=120)
assert cp.returncode == 0, (
    f"critpath rc={cp.returncode}\n{cp.stdout}{cp.stderr}")
doms = dict(re.findall(r"^model=(\S+) flows=\d+ dominant=(\S+)",
                       cp.stdout, re.M))
for m in ("sequential", "bounded", "eventual"):
    assert m in doms, (doms, cp.stdout)
assert doms["sequential"] == "gate_wait", (doms, cp.stdout)
print(f"OBS_CRITPATH_OK dominants=" + ",".join(
    f"{m}:{d}" for m, d in sorted(doms.items())))

# ---- phase 2: black-box postmortem of a SIGKILLed shard --------------
# A real split-deployment fleet (2 shard servers + 1 worker process, the
# --shard leg's topology) runs with --flight-dir; shard 1 is SIGKILLed
# mid-run — it writes NO dump, and that absence is the finding.  The
# survivors' death-hook/shutdown dumps are merged by the postmortem CLI,
# which must name the dead shard and its last acknowledged weights send.
import glob
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np

proot = tempfile.mkdtemp(prefix="kps-postmortem-")
flight = os.path.join(proot, "flight")
repo = os.getcwd()
prng = np.random.default_rng(0)
px = prng.normal(size=(256, 8)).astype(np.float32)
py = (px[:, 0] > 0).astype(np.int32) + 1
ptrain = os.path.join(proot, "train.csv")
ptest = os.path.join(proot, "test.csv")
for path, (xx, yy) in ((ptrain, (px[:200], py[:200])),
                       (ptest, (px[200:], py[200:]))):
    with open(path, "w") as fh:
        fh.write(",".join(f"f{i}" for i in range(8)) + ",Score\n")
        for r, lab in zip(xx, yy):
            fh.write(",".join(f"{v:.6f}" for v in r) + f",{lab}\n")

def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port

p0, p1 = free_port(), free_port()
penv = dict(os.environ, JAX_PLATFORMS="cpu",
            PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
# the fleet is killed mid-run; MAX_IT only has to outlast the kill point
MAX_IT = 5000
pcommon = ["--num_workers", "2", "--num_features", "8",
           "--num_classes", "2", "--max_iterations", str(MAX_IT),
           "--flight-dir", flight]
logdir = os.path.join(proot, "log")

def pshard(i, port):
    return subprocess.Popen(
        [sys.executable, "-m", "kafka_ps_tpu.cli.server_runner",
         "--listen", str(port), "--shards", "2", "--shard-id", str(i),
         "-training", ptrain, "-test", ptest, "-p", "5", "-c", "0",
         "--durable-log", logdir,
         "--checkpoint", os.path.join(proot, "ckpt.npz"),
         "--checkpoint_every", "50", *pcommon],
        env=penv, cwd=proot, stderr=subprocess.PIPE,
        stdout=subprocess.DEVNULL, text=True)

s0, s1 = pshard(0, p0), pshard(1, p1)
w = subprocess.Popen(
    [sys.executable, "-m", "kafka_ps_tpu.cli.worker_runner",
     "--connect", f"127.0.0.1:{p0},127.0.0.1:{p1}",
     "--worker_ids", "0,1", "-test", ptest,
     "-min", "8", "-max", "32", *pcommon],
    env=penv, cwd=proot, stderr=subprocess.PIPE,
    stdout=subprocess.DEVNULL, text=True)

# wait until shard 1 has served real traffic (its gradient log has a
# prefix of slices — so every surviving ring holds shard-1 evidence),
# then SIGKILL it: no handler runs, no dump is written
grad_glob = os.path.join(logdir, "shard1of2", "gradients", "*", "*.log")
deadline = time.monotonic() + 120
while time.monotonic() < deadline:
    segs = glob.glob(grad_glob)
    if segs and sum(os.path.getsize(s) for s in segs) > 8000:
        break
    if s1.poll() is not None:
        print(s1.stderr.read(), file=sys.stderr)
        raise SystemExit("shard1 exited before the kill point")
    time.sleep(0.1)
else:
    raise SystemExit("shard1 gradient log never grew")
os.kill(s1.pid, signal.SIGKILL)
s1.wait()
time.sleep(1.0)

# SIGTERM the survivors: the flight recorder's death hook dumps the
# rings then re-raises, so each leaves flightdump-<pid>.json behind
# (a survivor that already noticed the dead peer and exited through
# its normal path dumped on OpsPlane.close instead — either way the
# evidence is on disk; exit codes are NOT asserted here)
for p in (w, s0):
    if p.poll() is None:
        p.send_signal(signal.SIGTERM)
for p in (w, s0):
    try:
        p.wait(timeout=60)
    except subprocess.TimeoutExpired:
        p.kill()
        raise SystemExit("survivor ignored SIGTERM")

dumps = sorted(glob.glob(os.path.join(flight, "flightdump-*.json")))
pids = {int(os.path.basename(d).split("-")[1].split(".")[0])
        for d in dumps}
assert s0.pid in pids, f"shard0 left no dump: {dumps}"
assert w.pid in pids, f"worker left no dump: {dumps}"
assert s1.pid not in pids, "SIGKILLed shard must not have dumped"

pm = subprocess.run(
    [sys.executable, "-m", "kafka_ps_tpu.telemetry", "postmortem",
     flight], env=penv, cwd=proot, capture_output=True, text=True,
    timeout=120)
assert pm.returncode == 0, f"postmortem rc={pm.returncode}\n{pm.stderr}"
assert "dead shard 1" in pm.stdout, pm.stdout
assert "last ack from shard 1" in pm.stdout, pm.stdout
print(f"POSTMORTEM_OK dumps={len(dumps)} dead_shard=1 "
      f"survivors={sorted(pids)}")
EOF
    exit $?
fi

if [[ "${1:-}" == "--drift" ]]; then
    timeout -k 10 540 env JAX_PLATFORMS=cpu python - <<'EOF'
import glob
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

import numpy as np

# a socket-bridged pair (server process + worker process hosting 2
# logical workers) trains on a stream that TURNS: the first half of
# train.csv is clean and learnable, the second half label-flipped AND
# feature-shifted.  The held-out test set stays clean, so streaming
# eval loss rises once the poisoned rows displace the clean ones in
# the worker buffers — exactly the regime the drift plane exists for.
root = tempfile.mkdtemp(prefix="kps-drift-")
flight = os.path.join(root, "flight")
repo = os.getcwd()
rng = np.random.default_rng(0)
N_CLEAN, N_DRIFT, N_TEST = 600, 600, 56
xc = rng.normal(size=(N_CLEAN + N_TEST, 8)).astype(np.float32)
yc = (xc[:, 0] > 0).astype(np.int32) + 1
xd = (rng.normal(size=(N_DRIFT, 8)) + 2.0).astype(np.float32)
yd = (3 - ((xd[:, 0] - 2.0 > 0).astype(np.int32) + 1)).astype(np.int32)

def write_csv(path, parts):
    with open(path, "w") as fh:
        fh.write(",".join(f"f{i}" for i in range(8)) + ",Score\n")
        for xx, yy in parts:
            for r, lab in zip(xx, yy):
                fh.write(",".join(f"{v:.6f}" for v in r) + f",{lab}\n")

train = os.path.join(root, "train.csv")            # clean, then poisoned
clean_train = os.path.join(root, "train-clean.csv")
test = os.path.join(root, "test.csv")
write_csv(train, [(xc[:N_CLEAN], yc[:N_CLEAN]), (xd, yd)])
write_csv(clean_train, [(xc[:N_CLEAN], yc[:N_CLEAN])])
write_csv(test, [(xc[N_CLEAN:], yc[N_CLEAN:])])

def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port

p0, hp = free_port(), free_port()
env = dict(os.environ, JAX_PLATFORMS="cpu",
           PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
# the fleet is torn down once the verdict lands; MAX_IT only has to
# outlast the ~2.5 s stream plus the detector's baseline
MAX_IT = 100000
common = ["--num_workers", "2", "--num_features", "8",
          "--num_classes", "2", "--max_iterations", str(MAX_IT),
          "--eval_every", "2", "--model-health", "--drift-detector",
          "ph", "--flight-dir", flight]

server = subprocess.Popen(
    [sys.executable, "-m", "kafka_ps_tpu.cli.server_runner",
     "--listen", str(p0), "-training", train, "-test", test,
     "-p", "2", "-c", "0", "-l", "--health-port", str(hp), *common],
    env=env, cwd=root, stderr=subprocess.PIPE,
    stdout=subprocess.DEVNULL, text=True)
worker = subprocess.Popen(
    [sys.executable, "-m", "kafka_ps_tpu.cli.worker_runner",
     "--connect", f"127.0.0.1:{p0}", "--worker_ids", "0,1",
     "-test", test, "-min", "8", "-max", "64", *common],
    env=env, cwd=root, stderr=subprocess.PIPE,
    stdout=subprocess.DEVNULL, text=True)

def die(msg):
    for name, p in (("server", server), ("worker", worker)):
        if p.poll() is None:
            p.kill()
        print(f"== {name} rc={p.poll()}\n{p.stderr.read()[-4000:]}",
              file=sys.stderr)
    raise SystemExit(msg)

# watch the verdict live over /modelz until the server's plane latches
state, doc = None, {}
deadline = time.monotonic() + 240
while time.monotonic() < deadline:
    if server.poll() is not None or worker.poll() is not None:
        die("fleet died before the drift verdict")
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{hp}/modelz", timeout=2) as r:
            doc = json.loads(r.read())
        state = doc["drift"]["state"]
        if state == "DRIFT":
            break
    except (OSError, ValueError, KeyError):
        pass
    time.sleep(0.25)
else:
    die(f"drift never latched; last /modelz state={state}")
assert doc["drift"]["trips"] >= 1, doc
assert doc["updates"] > 0 and doc["workers"], doc

# the armed drift watchdog (latched DRIFT = continuous demand) must
# ship a flight dump carrying the drift.trip event within seconds
trip_dump = None
deadline = time.monotonic() + 60
while time.monotonic() < deadline and trip_dump is None:
    for path in sorted(glob.glob(
            os.path.join(flight, "flightdump-*.json"))):
        try:
            with open(path) as fh:
                d = json.load(fh)
        except (OSError, ValueError):
            continue
        if any(e.get("kind") == "drift.trip"
               for e in d.get("events") or []):
            trip_dump = path
    time.sleep(0.5)
if trip_dump is None:
    die("no flight dump carried the drift.trip event")

for p in (worker, server):
    if p.poll() is None:
        p.send_signal(signal.SIGTERM)
for p in (worker, server):
    try:
        p.wait(timeout=60)
    except subprocess.TimeoutExpired:
        p.kill()
        raise SystemExit("fleet ignored SIGTERM")

# the wall-clock-stamped drift CSV recorded the trip edge
with open(os.path.join(root, "logs-drift.csv")) as fh:
    rows = [ln.split(";") for ln in fh.read().splitlines()[1:] if ln]
trip_rows = [r for r in rows if r[1] == "trip"]
assert trip_rows, f"logs-drift.csv recorded no trip: {rows}"

# control: the same flags over a clean stream must end with ZERO trips
ctl = os.path.join(root, "control")
os.makedirs(ctl, exist_ok=True)
proc = subprocess.run(
    [sys.executable, "-m", "kafka_ps_tpu.cli.run",
     "-training", clean_train, "-test", test, "-min", "8", "-max", "64",
     "-p", "1", "-c", "0", "--mode", "serial", "-l",
     "--num_workers", "2", "--num_features", "8", "--num_classes", "2",
     "--eval_every", "2", "--max_iterations", "400",
     "--model-health", "--drift-detector", "ph"],
    env=env, cwd=ctl, capture_output=True, text=True, timeout=240)
assert proc.returncode == 0, \
    f"control rc={proc.returncode}\n{proc.stderr[-4000:]}"
with open(os.path.join(ctl, "logs-drift.csv")) as fh:
    crows = [ln.split(";") for ln in fh.read().splitlines()[1:] if ln]
ctrips = [r for r in crows if r[1] == "trip"]
assert not ctrips, f"control arm false-tripped: {ctrips}"

print(f"DRIFT_SMOKE_OK state=DRIFT trips={doc['drift']['trips']} "
      f"detector={doc['drift']['detector']} dump={os.path.basename(trip_dump)} "
      f"csv_trips={len(trip_rows)} control_trips=0 "
      f"control_events={len(crows)}")
EOF
    exit $?
fi

if [[ "${1:-}" == "--bench-gate" ]]; then
    timeout -k 10 120 env JAX_PLATFORMS=cpu python - <<'EOF'
import json
import os
import subprocess
import sys
import tempfile

repo = os.getcwd()

def gate(*args):
    return subprocess.run(
        [sys.executable, "scripts/bench_gate.py", *args],
        cwd=repo, capture_output=True, text=True, timeout=90)

# the committed results must pass against the committed baselines
ok = gate()
assert ok.returncode == 0, (
    f"gate failed on committed results rc={ok.returncode}\n"
    f"{ok.stdout}{ok.stderr}")

# a synthetic 20% worker-throughput regression (same device class:
# the baseline is the committed file itself) must fail, naming the key
with open(os.path.join(repo, "bench_out.json")) as fh:
    doc = json.load(fh)
doc["value"] = round(doc["value"] * 0.8, 1)
deg = os.path.join(tempfile.mkdtemp(prefix="kps-gate-"), "degraded.json")
with open(deg, "w") as fh:
    json.dump(doc, fh)
bad = gate("--fresh", deg, "--baseline", "bench_out.json")
assert bad.returncode == 1, (
    f"gate missed a 20% regression rc={bad.returncode}\n{bad.stdout}")
assert "FAIL worker_updates_per_sec" in bad.stdout, bad.stdout
print("BENCH_GATE_OK")
EOF
    exit $?
fi

if [[ "${1:-}" == "--compress" ]]; then
    timeout -k 10 300 env JAX_PLATFORMS=cpu python - <<'EOF'
import threading
import numpy as np
from kafka_ps_tpu.compress import wire as cwire
from kafka_ps_tpu.data.buffer import SlidingBuffer
from kafka_ps_tpu.data.synth import generate_hard
from kafka_ps_tpu.runtime import fabric as fabric_mod, net
from kafka_ps_tpu.runtime.server import ServerNode
from kafka_ps_tpu.runtime.worker import WorkerNode
from kafka_ps_tpu.utils.config import (BufferConfig, ModelConfig, PSConfig)
from kafka_ps_tpu.utils.csvlog import NullLogSink

model = ModelConfig(num_features=64, num_classes=2)
x, y = generate_hard(512 + 500, num_features=64, num_classes=2, seed=9)
test_x, test_y = x[-500:], y[-500:]

def run(compress, iters=24):
    ids = [0, 1]
    cfg = PSConfig(num_workers=2, consistency_model=0, model=model,
                   buffer=BufferConfig(min_size=32, max_size=256),
                   eval_every=10**9, use_gang=False, compress=compress)
    spec = cwire.parse_codec(compress)
    sbridge = net.ServerBridge(port=0, run_id=1, codec=spec)
    sfabric = sbridge.wrap(fabric_mod.Fabric())
    server = ServerNode(cfg, sfabric, test_x, test_y, NullLogSink())
    wbridge = net.WorkerBridge("127.0.0.1", sbridge.port, ids, codec=spec)
    wfabric = wbridge.make_fabric()
    buffers = {w: SlidingBuffer(64, cfg.buffer) for w in ids}
    nodes = {w: WorkerNode(w, cfg, wfabric, buffers[w], test_x, test_y,
                           NullLogSink()) for w in ids}
    if wbridge.negotiated.codec_id != net.CODEC_NONE:
        from kafka_ps_tpu import compress as comp
        codec = comp.get_codec(wbridge.negotiated, server.task.num_params)
        server.compressor = comp.WeightsCompressor(codec)
        for w in ids:
            nodes[w].compressor = comp.ErrorFeedback(codec)
    reader = threading.Thread(target=wbridge.run_reader, args=(buffers,),
                              daemon=True)
    reader.start()
    sbridge.wait_for_connected(ids, timeout=30)
    # batched ingest end-to-end: rows cross as ONE T_DATA_BATCH frame
    # and land via SlidingBuffer.add_many
    for w in ids:
        rows = [(dict(enumerate(x[i])), int(y[i]))
                for i in range(w, 512, 2)]
        assert sbridge.send_data_batch(w, rows), "batch send failed"
    deadline = 30.0
    import time
    t0 = time.monotonic()
    while any(buffers[w].count == 0 for w in ids):
        if time.monotonic() - t0 > deadline:
            raise AssertionError("batched rows never arrived")
        time.sleep(0.01)
    for w in ids:
        wbridge.mark_ready(w)
    sbridge.wait_for_workers(ids, timeout=30)
    stop = threading.Event()
    def worker_loop(node):
        try:
            while not stop.is_set():
                m = wfabric.poll_blocking(fabric_mod.WEIGHTS_TOPIC,
                                          node.worker_id, timeout=0.05)
                if m is not None:
                    node.on_weights(m)
        except (ConnectionError, OSError):
            pass
    ts = [threading.Thread(target=worker_loop, args=(nodes[w],),
                           daemon=True) for w in ids]
    for t in ts:
        t.start()
    server.start_training_loop()
    while server.iterations < iters:
        g = sfabric.poll_blocking(fabric_mod.GRADIENTS_TOPIC, 0,
                                  timeout=0.2)
        if g is not None:
            server.process(g)
    stop.set()
    sbridge.close()
    for t in ts:
        t.join(timeout=120)
    wbridge.close()
    reader.join(timeout=10)
    server.log.close()
    wire = (sbridge.wire_bytes.get(net.T_WEIGHTS, 0)
            + sbridge.wire_bytes.get(net.T_GRADIENTS, 0))
    return wbridge.negotiated.name, server.iterations, wire

neg8, it8, wire8 = run("int8")
assert neg8 == "int8", f"negotiation failed: {neg8}"
assert it8 >= 24, it8
neg0, it0, wire0 = run("none")
assert neg0 == "none", neg0
assert wire8 < wire0 / 2, (wire8, wire0)
print(f"COMPRESS_SMOKE_OK int8_wire={wire8} none_wire={wire0} "
      f"ratio={wire0 / wire8:.2f} iters={it8}")
EOF
    exit $?
fi

if [[ "${1:-}" == "--serve" ]]; then
    timeout -k 10 300 env JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np
from kafka_ps_tpu.runtime import net
from kafka_ps_tpu.runtime.app import StreamingPSApp
from kafka_ps_tpu.serving import StalenessError
from kafka_ps_tpu.utils.config import (BufferConfig, ModelConfig, PSConfig,
                                       ServingConfig, StreamConfig)

cfg = PSConfig(num_workers=4, consistency_model=0,
               model=ModelConfig(num_features=8, num_classes=2,
                                 local_learning_rate=0.5),
               buffer=BufferConfig(min_size=8, max_size=32),
               stream=StreamConfig(time_per_event_ms=1.0),
               serving=ServingConfig(enabled=True))
rng = np.random.default_rng(0)
x = rng.normal(size=(128, 8)).astype(np.float32)
y = (x[:, 0] > 0).astype(np.int32) + 1
app = StreamingPSApp(cfg, test_x=x, test_y=y)
engine = app.enable_serving()
for i in range(128):
    app.buffers[i % 4].add({j: float(x[i, j]) for j in range(8)},
                           int(y[i]))
app.run_serial(24)

# in-process prediction against the trained snapshot
pred = engine.predict(x[0])
assert pred.vector_clock > 0, pred
ref = app.server.task.predict_logits(app.server.theta, x[:1])
assert pred.label == int(np.argmax(np.asarray(ref)[0])), pred

# the staleness rejection path must fire for an unsatisfiable bound
try:
    engine.predict(x[0], min_clock=10**9)
except StalenessError:
    pass
else:
    raise AssertionError("unsatisfiable min_clock was served")
assert engine.rejections >= 1, engine.stats()

# the same predictions over the wire (cli/run.py --serve --serve_port)
bridge = net.ServerBridge(port=0, run_id=app.server.run_id)
bridge.attach_serving(engine)
client = net.PredictClient("127.0.0.1", bridge.port)
try:
    remote = client.predict(x[0])
    assert remote.label == pred.label, (remote, pred)
    try:
        client.predict(x[0], min_clock=10**9)
    except StalenessError:
        pass
    else:
        raise AssertionError("remote staleness bound was served")
finally:
    client.close()
    bridge.close()
    s = engine.stats()
    app.close_serving()
print(f"SERVE_SMOKE_OK requests={s['requests']} batches={s['batches']} "
      f"rejections={s['rejections']}")
EOF
    exit $?
fi

if [[ "${1:-}" == "--gang" ]]; then
    timeout -k 10 300 env JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np
from kafka_ps_tpu.runtime.app import StreamingPSApp
from kafka_ps_tpu.utils.config import (BufferConfig, ModelConfig, PSConfig,
                                       StreamConfig)
from kafka_ps_tpu.utils.trace import Tracer

def run(use_gang):
    cfg = PSConfig(num_workers=4, consistency_model=0,
                   model=ModelConfig(num_features=8, num_classes=2,
                                     local_learning_rate=0.5),
                   buffer=BufferConfig(min_size=8, max_size=32),
                   stream=StreamConfig(time_per_event_ms=1.0),
                   use_gang=use_gang)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 8)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32) + 1
    tracer = Tracer()
    app = StreamingPSApp(cfg, test_x=x, test_y=y, tracer=tracer)
    for i in range(128):
        app.buffers[i % 4].add({j: float(x[i, j]) for j in range(8)},
                               int(y[i]))
    app.run_serial(24)
    return (np.asarray(app.server.theta),
            tracer.counters().get("dispatch.device", 0))

theta_on, disp_on = run(True)
theta_off, disp_off = run(False)
assert theta_on.tobytes() == theta_off.tobytes(), \
    "gang smoke: final theta diverged from the per-message path"
assert disp_on < disp_off, \
    f"gang smoke: dispatch count did not drop ({disp_on} vs {disp_off})"
print(f"GANG_SMOKE_OK dispatches {disp_on} vs {disp_off} per-message")
EOF
    exit $?
fi

if [[ "${1:-}" == "--perf" ]]; then
    timeout -k 10 300 env JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np
from kafka_ps_tpu.runtime.app import StreamingPSApp
from kafka_ps_tpu.utils.config import (BufferConfig, ModelConfig, PSConfig,
                                       StreamConfig)

def run(consistency, slab_dtype, incremental):
    cfg = PSConfig(num_workers=4, consistency_model=consistency,
                   model=ModelConfig(num_features=8, num_classes=2,
                                     local_learning_rate=0.5),
                   buffer=BufferConfig(min_size=8, max_size=32),
                   stream=StreamConfig(time_per_event_ms=1.0),
                   slab_dtype=slab_dtype, slab_incremental=incremental)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 8)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32) + 1
    app = StreamingPSApp(cfg, test_x=x, test_y=y)
    for i in range(128):
        app.buffers[i % 4].add({j: float(x[i, j]) for j in range(8)},
                               int(y[i]))
    app.run_serial(24)
    assert app.server.iterations >= 24, app.server.iterations
    theta = np.asarray(app.server.theta)
    assert np.isfinite(theta).all(), f"non-finite theta ({slab_dtype})"
    return theta

for c in (0, 2, -1):
    # f32 contract: the incremental scatter path is BITWISE-invisible
    inc = run(c, "f32", incremental=True)
    full = run(c, "f32", incremental=False)
    assert inc.tobytes() == full.tobytes(), \
        f"perf smoke: incremental f32 slab diverged at consistency={c}"
    # bf16 slab storage trains end-to-end on every consistency model
    run(c, "bf16", incremental=True)
print("PERF_SMOKE_OK f32 bitwise + bf16 e2e at consistency 0/2/-1")
EOF
    exit $?
fi

if [[ "${1:-}" == "--tier" ]]; then
    timeout -k 10 540 env JAX_PLATFORMS=cpu python - <<'EOF'
import glob
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

# tiered-store smoke (docs/TIERING.md), all through the public CLI.
# Phase A: for each consistency model, an uncapped run vs a run whose
# hot tier holds ~1/13 of the parameter bytes (1 of 14 pages; warm 2
# more; the other 11 live as cold commit-log records) must produce
# bitwise-identical theta AND an identical eval CSV (timestamps
# stripped).  Phase B: SIGKILL a capped durable run mid-training,
# restart it, and replay its gradients partition through a fresh FULLY
# RESIDENT ServerNode — recovered-capped theta must equal the resident
# replay bit for bit.
root = tempfile.mkdtemp(prefix="kps-tier-")
repo = os.getcwd()
rng = np.random.default_rng(0)
x = rng.normal(size=(256, 8)).astype(np.float32)
y = (x[:, 0] > 0).astype(np.int32) + 1
train, test = os.path.join(root, "train.csv"), os.path.join(root, "test.csv")
for path, (xx, yy) in ((train, (x[:200], y[:200])),
                       (test, (x[200:], y[200:]))):
    with open(path, "w") as fh:
        fh.write(",".join(f"f{i}" for i in range(8)) + ",Score\n")
        for r, lab in zip(xx, yy):
            fh.write(",".join(f"{v:.6f}" for v in r) + f",{lab}\n")

env = dict(os.environ, JAX_PLATFORMS="cpu",
           PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
# logreg 8 features x 2 classes -> 27 params = 108 bytes.  Page 2
# params (8 bytes): hot 8 = 1 page (~1/13 of the model, under the 1/10
# acceptance cap), warm 16 = 2 pages, the remaining 11 pages cold.
TIER = ["--tier-hot-bytes", "8", "--tier-warm-bytes", "16",
        "--tier-page-params", "2"]

def run_arm(tag, consistency, max_it, tier, eval_every=1, extra=()):
    cwd = os.path.join(root, tag)
    os.makedirs(cwd, exist_ok=True)
    ckpt = os.path.join(cwd, "ckpt.npz")
    cmd = [sys.executable, "-m", "kafka_ps_tpu.cli.run",
           "-training", train, "-test", test, "--num_workers", "2",
           "--num_features", "8", "--num_classes", "2", "-min", "8",
           "-max", "32", "-p", "1", "-c", str(consistency),
           "--mode", "serial", "--eval_every", str(eval_every),
           "--max_iterations", str(max_it), "--logging",
           "--checkpoint", ckpt, "--checkpoint_every", "20"]
    if tier:
        cmd += [*TIER, "--durable-log", os.path.join(cwd, "log")]
    proc = subprocess.Popen([*cmd, *extra], env=env, cwd=cwd, text=True,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.PIPE)
    return proc, cwd, ckpt

def finish(proc, tag):
    rc = proc.wait(timeout=240)
    err = proc.stderr.read()
    assert rc == 0, f"{tag} rc={rc}\n{err[-4000:]}"

def csv_rows(cwd):
    # column 0 is the wall-clock timestamp — the only legal difference
    with open(os.path.join(cwd, "logs-server.csv")) as fh:
        return [";".join(ln.split(";")[1:]) for ln in fh.read().splitlines()]

# -- phase A: capped vs resident, all three consistency models ------------
MAX_IT = 80
for c in (0, 2, -1):
    pb, db, kb = run_arm(f"base-{c}", c, MAX_IT, tier=False)
    finish(pb, f"base-{c}")
    pt, dt, kt = run_arm(f"capped-{c}", c, MAX_IT, tier=True)
    finish(pt, f"capped-{c}")
    zb, zt = np.load(kb), np.load(kt)
    assert int(zt["iterations"]) >= MAX_IT <= int(zb["iterations"])
    tier_res = np.asarray(zt["tier_residency"])
    from kafka_ps_tpu.store import TIER_COLD
    assert (tier_res == TIER_COLD).sum() >= 8, \
        f"c={c}: capped arm was not actually tiered: {tier_res}"
    assert zt["theta"].tobytes() == zb["theta"].tobytes(), \
        f"c={c}: capped theta diverged from resident theta"
    assert csv_rows(dt) == csv_rows(db) != [], \
        f"c={c}: eval CSV diverged between capped and resident"

# -- phase B: SIGKILL the capped durable run, restart, resident replay ----
from kafka_ps_tpu.log import LogConfig
from kafka_ps_tpu.log.manager import LogManager
from kafka_ps_tpu.runtime import fabric as fabric_mod
from kafka_ps_tpu.runtime import serde
from kafka_ps_tpu.runtime.server import ServerNode
from kafka_ps_tpu.utils.config import (BufferConfig, ModelConfig, PSConfig,
                                       StreamConfig)

KILL_IT = 200
for c in (0, 2, -1):
    tag = f"crash-{c}"
    proc, cwd, ckpt = run_arm(tag, c, KILL_IT, tier=True,
                              eval_every=1000000)
    logdir = os.path.join(cwd, "log")
    grad_glob = os.path.join(logdir, "gradients", "*", "*.log")
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        segs = glob.glob(grad_glob)
        if (segs and sum(os.path.getsize(s) for s in segs) > 6000
                and os.path.exists(ckpt)):
            break
        if proc.poll() is not None:
            print(proc.stderr.read(), file=sys.stderr)
            raise SystemExit(f"{tag} exited before the kill point")
        time.sleep(0.05)
    else:
        raise SystemExit(f"{tag} gradient log never grew")
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()
    proc2, _, _ = run_arm(tag, c, KILL_IT, tier=True, eval_every=1000000)
    finish(proc2, f"{tag}-restarted")

    z = np.load(ckpt)
    from kafka_ps_tpu.store import TIER_COLD
    assert (np.asarray(z["tier_residency"]) == TIER_COLD).any(), \
        f"{tag}: final checkpoint recorded no cold pages"
    cold_segs = glob.glob(os.path.join(logdir, "param-cold", "*.log"))
    assert cold_segs and sum(os.path.getsize(s) for s in cold_segs) > 0, \
        f"{tag}: cold partition is empty — nothing was ever demoted"
    # resident replay: the gradients partition (offset 0 up to the
    # final checkpoint's committed offset) through a fresh UNTIERED
    # ServerNode — log order is processing order across both
    # incarnations and the tracker dedups redelivered slices, so a
    # bitwise match proves capped+crash+restart == fully resident
    end = json.loads(str(z["log_offsets"]))["gradients/0"]
    cfg = PSConfig(num_workers=2, consistency_model=c, task="logreg",
                   model=ModelConfig(num_features=8, num_classes=2),
                   buffer=BufferConfig(min_size=8, max_size=32),
                   stream=StreamConfig(time_per_event_ms=1),
                   use_gang=False)
    srv = ServerNode(cfg, fabric_mod.Fabric(), None, None, None)
    srv.start_training_loop()
    mgr = LogManager(logdir, LogConfig())
    n = 0
    for off, payload in mgr.get("gradients", 0).read_from(0):
        if off >= end:
            break
        srv.process(serde.from_bytes(payload))
        n += 1
    mgr.close()
    assert srv.iterations >= KILL_IT, (c, srv.iterations)
    replay = np.asarray(srv.theta, dtype=np.float32)
    assert replay.tobytes() == z["theta"].tobytes(), \
        f"{tag}: resident replay diverged from recovered capped theta"

print(f"TIER_SMOKE_OK models=0/2/-1 hot=8B/108B pages=1hot+2warm+11cold "
      f"phaseA_iters={MAX_IT} phaseB_iters={KILL_IT} "
      f"theta=bitwise csv=bitwise crash=recovered-bitwise")
EOF
    exit $?
fi

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
