#!/bin/bash
# Tier-1 verify: the exact command the driver runs (ROADMAP.md).
# Passes iff the suite exits 0 within the timeout; DOTS_PASSED echoes
# the progress-dot count so regressions against the recorded floor are
# visible at a glance.
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
