#!/bin/bash
# Tier-1 verify: the exact command the driver runs (ROADMAP.md).
# Passes iff the suite exits 0 within the timeout; DOTS_PASSED echoes
# the progress-dot count so regressions against the recorded floor are
# visible at a glance.
#
# `scripts/tier1.sh --gang` runs the gang-dispatch smoke leg instead: a
# tiny serial run with coalescing on vs off, asserting identical final
# theta (bitwise) and a strictly lower device-dispatch count
# (docs/GANG_DISPATCH.md).
#
# `scripts/tier1.sh --serve` runs the serving-plane smoke leg: train a
# tiny model with serving enabled, predict in-process AND over the
# socket (PredictClient), and assert the staleness rejection path fires
# (docs/SERVING.md).
set -o pipefail

if [[ "${1:-}" == "--serve" ]]; then
    timeout -k 10 300 env JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np
from kafka_ps_tpu.runtime import net
from kafka_ps_tpu.runtime.app import StreamingPSApp
from kafka_ps_tpu.serving import StalenessError
from kafka_ps_tpu.utils.config import (BufferConfig, ModelConfig, PSConfig,
                                       ServingConfig, StreamConfig)

cfg = PSConfig(num_workers=4, consistency_model=0,
               model=ModelConfig(num_features=8, num_classes=2,
                                 local_learning_rate=0.5),
               buffer=BufferConfig(min_size=8, max_size=32),
               stream=StreamConfig(time_per_event_ms=1.0),
               serving=ServingConfig(enabled=True))
rng = np.random.default_rng(0)
x = rng.normal(size=(128, 8)).astype(np.float32)
y = (x[:, 0] > 0).astype(np.int32) + 1
app = StreamingPSApp(cfg, test_x=x, test_y=y)
engine = app.enable_serving()
for i in range(128):
    app.buffers[i % 4].add({j: float(x[i, j]) for j in range(8)},
                           int(y[i]))
app.run_serial(24)

# in-process prediction against the trained snapshot
pred = engine.predict(x[0])
assert pred.vector_clock > 0, pred
ref = app.server.task.predict_logits(app.server.theta, x[:1])
assert pred.label == int(np.argmax(np.asarray(ref)[0])), pred

# the staleness rejection path must fire for an unsatisfiable bound
try:
    engine.predict(x[0], min_clock=10**9)
except StalenessError:
    pass
else:
    raise AssertionError("unsatisfiable min_clock was served")
assert engine.rejections >= 1, engine.stats()

# the same predictions over the wire (cli/run.py --serve --serve_port)
bridge = net.ServerBridge(port=0, run_id=app.server.run_id)
bridge.attach_serving(engine)
client = net.PredictClient("127.0.0.1", bridge.port)
try:
    remote = client.predict(x[0])
    assert remote.label == pred.label, (remote, pred)
    try:
        client.predict(x[0], min_clock=10**9)
    except StalenessError:
        pass
    else:
        raise AssertionError("remote staleness bound was served")
finally:
    client.close()
    bridge.close()
    s = engine.stats()
    app.close_serving()
print(f"SERVE_SMOKE_OK requests={s['requests']} batches={s['batches']} "
      f"rejections={s['rejections']}")
EOF
    exit $?
fi

if [[ "${1:-}" == "--gang" ]]; then
    timeout -k 10 300 env JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np
from kafka_ps_tpu.runtime.app import StreamingPSApp
from kafka_ps_tpu.utils.config import (BufferConfig, ModelConfig, PSConfig,
                                       StreamConfig)
from kafka_ps_tpu.utils.trace import Tracer

def run(use_gang):
    cfg = PSConfig(num_workers=4, consistency_model=0,
                   model=ModelConfig(num_features=8, num_classes=2,
                                     local_learning_rate=0.5),
                   buffer=BufferConfig(min_size=8, max_size=32),
                   stream=StreamConfig(time_per_event_ms=1.0),
                   use_gang=use_gang)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 8)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32) + 1
    tracer = Tracer()
    app = StreamingPSApp(cfg, test_x=x, test_y=y, tracer=tracer)
    for i in range(128):
        app.buffers[i % 4].add({j: float(x[i, j]) for j in range(8)},
                               int(y[i]))
    app.run_serial(24)
    return (np.asarray(app.server.theta),
            tracer.counters().get("dispatch.device", 0))

theta_on, disp_on = run(True)
theta_off, disp_off = run(False)
assert theta_on.tobytes() == theta_off.tobytes(), \
    "gang smoke: final theta diverged from the per-message path"
assert disp_on < disp_off, \
    f"gang smoke: dispatch count did not drop ({disp_on} vs {disp_off})"
print(f"GANG_SMOKE_OK dispatches {disp_on} vs {disp_off} per-message")
EOF
    exit $?
fi

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
