#!/usr/bin/env python
"""Bench regression gate — compare a fresh bench_out.json against the
best committed baseline per metric, with direction-aware tolerances.

    python scripts/bench_gate.py                       # repo defaults
    python scripts/bench_gate.py --fresh bench_out.json \
        --baseline 'BENCH_r*.json' --waivers scripts/bench_waivers.txt

Baselines may be any of three shapes: a harness capture record
({n, cmd, rc, tail, parsed} — `parsed` when present, else the last
JSON line of `tail`), a full bench payload (the bench_out.json shape),
or a bare summary line.  Shapes that yield no metrics are reported and
skipped, never fatal — history must not be able to wedge the gate.

Rules, per canonical metric:

  * higher-is-better throughput (worker updates/s, knee QPS, speedups)
    may not drop more than its relative tolerance (default 15%) below
    the BEST baseline value;
  * lower-is-better latency may not rise more than its tolerance above
    the best (lowest) baseline;
  * absolute caps (telemetry/flight/profiling overhead %) are checked
    against the FRESH file alone — they re-state the asserts bench.py
    already enforces at run time, so a hand-edited bench_out.json
    cannot sneak past;
  * bitwise keys must be exactly true in the fresh file;
  * performance comparisons only count between runs of the same device
    class (a CPU dev box must not "regress" a TPU baseline) — a class
    mismatch is a named SKIP, not a pass.

Waivers (scripts/bench_waivers.txt, one per line):

    <metric-key>: <reason why this regression is accepted>

A waived metric still prints its comparison but cannot fail the gate.
Blank lines and `#` comments are ignored.  Exit code: 0 when no
unwaived metric fails, 1 otherwise, 2 on an unreadable fresh file.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def _get(doc, *path):
    """Nested lookup; None on any miss."""
    cur = doc
    for key in path:
        if not isinstance(cur, dict) or key not in cur:
            return None
        cur = cur[key]
    return cur


def _scalar(v):
    """Collapse rate_stats dicts to their median; pass scalars through."""
    if isinstance(v, dict):
        v = v.get("median")
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return v
    return float(v)


# Canonical metrics.  `paths` are tried in order against both the old
# (pre-PR-14 harness `parsed`) and current bench_out.json layouts; the
# summary-line layout is handled by prefixing ("summary",).
# direction: "higher" | "lower";  rel = relative tolerance vs the best
# baseline;  cap = absolute ceiling checked on the fresh file alone;
# floor = absolute lower bound checked on the fresh file alone (the
# mirror of cap, for throughput/ratio claims with a hard acceptance
# bar);  must_be_true = bitwise/bool contract on the fresh file alone.
METRICS = {
    "worker_updates_per_sec": {
        "paths": [("value",)], "direction": "higher", "rel": 0.15},
    "server_rounds_per_sec": {
        "paths": [("detail", "server_rounds_per_sec"),
                  ("server_rounds_per_sec",)],
        "direction": "higher", "rel": 0.15},
    "final_f1": {
        "paths": [("detail", "final_f1"), ("final_f1",)],
        "direction": "higher", "abs": 0.02, "device_free": True,
        "same_dataset": True},
    "fused_mlp_rounds_per_sec": {
        "paths": [("detail", "paths", "fused_mlp_rounds_per_sec")],
        "direction": "higher", "rel": 0.15},
    "per_node_eval1": {
        "paths": [("detail", "paths",
                   "per_node_iters_per_sec_eval_every_1"),
                  ("per_node_eval1",)],
        "direction": "higher", "rel": 0.15},
    "per_node_eval10": {
        "paths": [("detail", "paths",
                   "per_node_iters_per_sec_eval_every_10"),
                  ("per_node_eval10",)],
        "direction": "higher", "rel": 0.15},
    "pallas_speedup": {
        "paths": [("detail", "paths", "pallas_ab", "pallas_speedup"),
                  ("pallas_speedup",)],
        "direction": "higher", "rel": 0.15},
    "serving_p50_ms": {
        "paths": [("detail", "paths", "serving_ab", "batched", "p50_ms"),
                  ("serving_p50_ms",)],
        "direction": "lower", "rel": 0.25},
    "serving_knee_qps": {
        "paths": [("detail", "paths", "serving_load", "single",
                   "knee_qps"), ("serving_knee_qps",)],
        "direction": "higher", "rel": 0.15},
    "tier_hot_hit_rate": {
        "paths": [("detail", "paths", "tiering_ab", "skew_drive",
                   "hit_rate", "hot"), ("tier_hot_hit_rate",)],
        "direction": "higher", "abs": 0.05, "device_free": True},
    # aggregation tier (docs/AGGREGATION.md): the gate must keep seeing
    # host-count messages per clock (cap restates bench.py's assert:
    # 4 hosts + slack so a partial-flush round cannot flake the gate),
    # and the summed-mode scaling win past the direct plateau may not
    # erode
    "agg_msgs_per_clock": {
        "paths": [("detail", "paths", "aggregation_ab",
                   "msgs_per_clock_max"), ("agg_msgs_per_clock",)],
        "direction": "lower", "cap": 4.5},
    "agg_updates_per_sec_scaling": {
        "paths": [("detail", "paths", "aggregation_ab",
                   "updates_per_sec_scaling"),
                  ("agg_updates_per_sec_scaling",)],
        "direction": "higher", "rel": 0.25},
    # wire engine (docs/WIRE.md): the coalesced path must stay bitwise,
    # actually batch frames into scatter-gather syscalls (>= 2.0 median
    # frames/sendmsg at the 64-worker/4-relay fan-out), and never lose
    # throughput to the un-coalesced path
    "wire_bitwise": {
        "paths": [("detail", "paths", "wire_ab", "all_bitwise"),
                  ("wire_bitwise",)],
        "must_be_true": True},
    "wire_fps_p50": {
        "paths": [("detail", "paths", "wire_ab",
                   "frames_per_syscall_p50"), ("wire_fps_p50",)],
        "direction": "higher", "floor": 2.0, "rel": 0.5},
    "wire_updates_ratio": {
        "paths": [("detail", "paths", "wire_ab", "updates_ratio_best"),
                  ("wire_updates_ratio",)],
        "direction": "higher", "floor": 1.0, "rel": 0.25},
    # async eval engine (docs/EVALUATION.md): the deferred plane must
    # stay bitwise (CSV rows AND theta, durable-log restart included)
    # and may never LOSE apply throughput to the fused path at
    # eval_every=1 (floor 1.0; the relative band tracks the committed
    # baselines' speedup once one exists for this device class)
    "eval_bitwise": {
        "paths": [("detail", "paths", "eval_ab", "all_bitwise"),
                  ("eval_bitwise",)],
        "must_be_true": True},
    "eval_async_speedup": {
        "paths": [("detail", "paths", "eval_ab", "async_speedup"),
                  ("eval_async_speedup",)],
        "direction": "higher", "floor": 1.0, "rel": 0.25},
    # absolute caps — the observability planes' cost contracts
    "telemetry_overhead_pct": {
        "paths": [("detail", "paths", "telemetry_overhead",
                   "overhead_pct"), ("telemetry_overhead_pct",)],
        "direction": "lower", "cap": 5.0},
    "flight_overhead_pct": {
        "paths": [("detail", "paths", "flight_overhead",
                   "max_overhead_pct"), ("flight_overhead_pct",)],
        "direction": "lower", "cap": 2.0},
    "profiling_overhead_pct": {
        "paths": [("detail", "paths", "profiling_overhead",
                   "max_overhead_pct"), ("profiling_overhead_pct",)],
        "direction": "lower", "cap": 2.0},
    "modelhealth_overhead_pct": {
        "paths": [("detail", "paths", "modelhealth_overhead",
                   "max_overhead_pct"), ("modelhealth_overhead_pct",)],
        "direction": "lower", "cap": 2.0},
    # drift-detection quality: delay may not balloon past baselines
    # (detectors count in eval rows — device-free), false trips on the
    # clean control arm are capped at zero
    "drift_delay_evals": {
        "paths": [("detail", "paths", "drift_detection", "delay_evals"),
                  ("drift_delay_evals",)],
        "direction": "lower", "rel": 0.5, "device_free": True},
    "drift_false_trips": {
        "paths": [("detail", "paths", "drift_detection", "false_trips"),
                  ("drift_false_trips",)],
        "direction": "lower", "cap": 1.0},
    "drift_detected": {
        "paths": [("detail", "paths", "drift_detection", "detected"),
                  ("drift_detected",)],
        "must_be_true": True},
    # bitwise contracts — never degradable, never device-scoped
    "telemetry_bitwise": {
        "paths": [("detail", "paths", "telemetry_overhead",
                   "theta_bitwise_identical"), ("telemetry_bitwise",)],
        "must_be_true": True},
    "flight_bitwise": {
        "paths": [("flight_bitwise",)], "must_be_true": True,
        "all_of": ("detail", "paths", "flight_overhead")},
    "profiling_bitwise": {
        "paths": [("profiling_bitwise",)], "must_be_true": True,
        "all_of": ("detail", "paths", "profiling_overhead")},
    "tier_bitwise": {
        "paths": [("detail", "paths", "tiering_ab", "all_bitwise"),
                  ("tier_bitwise",)], "must_be_true": True},
    "agg_n1_bitwise": {
        "paths": [("detail", "paths", "aggregation_ab",
                   "all_n1_bitwise"), ("agg_n1_bitwise",)],
        "must_be_true": True},
}

_MODELS = ("sequential", "bounded", "eventual")


def extract(doc: dict, key: str) -> object:
    spec = METRICS[key]
    # per-model bitwise blocks fold to a single all() verdict
    block_path = spec.get("all_of")
    if block_path:
        block = _get(doc, *block_path)
        if isinstance(block, dict):
            flags = [_get(block, m, "theta_bitwise_identical")
                     for m in _MODELS]
            if all(isinstance(f, bool) for f in flags):
                return all(flags)
    for path in spec["paths"]:
        v = _scalar(_get(doc, *path))
        if v is None:
            v = _scalar(_get(doc, "summary", *path))
        if v is not None:
            return v
    return None


def device_class(doc: dict) -> str | None:
    dev = _get(doc, "detail", "device")
    if not isinstance(dev, str):
        return None
    return "tpu" if "tpu" in dev.lower() else "cpu"


def load_baseline(path: str) -> tuple[dict | None, str]:
    """(document, note).  Harness records unwrap to `parsed`, falling
    back to the last parseable JSON line of `tail`."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return None, f"unreadable ({e.__class__.__name__})"
    if not isinstance(doc, dict):
        return None, "not a JSON object"
    if "tail" in doc and "parsed" in doc:            # harness record
        if isinstance(doc.get("parsed"), dict):
            return doc["parsed"], "harness parsed"
        for line in reversed(doc.get("tail", "").splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line), "harness tail"
                except ValueError:
                    continue
        return None, "harness record with no parseable summary"
    return doc, "payload"


def load_waivers(path: str) -> dict[str, str]:
    out: dict[str, str] = {}
    if not path or not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or ":" not in line:
                continue
            key, reason = line.split(":", 1)
            out[key.strip()] = reason.strip()
    return out


def run_gate(fresh_path: str, baseline_paths: list[str],
             waiver_path: str, out=sys.stdout) -> int:
    try:
        with open(fresh_path) as f:
            fresh = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench-gate: cannot read fresh results {fresh_path}: "
              f"{e}", file=out)
        return 2

    waivers = load_waivers(waiver_path)
    baselines: list[tuple[str, dict]] = []
    for path in baseline_paths:
        doc, note = load_baseline(path)
        if doc is None:
            print(f"bench-gate: SKIP baseline {path} — {note}", file=out)
        else:
            baselines.append((path, doc))

    fresh_class = device_class(fresh)
    failures: list[str] = []
    for key, spec in METRICS.items():
        val = extract(fresh, key)
        if val is None:
            print(f"bench-gate: SKIP {key} — absent from fresh "
                  "results", file=out)
            continue

        def fail(msg):
            if key in waivers:
                print(f"bench-gate: WAIVED {key} — {msg} "
                      f"(waiver: {waivers[key]})", file=out)
            else:
                failures.append(key)
                print(f"bench-gate: FAIL {key} — {msg}", file=out)

        if spec.get("must_be_true"):
            if val is True:
                print(f"bench-gate: ok {key}=true", file=out)
            else:
                fail(f"expected true, got {val!r}")
            continue
        cap = spec.get("cap")
        if cap is not None and isinstance(val, float) and val >= cap:
            fail(f"{val} >= cap {cap}")
            continue
        floor = spec.get("floor")
        if floor is not None and isinstance(val, float) and val < floor:
            fail(f"{val} < floor {floor}")
            continue

        # best comparable baseline value for this key
        cands = []
        for path, doc in baselines:
            bval = extract(doc, key)
            if not isinstance(bval, float):
                continue
            if not spec.get("device_free"):
                bclass = device_class(doc)
                if bclass is None or fresh_class is None \
                        or bclass != fresh_class:
                    print(f"bench-gate: SKIP {key} vs {path} — device "
                          f"class {bclass or '?'} != "
                          f"{fresh_class or '?'}", file=out)
                    continue
            if spec.get("same_dataset"):
                # quality metrics only compare like against like: a
                # dataset change moves the attainable ceiling
                bds = _get(doc, "detail", "dataset")
                fds = _get(fresh, "detail", "dataset")
                if bds != fds:
                    print(f"bench-gate: SKIP {key} vs {path} — "
                          f"dataset {bds!r} != {fds!r}", file=out)
                    continue
            cands.append(bval)
        if not cands or not isinstance(val, float):
            if cap is not None:
                print(f"bench-gate: ok {key}={val} (cap {cap}, no "
                      "comparable baseline)", file=out)
            elif floor is not None:
                print(f"bench-gate: ok {key}={val} (floor {floor}, no "
                      "comparable baseline)", file=out)
            else:
                print(f"bench-gate: SKIP {key} — no comparable "
                      "baseline", file=out)
            continue

        higher = spec.get("direction", "higher") == "higher"
        best = max(cands) if higher else min(cands)
        tol_abs = spec.get("abs")
        if tol_abs is None:
            tol_abs = abs(best) * spec.get("rel", 0.15)
        limit = best - tol_abs if higher else best + tol_abs
        bad = val < limit if higher else val > limit
        if bad:
            fail(f"fresh={val} vs best baseline={best} "
                 f"(limit {round(limit, 4)})")
        else:
            print(f"bench-gate: ok {key}={val} (best baseline {best}, "
                  f"limit {round(limit, 4)})", file=out)

    if failures:
        print(f"bench-gate: {len(failures)} metric(s) regressed: "
              + ", ".join(failures), file=out)
        return 1
    print("bench-gate: pass", file=out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="compare fresh bench results against committed "
                    "baselines")
    ap.add_argument("--fresh", default="bench_out.json")
    ap.add_argument("--baseline", action="append", default=None,
                    help="baseline file or glob (repeatable; default "
                         "BENCH_r*.json + last committed bench_out)")
    ap.add_argument("--waivers", default="scripts/bench_waivers.txt")
    args = ap.parse_args(argv)
    pats = args.baseline if args.baseline else ["BENCH_r*.json"]
    paths: list[str] = []
    for pat in pats:
        hits = sorted(glob.glob(pat))
        paths.extend(hits if hits else [pat])
    return run_gate(args.fresh, paths, args.waivers)


if __name__ == "__main__":
    sys.exit(main())
